"""Serving cost accounting: an analytic per-step work model, a
goodput-vs-waste ledger with per-cause attribution, and per-tenant
block-step billing — the layer that makes the engine ACCOUNTABLE, not
just observable.

PRs 8-9 record what happened and when (telemetry) and judge whether
the engine is healthy (monitor); nothing says how many FLOPs a step
actually did, what fraction of that work reached a finished stream,
or what a tenant's pool occupancy truly cost. This module closes that
gap with two objects:

* ``WorkModel`` — the ANALYTIC cost of one token-row through a
  FusedMultiTransformer-protocol core, as a pure function of the
  model dims and the row's absolute position (its causal KV extent):

      flops(row @ p) = L * (8 d^2 + 4 d f)            # qkv/out/ffn
                     + L * 4 d (p + 1)                # QK^T + AV
      kv bytes(row @ p) = (p + 2) * kv_token_bytes    # read + write

  Spans [a, b) close over the position sum in closed form, so the
  ledger can price a prefill chunk, a decode row, or a rolled-back
  verify tail EXACTLY — and pricing a rollback re-derives the same
  integers the original event added, which is what makes the
  conservation check exact instead of approximate. The same numbers,
  paired with the collector's ``span.model`` durations, yield the
  model-phase MFU/MBU the ragged kernel's tile sizing was missing
  (tools/tile_report.py reads durations; tools/cost_report.py now
  reads work/duration).

* ``CostLedger`` — the opt-in goodput ledger (``ledger=`` on
  ``PagedServingEngine`` / ``SpeculativeEngine``, the FaultInjector /
  collector wiring pattern). The unit of account is the TOKEN-ROW:
  one row of one model forward (target or draft pool — each priced by
  its own WorkModel). Every accounted row is, at any instant, in
  exactly one of three states:

      PENDING   computed, verdict unknown (the request is live)
      GOODPUT   part of a FINISHED request's delivered stream
      WASTE     attributed to exactly one cause:
                  spec_rejected  drafted + verified rows beyond the
                                 accepted prefix (rolled back)
                  replay         re-prefill recomputation of rows
                                 already computed once (preemption /
                                 un-admit retry; draft rebuilds), NET
                                 of prefix-cache warm-resume savings
                                 (skipped rows are never recomputed,
                                 so they never enter the ledger —
                                 ``replay_saved_tokens`` reports them)
                  draft_oom      partial draft rolls torn down by a
                                 draft-pool BlockOOM
                  shed / numeric / deadline
                                 a failed request's ENTIRE pending
                                 work, retroactively (FAILED_OOM /
                                 FAILED_NUMERIC / FAILED_DEADLINE)

  CONSERVATION (the load-bearing property, tested exactly):

      total_rows == goodput_rows + sum(waste_rows) + pending_rows

  holds after EVERY event, with the same identity on FLOPs. The
  replay-vs-fresh split runs off a per-request high-water mark of
  computed stream positions, so a warm-resumed re-prefill charges
  only what it actually recomputes.

  Per-tenant attribution rides the same events (rows/FLOPs/waste per
  tenant) plus BLOCK-STEP billing: on every completed engine step the
  ledger integrates PR 7's per-tenant block charge gauge, so a
  tenant's bill is sum(blocks held x steps) — deterministic and
  replayable where wall-clock block-seconds are not;
  ``tools/cost_report.py`` converts to block-seconds offline using
  measured step durations when a trace is available.

  CONTRACTS (tests/test_accounting.py — the collector/monitor three):

    - ZERO OVERHEAD OFF: every engine hook sits behind
      ``if self.ledger is not None``; the ledger itself NEVER reads a
      clock (this module does not import ``time`` — every duration it
      ever sees is a collector-measured span handed to ``on_step``).
    - PASSIVE: streams and outcomes are bit-identical with the ledger
      on vs off across plain / prefix / speculative / recoverable
      serving, fault storms included; engine snapshots carry no
      ledger state.
    - REPLAY-FROZEN: during journal replay, records the dead
      incarnation observed live are frozen (``set_replay``, the
      collector's exact pattern) and the step integral is gated on
      step monotonicity — a ledger riding through a crash counts
      nothing twice, and a FRESH ledger handed to ``recover()``
      rebuilds the post-snapshot state by watching the replay.

  What is NOT counted, by design: masked/trash rows of the fused call
  (the ledger prices ATTRIBUTED work — the serving-goodput view, not
  the launch-occupancy view), the token-ID readout matmul, and
  replay-skipped rows (never computed). Known approximations, stated
  not hidden: weight bytes are charged once per model-carrying step
  (legacy multi-call steps under-count HBM traffic), rows computed by
  SYNCHRONOUS admission prefill (which runs at submit time, outside
  any step bracket) fold into the NEXT completed step's work-log
  entry, and a round lost to a crash before it was journaled is
  genuinely computed twice after recovery and counts twice.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

__all__ = ["WorkModel", "CostLedger", "WASTE_CAUSES"]


# the exhaustive waste taxonomy: every wasted row names exactly one
WASTE_CAUSES = ("spec_rejected", "replay", "draft_oom", "shed",
                "numeric", "deadline", "bestof_pruned")

# RequestOutcome status -> retroactive waste cause for a failed
# request's pending work (FINISHED resolves to goodput; a rejected
# request never did any work). CANCELLED is a deliberate early stop
# (best-of-n loser pruning / beam cuts): the pruned branch's pending
# rows were real work that will never reach a delivered stream, so
# they resolve to their own cause instead of inflating "shed".
_FAIL_CAUSE = {"failed_oom": "shed", "failed_numeric": "numeric",
               "failed_deadline": "deadline",
               "cancelled": "bestof_pruned"}


class WorkModel:
    """Analytic FLOPs / HBM-bytes of one FusedMultiTransformer-protocol
    core (see the module docstring for the formulas). All outputs are
    exact python ints — additive over rows and therefore exactly
    subtractable on rollback."""

    __slots__ = ("num_layers", "d_model", "ffn_dim", "itemsize",
                 "weight_itemsize", "kv_token_bytes", "weight_bytes",
                 "_row_linear", "num_experts", "top_k")

    def __init__(self, num_layers: int, d_model: int, ffn_dim: int,
                 kv_token_bytes: Optional[int] = None,
                 itemsize: int = 4,
                 weight_itemsize: Optional[int] = None,
                 num_experts: int = 0, top_k: int = 0):
        self.num_layers = int(num_layers)
        self.d_model = int(d_model)
        self.ffn_dim = int(ffn_dim)
        self.itemsize = int(itemsize)
        # MoE routing spec (moe_serving.MoeServingCore.moe_spec):
        # num_experts=0 means dense. A routed row PRICES k experts'
        # FFN — what it computes — while weight RESIDENCY counts all E
        # expert tables: the gap between the two is exactly the
        # serving argument for MoE (capacity decoupled from per-token
        # FLOPs), and pricing E here would erase it.
        self.num_experts = int(num_experts)
        self.top_k = int(top_k)
        if self.num_experts and not (0 < self.top_k <= self.num_experts):
            raise ValueError(f"top_k={top_k} must be in "
                             f"[1, {num_experts}]")
        # int8-weight serving streams 1-byte weights (w8a16): a
        # distinct weight itemsize keeps MBU honest there — pricing an
        # int8 pass at 4-byte traffic would overstate MBU ~4x, the
        # same lie a stale bf16 KV byte model tells on int8 pools
        self.weight_itemsize = (self.itemsize if weight_itemsize is None
                                else int(weight_itemsize))
        L, d, f = self.num_layers, self.d_model, self.ffn_dim
        # K + V, all heads (num_heads * head_dim == d), every layer.
        # Callers with a real pool pass kv_token_bytes from
        # PagedKVCache.kv_bytes_per_token() — which on int8 pools
        # counts 1-byte payload + per-row scale bytes, so the analytic
        # KV traffic follows the pool's actual density
        self.kv_token_bytes = (int(kv_token_bytes)
                               if kv_token_bytes is not None
                               else 2 * d * self.itemsize * L)
        if self.num_experts:
            E, k = self.num_experts, self.top_k
            # qkv [d,3d]+[3d], out [d,d]+[d], gate [d,E]+[E], E expert
            # FFN pairs ([d,f]+[f], [f,d]+[d]), two LayerNorms [2d]
            # each — RESIDENCY streams every expert table (they all
            # must be HBM-resident for the router to pick any)
            self.weight_bytes = L * self.weight_itemsize * (
                4 * d * d + E * (2 * d * f + f + d) + d * E + E
                + 8 * d)
            # a routed row computes the gate projection plus its k
            # ROUTED experts' FFNs — not E (routed-FLOPs; overflow
            # bypass rows still get priced at k, the capacity they
            # were admitted to spend)
            self._row_linear = L * (8 * d * d + 2 * d * E
                                    + k * 4 * d * f)
        else:
            # qkv [d,3d]+[3d], out [d,d]+[d], ffn1 [d,f]+[f], ffn2
            # [f,d]+[d], two LayerNorms [2d] each — the bytes one
            # model call streams through the weights once
            self.weight_bytes = L * self.weight_itemsize * (
                4 * d * d + 2 * d * f + 9 * d + f)
            # position-independent FLOPs of one row: the four
            # projections (2*m*n per matmul row)
            self._row_linear = L * (8 * d * d + 4 * d * f)

    @classmethod
    def for_model(cls, model, itemsize: int = 4,
                  kv_token_bytes: Optional[int] = None,
                  weight_itemsize: Optional[int] = None) -> "WorkModel":
        """Build from a FusedMultiTransformer-protocol core (or a
        TokenServingModel wrapping one). MoE cores advertise their
        routing spec via ``moe_spec`` (they have no dense ffn1)."""
        core = getattr(model, "core", model)
        spec = getattr(core, "moe_spec", None)
        if spec is not None:
            return cls(core.num_layers, core.embed_dim,
                       int(spec["ffn_dim"]),
                       kv_token_bytes=kv_token_bytes, itemsize=itemsize,
                       weight_itemsize=weight_itemsize,
                       num_experts=int(spec["num_experts"]),
                       top_k=int(spec["top_k"]))
        return cls(core.num_layers, core.embed_dim,
                   int(core.layers[0].ffn1.weight.shape[1]),
                   kv_token_bytes=kv_token_bytes, itemsize=itemsize,
                   weight_itemsize=weight_itemsize)

    # -- FLOPs --------------------------------------------------------
    def row_flops(self, pos: int) -> int:
        """One token-row at absolute position ``pos`` (attends pos+1
        keys, itself included)."""
        return self._row_linear + self.num_layers * 4 * self.d_model \
            * (int(pos) + 1)

    def span_flops(self, start: int, end: int) -> int:
        """Rows at positions [start, end) in closed form:
        sum(p+1 for p in [start, end)) = (end(end+1)-start(start+1))/2."""
        a, b = int(start), int(end)
        if b <= a:
            return 0
        n = b - a
        keys = (b * (b + 1) - a * (a + 1)) // 2
        return n * self._row_linear + self.num_layers * 4 \
            * self.d_model * keys

    # -- HBM bytes ----------------------------------------------------
    def span_kv_bytes(self, start: int, end: int) -> int:
        """KV traffic of rows [start, end): each row READS its causal
        extent (pos+1 tokens) and WRITES its own K/V."""
        a, b = int(start), int(end)
        if b <= a:
            return 0
        keys = (b * (b + 1) - a * (a + 1)) // 2
        return self.kv_token_bytes * (keys + (b - a))

    def resident_kv_bytes(self, tokens: int) -> int:
        """KV footprint of ``tokens`` STORED rows — pages at rest, not
        traffic. This is the slice-transfer payload a fleet migration
        ships (export_slices -> import_slices), i.e. the cost side of
        ``MigrationPolicy``'s move/stay inequality
        (inference/fleet.py)."""
        return self.kv_token_bytes * max(0, int(tokens))

    def as_dict(self) -> dict:
        return {"num_layers": self.num_layers, "d_model": self.d_model,
                "ffn_dim": self.ffn_dim,
                "num_experts": self.num_experts, "top_k": self.top_k,
                "kv_token_bytes": self.kv_token_bytes,
                "weight_bytes": self.weight_bytes,
                "weight_itemsize": self.weight_itemsize,
                "row_linear_flops": self._row_linear}


class _Side:
    """One accounting domain (target or draft pool) of one request:
    rows pending a verdict, their exact FLOPs, and the high-water
    mark of computed stream positions (the replay-vs-fresh split)."""

    __slots__ = ("rows", "flops", "hwm")

    def __init__(self):
        self.rows = 0
        self.flops = 0
        self.hwm = 0


class _LedgerRec:
    """Ledger-internal record of one request (the collector's _ReqTrace
    pattern: created at submit or not at all; frozen during replay
    when the dead incarnation observed it live)."""

    __slots__ = ("rid", "tenant", "replayed", "outcome",
                 "target", "draft")

    def __init__(self, rid: int, tenant: str, replayed: bool):
        self.rid = rid
        self.tenant = tenant
        self.replayed = replayed
        self.outcome: Optional[str] = None
        self.target = _Side()
        self.draft = _Side()


class _Bucket:
    """Row/FLOP tallies for one scope (global, or one tenant):
    goodput, per-cause waste, and the running totals the conservation
    identity is checked against."""

    __slots__ = ("rows", "flops", "goodput_rows", "goodput_flops",
                 "waste_rows", "waste_flops", "block_steps")

    def __init__(self):
        self.rows = 0
        self.flops = 0
        self.goodput_rows = 0
        self.goodput_flops = 0
        self.waste_rows = {c: 0 for c in WASTE_CAUSES}
        self.waste_flops = {c: 0 for c in WASTE_CAUSES}
        self.block_steps = 0

    def add(self, rows: int, flops: int) -> None:
        self.rows += rows
        self.flops += flops

    def waste(self, cause: str, rows: int, flops: int) -> None:
        self.waste_rows[cause] += rows
        self.waste_flops[cause] += flops

    def good(self, rows: int, flops: int) -> None:
        self.goodput_rows += rows
        self.goodput_flops += flops

    @property
    def wasted_rows(self) -> int:
        return sum(self.waste_rows.values())

    @property
    def wasted_flops(self) -> int:
        return sum(self.waste_flops.values())

    def as_dict(self) -> dict:
        return {"rows": self.rows, "flops": self.flops,
                "goodput_rows": self.goodput_rows,
                "goodput_flops": self.goodput_flops,
                "waste_rows": dict(self.waste_rows),
                "waste_flops": dict(self.waste_flops),
                "wasted_rows": self.wasted_rows,
                "block_steps": self.block_steps}


class CostLedger:
    """See the module docstring. Every hook is cheap integer
    arithmetic; the ledger never reaches back into the engine and
    never reads a clock."""

    # bounded per-step work log (kind, rows, flops, bytes, model_s) —
    # the offline MFU/MBU percentile source for tools/cost_report.py.
    # TARGET-model scoped: span.model times the target call only, so
    # draft-pool work (priced in the conservation totals) is excluded
    # from the paired numerator too.
    STEP_LOG = 4096

    # long-lived-server bound on per-request records (the collector's
    # max_requests pattern): past it, the OLDEST TERMINAL record is
    # evicted — terminal records hold no pending work, so eviction
    # never touches the conservation identity; live records are never
    # evicted
    MAX_REQUESTS = 100_000

    def __init__(self, work_model: Optional[WorkModel] = None,
                 draft_work_model: Optional[WorkModel] = None,
                 peak_flops_per_s: Optional[float] = None,
                 peak_bytes_per_s: Optional[float] = None,
                 max_requests: Optional[int] = None):
        self.work = work_model
        self.draft_work = draft_work_model
        self.peak_flops_per_s = peak_flops_per_s
        self.peak_bytes_per_s = peak_bytes_per_s
        self.max_requests = (self.MAX_REQUESTS if max_requests is None
                             else int(max_requests))
        self.evicted_records = 0
        self._registry = None
        self._recs: Dict[int, _LedgerRec] = {}
        self.totals = _Bucket()
        self.tenants: Dict[str, _Bucket] = {}
        # pending maintained as counters (O(1) conservation check)
        self.pending_rows = 0
        self.pending_flops = 0
        # split visibility: how much of the row total each pool did
        self.target_rows = 0
        self.draft_rows = 0
        # prefill work AVOIDED (never entered the ledger): first-touch
        # prefix hits vs warm-resume hits on a re-prefill
        self.prefix_saved_tokens = 0
        self.replay_saved_tokens = 0
        self.steps = 0
        self._last_step = -1          # replay freeze gate (monitor's)
        self._replay = False
        # per-step accumulators (reset by on_step)
        self._step_flops = 0
        self._step_bytes = 0
        self._step_prefill_rows = 0
        self._step_decode_rows = 0
        self._step_max_l = 0
        self._span_mark = 0           # span.model observations consumed
        self.step_log: List[tuple] = []
        self.step_log_dropped = 0

    # -- wiring (engine-side) -----------------------------------------
    def bind(self, registry, model=None,
             kv_token_bytes: Optional[int] = None) -> None:
        """Wire onto an engine: build the target WorkModel from the
        engine's core (kept if already built — a ledger riding through
        an engine restore keeps its accumulated state, like the
        monitor), and attach the live ``work`` source to the always-on
        MetricsRegistry."""
        self._registry = registry
        if self.work is None and model is not None:
            self.work = WorkModel.for_model(
                model, kv_token_bytes=kv_token_bytes)
        registry.attach("work", self.registry_view)

    def bind_draft(self, model) -> None:
        if self.draft_work is None and model is not None:
            self.draft_work = WorkModel.for_model(model)

    def set_replay(self, on: bool) -> None:
        """Journal-replay bracket (RecoverableServer.recover): records
        the dead incarnation observed live freeze, replay-born records
        accumulate — the collector's exact semantics."""
        self._replay = bool(on)

    # -- internals ----------------------------------------------------
    def _rec(self, rid: int) -> Optional[_LedgerRec]:
        rec = self._recs.get(rid)
        if rec is None or (self._replay and not rec.replayed):
            return None
        return rec

    def _tb(self, tenant: str) -> _Bucket:
        b = self.tenants.get(tenant)
        if b is None:
            b = self.tenants[tenant] = _Bucket()
        return b

    def _add(self, rec: _LedgerRec, side: _Side, rows: int,
             flops: int) -> None:
        side.rows += rows
        side.flops += flops
        self.pending_rows += rows
        self.pending_flops += flops
        self.totals.add(rows, flops)
        self._tb(rec.tenant).add(rows, flops)

    def _waste_now(self, rec: _LedgerRec, cause: str, rows: int,
                   flops: int) -> None:
        """Account rows that are waste at the moment they are computed
        (replay recomputation): total grows AND the waste bucket grows
        — they never pass through pending."""
        self.totals.add(rows, flops)
        self.totals.waste(cause, rows, flops)
        tb = self._tb(rec.tenant)
        tb.add(rows, flops)
        tb.waste(cause, rows, flops)

    def _resolve(self, rec: _LedgerRec, side: _Side, cause: str,
                 rows: int, flops: int) -> None:
        """Move rows out of pending into a waste cause."""
        side.rows -= rows
        side.flops -= flops
        self.pending_rows -= rows
        self.pending_flops -= flops
        self.totals.waste(cause, rows, flops)
        self._tb(rec.tenant).waste(cause, rows, flops)

    def _span(self, wm: Optional[WorkModel], a: int, b: int
              ) -> Tuple[int, int]:
        """(flops, kv_bytes) of rows [a, b) — zeros without a model."""
        if wm is None:
            return 0, 0
        return wm.span_flops(a, b), wm.span_kv_bytes(a, b)

    def _prefill_rows(self, rec: _LedgerRec, side: _Side,
                      wm: Optional[WorkModel], start: int,
                      end: int) -> int:
        """Prefill rows [start, end): the part below the request's
        computed high-water mark is recomputation (replay waste, NOW);
        the rest is fresh pending work. Returns the rows computed.
        Conservation accounting only — the caller owns the per-step
        (MFU-pairing) accumulators, because they are TARGET-model
        scoped (``span.model`` never times the draft pool)."""
        if end <= start:
            return 0
        cut = max(start, min(end, side.hwm))
        if cut > start:     # recomputed span [start, cut)
            self._waste_now(rec, "replay", cut - start,
                            self._span(wm, start, cut)[0])
        if end > cut:       # fresh span [cut, end)
            self._add(rec, side, end - cut,
                      self._span(wm, cut, end)[0])
        side.hwm = max(side.hwm, end)
        return end - start

    # -- hooks: target engine -----------------------------------------
    def on_submit(self, rid: int, tenant: str,
                  prompt_tokens: int) -> None:
        if rid in self._recs:       # replayed submit of a live record
            return
        if len(self._recs) >= self.max_requests:
            # dict order == submission order: evict the oldest
            # TERMINAL record (its work is fully resolved into the
            # cumulative buckets; the record itself is only identity)
            victim = next((k for k, r in self._recs.items()
                           if r.outcome is not None), None)
            if victim is not None:
                del self._recs[victim]
                self.evicted_records += 1
        self._recs[rid] = _LedgerRec(rid, tenant,
                                     replayed=self._replay)

    def on_fork(self, rid: int, tokens: int) -> None:
        """Branch ``rid`` was COW-forked at stream length ``tokens``:
        its prompt rows were computed ONCE under the group lead and
        are already in the ledger there — raising the branch's target
        high-water mark to ``tokens`` WITHOUT adding pending rows is
        what keeps the shared prefill priced exactly once no matter
        how many branches finish. A later re-prefill of the branch
        (post-preemption, when the COW sharing is lost) then honestly
        lands below the mark and counts as replay waste."""
        rec = self._rec(rid)
        if rec is None:
            return
        rec.target.hwm = max(rec.target.hwm, int(tokens))

    def on_prefill_skip(self, rid: int, n: int) -> None:
        """``n`` prompt rows adopted from the prefix cache instead of
        computed. Below the high-water mark they are warm-resume
        savings (a re-prefill that did NOT replay); above it,
        first-touch prefix-cache savings."""
        rec = self._rec(rid)
        if rec is None or n <= 0:
            return
        warm = min(int(n), rec.target.hwm)
        self.replay_saved_tokens += warm
        self.prefix_saved_tokens += int(n) - warm

    def on_prefill(self, rid: int, start: int, end: int) -> None:
        """Target prefill rows [start, end) computed (one chunk)."""
        rec = self._rec(rid)
        if rec is None:
            return
        n = self._prefill_rows(rec, rec.target, self.work,
                               int(start), int(end))
        if n:
            f, kv = self._span(self.work, int(start), int(end))
            self.target_rows += n
            self._step_flops += f
            self._step_bytes += kv
            self._step_prefill_rows += n

    def on_decode(self, pairs, n: int) -> None:
        """One fused step consumed ``n`` rows per (rid, start_pos) —
        decode (n=1) or multi-token verify (n=K+1)."""
        for rid, start in pairs:
            rec = self._rec(rid)
            if rec is None:
                continue
            a = int(start)
            f, kv = self._span(self.work, a, a + n)
            self._add(rec, rec.target, n, f)
            rec.target.hwm = max(rec.target.hwm, a + n)
            self.target_rows += n
            self._step_flops += f
            self._step_bytes += kv
            self._step_decode_rows += n
        self._step_max_l = max(self._step_max_l, int(n))

    def on_rollback(self, rid: int, new_len: int,
                    old_len: int) -> None:
        """Speculative rejection: verified rows [new_len, old_len)
        are discarded — exactly the FLOPs they were priced at move
        from pending to spec_rejected waste."""
        rec = self._rec(rid)
        if rec is None or old_len <= new_len:
            return
        f, _ = self._span(self.work, int(new_len), int(old_len))
        self._resolve(rec, rec.target, "spec_rejected",
                      int(old_len) - int(new_len), f)
        rec.target.hwm = min(rec.target.hwm, int(new_len))

    def on_outcome(self, rid: int, status: str) -> None:
        """Terminal verdict: ALL the request's pending work (both
        pools) resolves — goodput on FINISHED, the matching waste
        cause on failure. Exactly once per record."""
        rec = self._rec(rid)
        if rec is None or rec.outcome is not None:
            return
        rec.outcome = status
        cause = _FAIL_CAUSE.get(status)
        for side in (rec.target, rec.draft):
            rows, flops = side.rows, side.flops
            if rows == 0 and flops == 0:
                continue
            side.rows = 0
            side.flops = 0
            self.pending_rows -= rows
            self.pending_flops -= flops
            if cause is None:
                self.totals.good(rows, flops)
                self._tb(rec.tenant).good(rows, flops)
            else:
                self.totals.waste(cause, rows, flops)
                self._tb(rec.tenant).waste(cause, rows, flops)

    def on_step(self, step: int, tenant_charges: Dict[str, int],
                span_src=None) -> None:
        """End of one COMPLETED engine step: integrate the per-tenant
        block charge (the block-step bill), flush the step's work
        accumulators into the log, and — when a collector measured
        this step's model phase (``span_src`` is its registry) — pair
        work with duration into MFU/MBU observations on the engine
        registry. Steps at or below the last seen step are journal
        replay of already-counted steps: frozen."""
        if step <= self._last_step:
            self._reset_step()
            return
        self._last_step = int(step)
        self.steps += 1
        for tid, charge in tenant_charges.items():
            if charge:
                self._tb(tid).block_steps += int(charge)
                self.totals.block_steps += int(charge)
        rows = self._step_prefill_rows + self._step_decode_rows
        flops, byts = self._step_flops, self._step_bytes
        model_s = None
        if rows and self.work is not None:
            # one pass through the weights per model-carrying step
            # (the packed/fused call's dominant read; legacy multi-
            # call steps under-count — documented approximation)
            byts += self.work.weight_bytes
        if span_src is not None and rows:
            total = span_src.hist_total("span.model")
            if total < self._span_mark:
                # a FRESH collector replaced the one the mark was
                # taken against (engine recovery wires collectors
                # fresh): its series restarts from zero — rebase, or
                # MFU pairing would stay dark for a whole pre-crash
                # run's worth of steps
                self._span_mark = 0
            if total > self._span_mark:
                self._span_mark = total
                model_s = span_src.last_value("span.model")
        if model_s is not None and model_s > 0 and \
                self._registry is not None:
            self._registry.observe("work.model_flops_per_s",
                                   flops / model_s)
            self._registry.observe("work.model_bytes_per_s",
                                   byts / model_s)
            if self.peak_flops_per_s:
                self._registry.observe(
                    "work.mfu", flops / model_s / self.peak_flops_per_s)
            if self.peak_bytes_per_s:
                self._registry.observe(
                    "work.mbu", byts / model_s / self.peak_bytes_per_s)
        if rows:
            if self._step_max_l > 1:
                kind = "verify"
            elif self._step_prefill_rows and self._step_decode_rows:
                kind = "mixed"
            elif self._step_prefill_rows:
                kind = "prefill"
            else:
                kind = "decode"
            if len(self.step_log) >= self.STEP_LOG:
                del self.step_log[:self.STEP_LOG // 2]
                self.step_log_dropped += self.STEP_LOG // 2
            self.step_log.append((int(step), kind, rows, flops, byts,
                                  model_s))
        self._reset_step()

    def on_step_abort(self) -> None:
        """A crash tore the step down mid-flight: drop the step's
        work-log accumulators (the partial EVENT tallies stand — they
        are real computed work and conservation covers them; only the
        per-step MFU/log sample is discarded, mirroring the monitor's
        aborted-step skip)."""
        self._reset_step()

    def _reset_step(self) -> None:
        self._step_flops = 0
        self._step_bytes = 0
        self._step_prefill_rows = 0
        self._step_decode_rows = 0
        self._step_max_l = 0

    # -- hooks: draft pool (SpeculativeEngine) ------------------------
    def on_draft_prefill(self, rid: int, start: int,
                         end: int) -> None:
        """Draft-cache (re)build rows [start, end): split replay vs
        fresh on the draft high-water mark, same as target prefill."""
        rec = self._rec(rid)
        if rec is None:
            return
        # conservation only: draft work never enters the per-step
        # MFU accumulators (span.model times the TARGET call; pairing
        # draft FLOPs with it would overstate utilization)
        self.draft_rows += self._prefill_rows(
            rec, rec.draft, self.draft_work, int(start), int(end))

    def on_draft_rows(self, pairs) -> None:
        """One draft forward consumed one row per (rid, pos).
        Conservation only — see ``on_draft_prefill`` for why draft
        work stays out of the MFU-paired step accumulators."""
        for rid, pos in pairs:
            rec = self._rec(rid)
            if rec is None:
                continue
            p = int(pos)
            f, _ = self._span(self.draft_work, p, p + 1)
            self._add(rec, rec.draft, 1, f)
            rec.draft.hwm = max(rec.draft.hwm, p + 1)
            self.draft_rows += 1

    def on_draft_truncate(self, rid: int, new_len: int, old_len: int,
                          cause: str = "spec_rejected") -> None:
        """Draft rows [new_len, old_len) discarded: the rejected tail
        of a verified roll (``spec_rejected``) or a partial roll torn
        down by a draft-pool OOM (``draft_oom``)."""
        rec = self._rec(rid)
        if rec is None or old_len <= new_len:
            return
        f, _ = self._span(self.draft_work, int(new_len), int(old_len))
        self._resolve(rec, rec.draft, cause,
                      int(old_len) - int(new_len), f)
        rec.draft.hwm = min(rec.draft.hwm, int(new_len))

    # -- reads --------------------------------------------------------
    def conservation(self) -> dict:
        """The exact identity the whole design defends:
        total == goodput + sum(waste) + pending, rows and FLOPs."""
        t = self.totals
        rows_ok = t.rows == t.goodput_rows + t.wasted_rows \
            + self.pending_rows
        flops_ok = t.flops == t.goodput_flops + t.wasted_flops \
            + self.pending_flops
        return {"rows": {"total": t.rows, "goodput": t.goodput_rows,
                         "waste": t.wasted_rows,
                         "pending": self.pending_rows},
                "flops": {"total": t.flops, "goodput": t.goodput_flops,
                          "waste": t.wasted_flops,
                          "pending": self.pending_flops},
                "ok": bool(rows_ok and flops_ok)}

    def waste_breakdown(self) -> dict:
        """{cause: rows} over every accounted row (the determinism
        currency: two identical seeded runs produce the identical
        dict), plus the goodput/pending balance."""
        t = self.totals
        return {"goodput": t.goodput_rows,
                "pending": self.pending_rows,
                "waste": {c: t.waste_rows[c] for c in WASTE_CAUSES},
                "total": t.rows}

    def goodput_fraction(self) -> Optional[float]:
        """Goodput share of RESOLVED work (pending excluded) — None
        until any work resolved."""
        t = self.totals
        resolved = t.goodput_rows + t.wasted_rows
        if resolved == 0:
            return None
        return t.goodput_rows / resolved

    def tenant_cost(self) -> Dict[str, dict]:
        """The per-tenant bill: block-steps, attributed rows/FLOPs,
        goodput and per-cause waste."""
        return {tid: b.as_dict()
                for tid, b in sorted(self.tenants.items())}

    def registry_view(self) -> dict:
        """The live ``work.*`` source on the engine registry — flat
        counters the HealthMonitor deltas into goodput/waste rates
        (``goodput_tokens_per_step`` next to raw throughput)."""
        t = self.totals
        out = {"total_tokens": t.rows,
               "goodput_tokens": t.goodput_rows,
               "waste_tokens": t.wasted_rows,
               "pending_tokens": self.pending_rows,
               "target_tokens": self.target_rows,
               "draft_tokens": self.draft_rows,
               "flops": t.flops,
               "goodput_flops": t.goodput_flops,
               "prefix_saved_tokens": self.prefix_saved_tokens,
               "replay_saved_tokens": self.replay_saved_tokens,
               "block_steps": t.block_steps}
        for c in WASTE_CAUSES:
            out[f"waste.{c}"] = t.waste_rows[c]
        return out

    def as_dict(self) -> dict:
        """Machine-readable dump — what ``tools/cost_report.py``
        renders and gates on."""
        return {"kind": "cost_ledger",
                "steps": self.steps,
                "work_model": (self.work.as_dict()
                               if self.work is not None else None),
                "draft_work_model": (self.draft_work.as_dict()
                                     if self.draft_work is not None
                                     else None),
                "peak_flops_per_s": self.peak_flops_per_s,
                "peak_bytes_per_s": self.peak_bytes_per_s,
                "conservation": self.conservation(),
                "breakdown": self.waste_breakdown(),
                "goodput_fraction": self.goodput_fraction(),
                "totals": self.totals.as_dict(),
                "tenants": self.tenant_cost(),
                "savings": {
                    "prefix_saved_tokens": self.prefix_saved_tokens,
                    "replay_saved_tokens": self.replay_saved_tokens},
                "step_log": [list(rec) for rec in self.step_log],
                "step_log_dropped": self.step_log_dropped,
                "requests": len(self._recs),
                "evicted_records": self.evicted_records}

    def save(self, path: str) -> int:
        blob = json.dumps(self.as_dict(), indent=1)
        with open(path, "w") as f:
            f.write(blob)
        return len(blob)
