"""Continuous health monitoring: windowed time-series over the
always-on metrics registry, per-tenant SLO tracking, and deterministic
threshold alerting — the serving control plane.

PR 8 made every load signal SAMPLABLE (``engine.registry.delta_since``
interval deltas, per-tenant latency histograms in ``TraceCollector``)
but nothing consumed them: there was no windowed view, no SLO
judgment, no "this engine is unhealthy" verdict. This module is that
consumer — the layer a disaggregated prefill/decode router scrapes
for placement verdicts, and the source of windowed per-phase step
timings for kernel tile sizing:

* ``SeriesBuffer`` — a fixed-capacity ring buffer of (step, value)
  samples with windowed last/mean/max/min/sum queries. Everything is
  keyed to the ENGINE STEP COUNTER, never the wall clock: the series
  (and every judgment derived from them) are a pure function of the
  sampled step sequence, so the same serving run always produces the
  same verdicts — replayable, diffable, testable.

* ``SloPolicy`` / ``SloTracker`` — per-tenant TTFT / TPOT / queue-wait
  targets with a compliance objective. The tracker pulls the
  TraceCollector's per-tenant latency observations through the
  registry's windowed histogram views (``values_since``) into rolling
  windows and reports, per tenant and metric, the compliance fraction
  and the ERROR-BUDGET BURN RATE ((1 - compliance) / (1 - objective):
  1.0 = burning exactly the budget, 2.0 = burning it twice as fast —
  the multiwindow-burn-rate alerting currency of SRE practice).

* ``HealthMonitor`` — composes the series and the SLO state into a
  structured ``HealthReport`` (overall score, per-signal verdicts,
  per-tenant SLO status — the router's future placement input) and
  emits deterministic threshold-crossing ``Alert`` events through a
  bounded stream. Detectors are edge-triggered with explicit
  hysteresis, and the shed-spike detector runs an EWMA baseline
  updated per SAMPLE (step-keyed, not time-keyed), so the same step
  sequence always yields the same ordered alert sequence:

    pool-pressure-high   pool.active / usable crossed the high mark
    shed-spike           windowed shed rate jumped over its EWMA
                         baseline
    acceptance-collapse  windowed speculative acceptance fell through
                         the floor while proposals were still flowing
    queue-growth         queue depth grew monotonically across the
                         detection window
    journal-lag          records appended since the last snapshot
                         crossed the lag bound (RecoverableServer's
                         durability gauges)
    capacity-degraded    the fleet's live-worker fraction fell under
                         its floor (FleetSupervisor-fed; dark when no
                         supervisor registry is bound)
    network-flapping     session-transport reconnects within the
                         detection window crossed the bound (net.*-
                         fed, inference/net.py; dark when no worker
                         runs the session layer)
    slo-burn             a tenant's error-budget burn rate crossed the
                         alerting bound

  Wiring: pass ``monitor=HealthMonitor(...)`` to
  ``PagedServingEngine`` / ``SpeculativeEngine`` (and
  ``RecoverableServer.recover(monitor=...)``). The engine samples the
  monitor inside the existing ``_end_step_telemetry`` path — one
  ``is not None`` check when off, one registry snapshot per cadence
  step when on.

CONTRACTS (tests/test_monitor.py — the same three the telemetry layer
proved in PR 8):

  * ZERO OVERHEAD OFF: with ``monitor=None`` the engines perform no
    monitor work at all — no clock reads, no allocations (and the
    monitor itself NEVER reads a clock even when on: this module does
    not import ``time``; every timestamp it ever sees is an engine
    step number, and the only wall-clock quantities it consumes are
    the latency observations an opt-in TraceCollector already made).
  * PASSIVE: the monitor only reads (registry snapshots, collector
    histograms); token streams and outcomes are bit-identical with
    monitoring on vs off across plain / prefix-cached / speculative /
    recoverable serving, fault storms included.
  * RECOVERY-DERIVED: monitor state is DERIVED, never snapshotted —
    engine snapshots carry no monitor state, and after a restore the
    series rebuild by resampling. During journal replay the monitor
    mirrors the collector's replay semantics (``set_replay``): steps
    it already sampled live are FROZEN (no double counting), steps
    first seen during replay sample normally with their alerts
    flagged ``replayed`` and excluded from the live alert counts.
    ``rebase`` re-baselines the interval-delta snapshot at the
    restored step so a fresh monitor's replayed samples compute the
    same deltas the dead incarnation's monitor did.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from .telemetry import MetricsRegistry

__all__ = ["SeriesBuffer", "SloPolicy", "SloTracker", "Alert",
           "HealthReport", "HealthMonitor"]


# ---------------------------------------------------------------------
# windowed time-series
# ---------------------------------------------------------------------

class SeriesBuffer:
    """Fixed-capacity ring buffer of (step, value) samples with
    windowed queries. ``window=None`` queries span every retained
    sample; ``window=n`` the most recent n. Appends are O(1) into
    preallocated arrays — a long-lived server's series cost is fixed
    at construction, never O(steps served)."""

    __slots__ = ("name", "capacity", "_steps", "_vals", "_n")

    def __init__(self, name: str, capacity: int = 512):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.name = name
        self.capacity = int(capacity)
        self._steps = np.zeros(self.capacity, np.int64)
        self._vals = np.zeros(self.capacity, np.float64)
        self._n = 0             # total samples ever appended

    def append(self, step: int, value: float) -> None:
        i = self._n % self.capacity
        self._steps[i] = int(step)
        self._vals[i] = float(value)
        self._n += 1

    def __len__(self) -> int:
        return min(self._n, self.capacity)

    @property
    def total(self) -> int:
        """Samples ever appended (>= len once the ring wrapped)."""
        return self._n

    def window(self, n: Optional[int] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """(steps, values) of the last ``n`` samples in chronological
        order (everything retained when n is None)."""
        have = len(self)
        if have == 0:
            return (np.empty(0, np.int64), np.empty(0, np.float64))
        take = have if n is None else min(int(n), have)
        end = self._n % self.capacity
        idx = (np.arange(end - take, end)) % self.capacity
        return self._steps[idx].copy(), self._vals[idx].copy()

    # -- windowed scalar queries --------------------------------------
    def last(self) -> Optional[float]:
        if self._n == 0:
            return None
        return float(self._vals[(self._n - 1) % self.capacity])

    def last_step(self) -> Optional[int]:
        if self._n == 0:
            return None
        return int(self._steps[(self._n - 1) % self.capacity])

    def mean(self, n: Optional[int] = None) -> Optional[float]:
        _, v = self.window(n)
        return float(v.mean()) if v.size else None

    def max(self, n: Optional[int] = None) -> Optional[float]:
        _, v = self.window(n)
        return float(v.max()) if v.size else None

    def min(self, n: Optional[int] = None) -> Optional[float]:
        _, v = self.window(n)
        return float(v.min()) if v.size else None

    def sum(self, n: Optional[int] = None) -> float:
        _, v = self.window(n)
        return float(v.sum())

    def rate(self, n: Optional[int] = None) -> Optional[float]:
        """Per-step slope over the window: (last - first) / step span.
        For GAUGE series this is the growth rate (queue-growth's
        signal); delta-fed series are already per-step rates — query
        ``mean`` there instead."""
        s, v = self.window(n)
        if v.size < 2 or s[-1] == s[0]:
            return None
        return float((v[-1] - v[0]) / (s[-1] - s[0]))

    def as_dict(self, n: Optional[int] = None) -> dict:
        r = lambda v: None if v is None else round(v, 6)  # noqa: E731
        return {"samples": len(self), "total": self._n,
                "last": r(self.last()), "mean": r(self.mean(n)),
                "max": r(self.max(n)), "min": r(self.min(n))}


# ---------------------------------------------------------------------
# per-tenant SLO tracking
# ---------------------------------------------------------------------

class SloPolicy:
    """Latency targets for one tenant: any subset of TTFT / TPOT /
    queue-wait (seconds), plus the compliance ``objective`` — the
    fraction of requests that must meet each target (0.99 = a 1%
    error budget). ``objective`` must sit strictly inside (0, 1):
    1.0 would make the burn rate undefined (zero budget)."""

    METRICS = ("ttft_s", "tpot_s", "queue_wait_s")

    __slots__ = METRICS + ("objective",)

    def __init__(self, *, ttft_s: Optional[float] = None,
                 tpot_s: Optional[float] = None,
                 queue_wait_s: Optional[float] = None,
                 objective: float = 0.99):
        if not (0.0 < objective < 1.0):
            raise ValueError(
                f"objective must be in (0, 1), got {objective} (an "
                f"objective of 1.0 leaves no error budget to burn)")
        if ttft_s is None and tpot_s is None and queue_wait_s is None:
            raise ValueError("at least one latency target must be set")
        for name, v in (("ttft_s", ttft_s), ("tpot_s", tpot_s),
                        ("queue_wait_s", queue_wait_s)):
            if v is not None and v <= 0:
                raise ValueError(f"{name} target must be > 0")
        self.ttft_s = ttft_s
        self.tpot_s = tpot_s
        self.queue_wait_s = queue_wait_s
        self.objective = float(objective)

    def as_dict(self) -> dict:
        out = {m: getattr(self, m) for m in self.METRICS
               if getattr(self, m) is not None}
        out["objective"] = self.objective
        return out


class SloTracker:
    """Rolling per-tenant SLO compliance over the TraceCollector's
    per-tenant latency histograms (``latency.<metric>.tenant.<tid>``
    in the collector's registry). ``policies`` maps tenant id ->
    SloPolicy; the ``"*"`` entry (or a bare SloPolicy) is the default
    for tenants not listed — tenants with no applicable policy are
    not tracked. ``update`` pulls only the observations since the
    last pull (the registry's windowed ``values_since`` view) into
    bounded per-(tenant, metric) windows; ``status`` reports
    compliance fraction and burn rate per window."""

    def __init__(self, policies, window: int = 128):
        if isinstance(policies, SloPolicy):
            policies = {"*": policies}
        if not policies:
            raise ValueError("at least one SloPolicy is required")
        for tid, pol in policies.items():
            if not isinstance(pol, SloPolicy):
                raise TypeError(
                    f"policies[{tid!r}] must be an SloPolicy")
        self.policies: Dict[str, SloPolicy] = dict(policies)
        self.window = int(window)
        self._marks: Dict[str, int] = {}
        self._vals: Dict[Tuple[str, str], deque] = {}

    def policy_for(self, tenant: str) -> Optional[SloPolicy]:
        return self.policies.get(tenant, self.policies.get("*"))

    def update(self, registry: MetricsRegistry) -> None:
        """Pull new per-tenant latency observations from a collector's
        registry into the rolling windows (idempotent between new
        observations — the marks remember what was consumed)."""
        for name in registry.hist_names():
            if not name.startswith("latency.") or ".tenant." not in name:
                continue
            metric, _, tid = \
                name[len("latency."):].partition(".tenant.")
            pol = self.policy_for(tid)
            if pol is None or metric not in SloPolicy.METRICS or \
                    getattr(pol, metric) is None:
                continue
            total = registry.hist_total(name)
            start = self._marks.get(name, 0)
            if total <= start:
                continue
            vals = registry.values_since(name, start)
            self._marks[name] = total
            dq = self._vals.setdefault(
                (tid, metric), deque(maxlen=self.window))
            dq.extend(vals)

    def status(self) -> Dict[str, dict]:
        """{tenant: {metric: {target_s, objective, window, compliance,
        burn, ok}}} over each rolling window. ``burn`` is the
        error-budget burn rate: 1.0 = exactly on budget, above 1 =
        burning faster than the objective allows."""
        out: Dict[str, dict] = {}
        for (tid, metric) in sorted(self._vals):
            dq = self._vals[(tid, metric)]
            if not dq:
                continue
            pol = self.policy_for(tid)
            target = getattr(pol, metric)
            n = len(dq)
            good = sum(1 for v in dq if v <= target)
            comp = good / n
            burn = (1.0 - comp) / (1.0 - pol.objective)
            out.setdefault(tid, {})[metric] = {
                "target_s": target, "objective": pol.objective,
                "window": n, "compliance": round(comp, 6),
                "burn": round(burn, 6), "ok": comp >= pol.objective}
        return out


# ---------------------------------------------------------------------
# alerts
# ---------------------------------------------------------------------

class Alert:
    """One deterministic threshold crossing. ``step`` is the engine
    step the detector fired at; ``replayed`` flags alerts re-derived
    during journal replay (mirroring the collector's replay-flagged
    spans — same verdict, but not a fresh incident)."""

    __slots__ = ("step", "kind", "signal", "value", "threshold",
                 "tenant", "replayed")

    def __init__(self, step: int, kind: str, signal: str, value: float,
                 threshold: float, tenant: Optional[str] = None,
                 replayed: bool = False):
        self.step = int(step)
        self.kind = kind
        self.signal = signal
        self.value = float(value)
        self.threshold = float(threshold)
        self.tenant = tenant
        self.replayed = bool(replayed)

    def sig(self) -> tuple:
        """Identity tuple WITHOUT the replay flag — two derivations of
        the same incident (live vs replayed) share a sig."""
        return (self.step, self.kind, self.signal,
                round(self.value, 9), round(self.threshold, 9),
                self.tenant)

    def as_dict(self) -> dict:
        return {"step": self.step, "kind": self.kind,
                "signal": self.signal, "value": round(self.value, 6),
                "threshold": round(self.threshold, 6),
                "tenant": self.tenant, "replayed": self.replayed}

    def __eq__(self, other):
        return isinstance(other, Alert) and \
            self.sig() == other.sig() and \
            self.replayed == other.replayed

    def __hash__(self):
        return hash((self.sig(), self.replayed))

    def __repr__(self):
        t = f", tenant={self.tenant!r}" if self.tenant else ""
        r = ", replayed" if self.replayed else ""
        return (f"Alert(step={self.step}, {self.kind}: "
                f"{self.signal}={self.value:.4g} vs "
                f"{self.threshold:.4g}{t}{r})")


class HealthReport:
    """Structured verdict over the monitored engine: an overall score
    in [0, 1] with a worst-of verdict, per-signal windowed stats +
    verdicts, per-tenant SLO status, and the alert tallies. A pure
    function of the sampled step sequence (plus the SLO windows) —
    the placement input a router scrapes per host."""

    __slots__ = ("step", "samples", "score", "verdict", "signals",
                 "tenants", "alerts")

    def __init__(self, step, samples, score, verdict, signals,
                 tenants, alerts):
        self.step = step
        self.samples = samples
        self.score = score
        self.verdict = verdict
        self.signals = signals
        self.tenants = tenants
        self.alerts = alerts

    def as_dict(self) -> dict:
        return {"kind": "health_report", "step": self.step,
                "samples": self.samples, "score": self.score,
                "verdict": self.verdict, "signals": self.signals,
                "tenants": self.tenants, "alerts": self.alerts}

    def placement(self) -> dict:
        """Compact placement view — the handful of numbers a
        disaggregated router (inference/router.py) needs from each
        worker's scrape: overall verdict/score plus the last windowed
        value of the load-bearing signals. Small enough to cross a
        pipe every tick; the full report stays host-side."""
        def last(name):
            s = self.signals.get(name)
            return None if not s else s.get("last")
        return {"verdict": self.verdict, "score": self.score,
                "step": self.step,
                "pool_pressure": last("pool.pressure"),
                "queue_depth": last("queue.depth"),
                "shed_rate": last("shed_rate"),
                "tokens_per_step": last("tokens_per_step")}

    def __repr__(self):
        return (f"HealthReport(step={self.step}, "
                f"score={self.score:.2f}, {self.verdict}, "
                f"{len(self.signals)} signal(s))")


# ---------------------------------------------------------------------
# the monitor
# ---------------------------------------------------------------------

class HealthMonitor:
    """See the module docstring. Construction is engine-free; an
    engine ``bind``s its registry (and optional collector) at wiring
    time and calls ``on_step(step_count)`` from its telemetry path.
    One monitor watches one engine."""

    # detector thresholds (override any subset via ``thresholds=``)
    DEFAULTS = {
        # pool occupancy fraction that fires / re-arms the pressure
        # alert (hysteresis: stays active until it falls below clear)
        "pool_pressure_high": 0.9,
        "pool_pressure_clear": 0.8,
        # shed-spike: windowed shed rate > factor x its EWMA baseline
        # (alpha is the per-sample EWMA weight)
        "shed_spike_factor": 4.0,
        "shed_ewma_alpha": 0.2,
        # speculative acceptance collapse floor (windowed mean)
        "acceptance_floor": 0.2,
        # queue-growth: depth non-decreasing across this many samples
        # with at least this much total growth
        "queue_growth_samples": 4,
        "queue_growth_min": 3,
        # journal records appended since the last snapshot
        "journal_lag_high": 256,
        # fleet capacity: live-worker fraction under the floor fires;
        # hysteresis: stays active until back above clear (a respawned
        # fleet must actually rejoin before the alert re-arms)
        "capacity_degraded_floor": 0.75,
        "capacity_degraded_clear": 0.99,
        # goodput-collapse: the share of the window's TOTAL ledger
        # work not known-wasted ((work - waste) / work) fell through
        # the floor (CostLedger-fed; dark without a ledger)
        "goodput_floor": 0.5,
        # waste-spike: windowed waste rate > factor x its EWMA
        # baseline (the shed-spike pattern on wasted token-rows)
        "waste_spike_factor": 4.0,
        "waste_ewma_alpha": 0.2,
        # SLO error-budget burn rate that fires, and the minimum
        # window occupancy before burn is judged at all
        "slo_burn_high": 2.0,
        "slo_min_samples": 8,
        # expert-collapse (MoE-fed, dark for dense models — the moe.*
        # registry namespace never appears): the top expert's share of
        # the interval's routed assignments at/above the fraction
        # fires; hysteresis: re-arms below _clear. Intervals routing
        # fewer than _min_routed assignments are not judged (a 2-row
        # step trivially routes 100% to one expert).
        "expert_collapse_frac": 0.8,
        "expert_collapse_clear": 0.5,
        "expert_collapse_min_routed": 8,
        # network-flapping (session-transport-fed, inference/net.py;
        # dark when no worker runs the session layer — the net.*
        # namespace never appears): reconnects within the detection
        # window at/above _min fires; hysteresis: re-arms only after
        # a window with at most _clear reconnects (a settled network)
        "network_flapping_min": 3,
        "network_flapping_clear": 0,
    }

    def __init__(self, slo=None, *, sample_every: int = 1,
                 capacity: int = 512, window: int = 16,
                 slo_window: int = 128, max_alerts: int = 4096,
                 thresholds: Optional[dict] = None):
        if sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        self.sample_every = int(sample_every)
        self.capacity = int(capacity)
        self.window = int(window)
        self.max_alerts = int(max_alerts)
        self.thresholds = dict(self.DEFAULTS)
        for k, v in (thresholds or {}).items():
            if k not in self.DEFAULTS:
                raise ValueError(f"unknown threshold {k!r} (have: "
                                 f"{sorted(self.DEFAULTS)})")
            self.thresholds[k] = v
        self.slo = None
        if slo is not None:
            self.slo = slo if isinstance(slo, SloTracker) \
                else SloTracker(slo, window=slo_window)
        self._registry: Optional[MetricsRegistry] = None
        self._collector = None
        self._series: Dict[str, SeriesBuffer] = {}
        self._prev: Optional[dict] = None
        self._prev_step = 0
        self._last_step = -1          # last SAMPLED step (frozen gate)
        self._span_marks: Dict[str, int] = {}
        self._ewma: Dict[str, float] = {}
        self._active: set = set()     # (kind, tenant) currently firing
        self._replay = False
        self.samples = 0
        self.alerts: List[Alert] = []
        self.alerts_dropped = 0
        self.alert_counts: Dict[str, int] = {}

    # -- wiring (engine-side) -----------------------------------------
    def bind(self, registry: MetricsRegistry, collector=None) -> None:
        """Wire the monitor onto an engine's always-on registry (and
        its optional TraceCollector, the SLO latency source). Called
        by the engine constructor; re-binding (engine restore) keeps
        every accumulated series and alert — derived state survives
        the engine object it was derived from."""
        self._registry = registry
        self._collector = collector

    def set_replay(self, on: bool) -> None:
        """Journal-replay bracket (RecoverableServer.recover), the
        mirror of TraceCollector.set_replay: steps already sampled
        live stay frozen, newly seen steps sample with their alerts
        flagged ``replayed`` and kept out of ``alert_counts``."""
        self._replay = bool(on)

    def rebase(self, step: int) -> None:
        """Re-baseline after an engine restore: snapshot the restored
        registry at ``step`` so the NEXT sample's interval deltas span
        exactly one interval (counters are snapshot-restored to their
        step-``step`` values, so a fresh monitor resampling the replay
        computes the same deltas the dead incarnation's monitor did).
        A monitor that already holds samples past ``step`` (the same
        object riding through recovery) is left untouched — its live
        history IS the baseline."""
        if self._registry is None or int(step) < self._last_step:
            return
        self._prev = self._registry.as_dict()
        self._prev_step = int(step)
        self._last_step = int(step)

    # -- sampling -----------------------------------------------------
    def on_step(self, step: int) -> None:
        """Engine hook, called at the end of every step with the step
        counter. Samples at the configured cadence; non-monotonic
        steps (journal replay of steps already sampled live) are
        frozen — the live samples stand, nothing double-counts."""
        if self._registry is None or step <= self._last_step:
            return
        if step % self.sample_every:
            return
        self._last_step = int(step)
        self._sample(int(step))

    def series(self, name: str) -> Optional[SeriesBuffer]:
        return self._series.get(name)

    def _push(self, name: str, step: int, value: float) -> None:
        sb = self._series.get(name)
        if sb is None:
            sb = self._series[name] = SeriesBuffer(
                name, capacity=self.capacity)
        sb.append(step, value)

    def _sample(self, step: int) -> None:
        cur = self._registry.as_dict()
        prev, pstep = self._prev, self._prev_step
        self._prev, self._prev_step = cur, step
        self.samples += 1

        def num(d, key, default=0.0):
            v = d.get(key, default)
            return float(v) if isinstance(v, (int, float)) and \
                not isinstance(v, bool) else float(default)

        # gauges — step-boundary ground truth
        active = num(cur, "pool.active")
        usable = num(cur, "pool.usable") or 1.0
        self._push("pool.active", step, active)
        self._push("pool.cached_free", step,
                   num(cur, "pool.cached_free"))
        self._push("pool.free", step, num(cur, "pool.free"))
        self._push("pool.pressure", step, active / usable)
        self._push("queue.depth", step, num(cur, "queue.depth"))
        self._push("queue.active", step, num(cur, "queue.active"))
        for key, v in cur.items():
            # the live gauge is tenants.<tid>.blocks_held; the
            # .stats.blocks_held sibling is the same number through
            # TenantStats — one series per tenant, not two
            if key.startswith("tenants.") and \
                    key.endswith(".blocks_held") and \
                    ".stats." not in key:
                tid = key[len("tenants."):-len(".blocks_held")]
                self._push(f"tenant.{tid}.charge", step, float(v))
        if "journal.lag_records" in cur:
            self._push("journal.lag", step,
                       num(cur, "journal.lag_records"))
            self._push("journal.bytes", step, num(cur, "journal.bytes"))
        if "snapshot.age_steps" in cur:
            self._push("snapshot.age", step,
                       num(cur, "snapshot.age_steps"))
        if "fleet.workers_total" in cur:
            total = num(cur, "fleet.workers_total") or 1.0
            self._push("fleet.capacity", step,
                       num(cur, "fleet.workers_live") / total)
            self._push("fleet.respawns", step,
                       num(cur, "fleet.respawns"))
        # session-transport counters (inference/net.py — dark when no
        # worker runs the session layer: the net.* namespace never
        # appears and the network-flapping detector stays off)
        if "net.reconnects" in cur:
            self._push("net.reconnects", step,
                       num(cur, "net.reconnects"))
            self._push("net.retried_ops", step,
                       num(cur, "net.retried_ops"))
        if "moe.routed_tokens" in cur:
            self._push("moe.overflow_rate", step,
                       num(cur, "moe.overflow_rate"))
        # fork-shared parallel decoding gauges (dark until the first
        # submit(n>1)/fork_stream — the parallel.* namespace stays all
        # zero for plain serving and the series are never pushed)
        if num(cur, "parallel.groups") > 0:
            self._push("parallel.branches_per_group", step,
                       num(cur, "parallel.branches_per_group"))
            self._push("parallel.shared_blocks", step,
                       num(cur, "parallel.shared_blocks"))

        # interval deltas — the first sample is baseline only
        if prev is not None:
            dstep = max(1, step - pstep)
            tok = sum(v - num(prev, k)
                      for k, v in cur.items()
                      if k.endswith(".stats.tokens_served")
                      and isinstance(v, (int, float)))
            self._push("tokens_per_step", step, tok / dstep)
            shed = num(cur, "resilience.shed") \
                - num(prev, "resilience.shed")
            self._push("shed_rate", step, shed / dstep)
            prop = num(cur, "spec.proposed") \
                - num(prev, "spec.proposed")
            if prop > 0:
                acc = num(cur, "spec.accepted") \
                    - num(prev, "spec.accepted")
                self._push("spec.acceptance", step, acc / prop)
            # cost-ledger work signals (present only with a ledger
            # wired — inference/accounting.py): work and goodput vs
            # waste per step, and the share of the interval's work
            # NOT known-wasted. Goodput itself resolves only at
            # request FINISH (lumpy at request granularity), so the
            # collapse fraction is judged against TOTAL work done —
            # a long generation with no completions in the window
            # must not read as a collapse
            if "work.total_tokens" in cur:
                tot = num(cur, "work.total_tokens") \
                    - num(prev, "work.total_tokens")
                good = num(cur, "work.goodput_tokens") \
                    - num(prev, "work.goodput_tokens")
                waste = num(cur, "work.waste_tokens") \
                    - num(prev, "work.waste_tokens")
                self._push("work_per_step", step, tot / dstep)
                self._push("goodput_per_step", step, good / dstep)
                self._push("waste_rate", step, waste / dstep)
                if tot > 0:
                    self._push("goodput_fraction", step,
                               max(0.0, (tot - waste) / tot))
            # MoE per-expert load skew over the interval (MoE-fed;
            # dense models never surface moe.* keys and the series
            # stays dark). Thin intervals (fewer routed assignments
            # than the judging floor) are NOT pushed — a near-empty
            # step trivially routes everything to one expert and must
            # not read as a collapse or a recovery.
            if "moe.routed_tokens" in cur:
                E = int(num(cur, "moe.experts"))
                loads = [num(cur, f"moe.load.{e}")
                         - num(prev, f"moe.load.{e}") for e in range(E)]
                routed = sum(loads)
                if routed >= self.thresholds[
                        "expert_collapse_min_routed"]:
                    self._push("moe.top_frac", step,
                               max(loads) / routed)
                    self._push("moe.routed_per_step", step,
                               routed / dstep)

        # per-phase step-span durations (collector-side wall clock —
        # observational, feeds kernel tile sizing, never a detector)
        col = self._collector
        if col is not None:
            for name in col.registry.hist_names():
                if not name.startswith("span."):
                    continue
                total = col.registry.hist_total(name)
                start = self._span_marks.get(name, 0)
                if total <= start:
                    continue
                vals = col.registry.values_since(name, start)
                self._span_marks[name] = total
                self._push(name, step, float(np.mean(vals)))
            if self.slo is not None:
                self.slo.update(col.registry)
        self._detect(step)

    # -- detectors ----------------------------------------------------
    def _fire(self, kind: str, firing: bool, step: int, signal: str,
              value, threshold: float,
              tenant: Optional[str] = None) -> None:
        """Edge-triggered alert with hysteresis folded into ``firing``
        by the caller: an alert fires once per crossing and re-arms
        when the condition clears."""
        key = (kind, tenant)
        if firing and key not in self._active:
            self._active.add(key)
            a = Alert(step, kind, signal, float(value),
                      float(threshold), tenant=tenant,
                      replayed=self._replay)
            if len(self.alerts) < self.max_alerts:
                self.alerts.append(a)
            else:
                self.alerts_dropped += 1
            if not self._replay:
                self.alert_counts[kind] = \
                    self.alert_counts.get(kind, 0) + 1
        elif not firing:
            self._active.discard(key)

    def _detect(self, step: int) -> None:
        th = self.thresholds
        # 1. pool-pressure-high (hysteresis: clears below _clear)
        sb = self._series.get("pool.pressure")
        if sb is not None:
            v = sb.last()
            bound = th["pool_pressure_clear"] \
                if ("pool-pressure-high", None) in self._active \
                else th["pool_pressure_high"]
            self._fire("pool-pressure-high", v >= bound, step,
                       "pool.pressure", v, th["pool_pressure_high"])
        # 2. shed-spike (EWMA baseline; clears when the rate decays
        #    back to the baseline)
        sb = self._series.get("shed_rate")
        if sb is not None and sb.total > 0:
            v = sb.last()
            base = self._ewma.get("shed_rate")
            b = 0.0 if base is None else base
            if ("shed-spike", None) in self._active:
                firing = v > b
            else:
                firing = v > 0 and v > th["shed_spike_factor"] * b
            self._fire("shed-spike", firing, step, "shed_rate", v,
                       th["shed_spike_factor"] * b)
            a = th["shed_ewma_alpha"]
            self._ewma["shed_rate"] = v if base is None \
                else a * v + (1 - a) * base
        # 3. acceptance-collapse (windowed mean under the floor)
        sb = self._series.get("spec.acceptance")
        if sb is not None and sb.total > 0:
            m = sb.mean(self.window)
            self._fire("acceptance-collapse",
                       m < th["acceptance_floor"], step,
                       "spec.acceptance", m, th["acceptance_floor"])
        # 4. queue-growth (monotone growth across the window)
        sb = self._series.get("queue.depth")
        if sb is not None:
            g = int(th["queue_growth_samples"])
            _, v = sb.window(g)
            firing = v.size >= g and bool(np.all(np.diff(v) >= 0)) \
                and v[-1] - v[0] >= th["queue_growth_min"]
            self._fire("queue-growth", firing, step, "queue.depth",
                       sb.last(), th["queue_growth_min"])
        # 4b. goodput-collapse (CostLedger-fed: the share of the
        #     window's TOTAL work not known-wasted fell through the
        #     floor — judged against work done, not work resolved,
        #     because goodput lands in one lump when a request
        #     finishes: a long generation mid-flight has zero
        #     resolved goodput and must not read as a collapse)
        sbt = self._series.get("work_per_step")
        sbw = self._series.get("waste_rate")
        if sbt is not None and sbw is not None:
            t_sum = sbt.sum(self.window)
            w = sbw.sum(self.window)
            frac = max(0.0, (t_sum - w) / t_sum) if t_sum > 0 else None
            self._fire("goodput-collapse",
                       frac is not None and frac < th["goodput_floor"],
                       step, "goodput_fraction",
                       frac if frac is not None else 1.0,
                       th["goodput_floor"])
            # 4c. waste-spike (EWMA baseline, the shed-spike pattern —
            #     except the FIRST NONZERO waste sample only SEEDS the
            #     baseline: speculative rejection makes routine waste,
            #     so "any waste at all" must not read as a spike the
            #     way a first shed legitimately does. Zero-waste
            #     intervals before that leave the baseline UNSEEDED —
            #     a 0.0-seeded EWMA would turn the first routine
            #     rejection into a division-free infinite spike.)
            v = sbw.last()
            base = self._ewma.get("waste_rate")
            b = 0.0 if base is None else base
            if ("waste-spike", None) in self._active:
                firing = v > b
            else:
                firing = base is not None and v > 0 and \
                    v > th["waste_spike_factor"] * b
            self._fire("waste-spike", firing, step, "waste_rate", v,
                       th["waste_spike_factor"] * b)
            if v > 0 or base is not None:
                a = th["waste_ewma_alpha"]
                self._ewma["waste_rate"] = v if base is None \
                    else a * v + (1 - a) * base
        # 5. journal-lag (clears below half the bound)
        sb = self._series.get("journal.lag")
        if sb is not None:
            v = sb.last()
            bound = th["journal_lag_high"] / 2 \
                if ("journal-lag", None) in self._active \
                else th["journal_lag_high"]
            self._fire("journal-lag", v >= bound, step, "journal.lag",
                       v, th["journal_lag_high"])
        # 5b. capacity-degraded (FleetSupervisor-fed: the live-worker
        #     fraction fell under the floor. Dark without a fleet —
        #     the series simply never appears. Hysteresis: a storm's
        #     respawns must carry capacity back above _clear before
        #     the alert re-arms, so one kill storm is one alert.)
        sb = self._series.get("fleet.capacity")
        if sb is not None:
            v = sb.last()
            bound = th["capacity_degraded_clear"] \
                if ("capacity-degraded", None) in self._active \
                else th["capacity_degraded_floor"]
            self._fire("capacity-degraded", v < bound, step,
                       "fleet.capacity", v,
                       th["capacity_degraded_floor"])
        # 5c. expert-collapse (MoE-fed: the top expert's share of the
        #     interval's routed assignments pinned high — the router
        #     has stopped spreading and E-1 expert tables are dead
        #     HBM. Dark for dense models: the moe.* namespace never
        #     appears, so the series is never pushed. Hysteresis: the
        #     alert re-arms only after the share falls under _clear.)
        sb = self._series.get("moe.top_frac")
        if sb is not None and sb.total > 0:
            v = sb.last()
            bound = th["expert_collapse_clear"] \
                if ("expert-collapse", None) in self._active \
                else th["expert_collapse_frac"]
            self._fire("expert-collapse", v >= bound, step,
                       "moe.top_frac", v, th["expert_collapse_frac"])
        # 5d. network-flapping (session-transport-fed: reconnects
        #     within the detection window crossed the bound — the
        #     fleet is riding out repeated drops rather than a single
        #     blip. Dark without the session layer: the net.* series
        #     is never pushed. Hysteresis: the alert re-arms only
        #     after a window with at most _clear NEW reconnects, so
        #     one storm is one alert however many drops it lands.)
        sb = self._series.get("net.reconnects")
        if sb is not None:
            _, vals = sb.window(self.window)
            delta = float(vals[-1] - vals[0]) if vals.size >= 2 \
                else 0.0
            active = ("network-flapping", None) in self._active
            firing = (delta > th["network_flapping_clear"] if active
                      else delta >= th["network_flapping_min"])
            self._fire("network-flapping", firing, step,
                       "net.reconnects", delta,
                       th["network_flapping_min"])
        # 6. slo-burn (per tenant, deterministic order)
        if self.slo is not None:
            status = self.slo.status()
            for tid in sorted(status):
                worst_m, worst = None, None
                for metric, rec in sorted(status[tid].items()):
                    if rec["window"] < th["slo_min_samples"]:
                        continue
                    if worst is None or rec["burn"] > worst:
                        worst_m, worst = metric, rec["burn"]
                firing = worst is not None and \
                    worst >= th["slo_burn_high"]
                self._fire("slo-burn", firing, step,
                           worst_m or "slo", worst or 0.0,
                           th["slo_burn_high"], tenant=tid)

    def drain_alerts(self) -> List[Alert]:
        out, self.alerts = self.alerts, []
        return out

    # -- the report -----------------------------------------------------
    _VERDICT_RANK = {"ok": 0, "warn": 1, "critical": 2}

    def _signal_verdict(self, name: str, sb: SeriesBuffer) -> str:
        th = self.thresholds
        if name == "pool.pressure":
            if ("pool-pressure-high", None) in self._active:
                return "critical"
            if (sb.last() or 0.0) >= th["pool_pressure_clear"]:
                return "warn"
        elif name == "shed_rate":
            if ("shed-spike", None) in self._active:
                return "critical"
            if sb.sum(self.window) > 0:
                return "warn"
        elif name == "spec.acceptance":
            if ("acceptance-collapse", None) in self._active:
                return "critical"
        elif name == "queue.depth":
            if ("queue-growth", None) in self._active:
                return "warn"
        elif name == "goodput_fraction":
            if ("goodput-collapse", None) in self._active:
                return "critical"
        elif name == "waste_rate":
            # routine speculative rejection IS waste — only a spike
            # over the run's own baseline degrades the verdict
            if ("waste-spike", None) in self._active:
                return "critical"
        elif name == "journal.lag":
            if ("journal-lag", None) in self._active:
                return "critical"
            if (sb.last() or 0.0) >= th["journal_lag_high"] / 2:
                return "warn"
        elif name == "fleet.capacity":
            # 0.0 is a REAL capacity reading (every worker dead) —
            # never `or`-default this one
            if ("capacity-degraded", None) in self._active:
                return "critical"
            last = sb.last()
            if last is not None and \
                    last < th["capacity_degraded_clear"]:
                return "warn"
        elif name == "moe.top_frac":
            if ("expert-collapse", None) in self._active:
                return "critical"
            if (sb.last() or 0.0) >= th["expert_collapse_clear"]:
                return "warn"
        elif name == "net.reconnects":
            if ("network-flapping", None) in self._active:
                return "critical"
            _, vals = sb.window(self.window)
            if vals.size >= 2 and vals[-1] > vals[0]:
                return "warn"          # reconnecting, under the bound
        return "ok"

    def report(self) -> HealthReport:
        """The structured health verdict — a pure function of the
        sampled series, the SLO windows and the active-alert state
        (all of it step-derived)."""
        th = self.thresholds
        signals = {}
        worst = 0
        n_warn = n_crit = 0
        for name in sorted(self._series):
            sb = self._series[name]
            verdict = self._signal_verdict(name, sb)
            rank = self._VERDICT_RANK[verdict]
            worst = max(worst, rank)
            n_warn += rank == 1
            n_crit += rank == 2
            signals[name] = dict(sb.as_dict(self.window),
                                 verdict=verdict)
        tenants: Dict[str, dict] = {}
        for name in self._series:
            if name.startswith("tenant.") and name.endswith(".charge"):
                tid = name[len("tenant."):-len(".charge")]
                tenants.setdefault(tid, {})["charge"] = \
                    self._series[name].last()
        slo_status = self.slo.status() if self.slo is not None else {}
        for tid, metrics in slo_status.items():
            burns = [r["burn"] for r in metrics.values()
                     if r["window"] >= th["slo_min_samples"]]
            burn = max(burns) if burns else 0.0
            if burn >= th["slo_burn_high"]:
                v = "critical"
            elif burn > 1.0 or any(not r["ok"]
                                   for r in metrics.values()):
                v = "warn"
            else:
                v = "ok"
            rank = self._VERDICT_RANK[v]
            worst = max(worst, rank)
            n_warn += rank == 1
            n_crit += rank == 2
            tenants.setdefault(tid, {})["slo"] = \
                dict(metrics, verdict=v)
        score = max(0.0, round(1.0 - 0.25 * n_crit - 0.1 * n_warn, 4))
        verdict = ("ok", "warn", "critical")[worst]
        active = sorted(f"{k}:{t}" if t else k
                        for k, t in self._active)
        return HealthReport(
            step=self._last_step if self._last_step >= 0 else None,
            samples=self.samples, score=score, verdict=verdict,
            signals=signals, tenants=tenants,
            alerts={"counts": dict(sorted(self.alert_counts.items())),
                    "active": active,
                    "pending": len(self.alerts),
                    "dropped": self.alerts_dropped})

    # -- export ---------------------------------------------------------
    def as_dict(self) -> dict:
        """Machine-readable dump: the report plus the raw alert stream
        and SLO detail — what ``tools/health_report.py`` renders."""
        return {"kind": "health_monitor",
                "sample_every": self.sample_every,
                "window": self.window,
                "thresholds": dict(self.thresholds),
                "report": self.report().as_dict(),
                "alerts": [a.as_dict() for a in self.alerts],
                "alert_counts": dict(sorted(
                    self.alert_counts.items())),
                "alerts_dropped": self.alerts_dropped,
                "slo": (self.slo.status()
                        if self.slo is not None else {}),
                "slo_policies": ({t: p.as_dict() for t, p in
                                  self.slo.policies.items()}
                                 if self.slo is not None else {})}

    def save(self, path: str) -> int:
        """Write ``as_dict()`` as JSON; returns bytes written."""
        import json
        blob = json.dumps(self.as_dict(), indent=1)
        with open(path, "w") as f:
            f.write(blob)
        return len(blob)
