"""Disaggregated prefill/decode serving behind a fault-tolerant,
prefix-aware router.

The single engine behind ``RecoverableServer`` is an operable node:
restartable (PR 6), multi-tenant (PR 7), observable (PRs 8-9),
accounted (PR 11). Serving past one process means a FLEET of those
nodes behind a router that owns three jobs, each built from a piece
that already exists:

* **Placement** — every worker advertises its chain-hash prefix index
  (the PR 2 identity: ``h_i = H(h_{i-1}, block_tokens)``) plus a
  health/pressure scrape (PR 9's ``HealthReport``). A new request
  lands on the worker holding its LONGEST indexed prefix (its prefill
  is mostly already paid for there); with no match anywhere it lands
  on a prefill-role worker by load, and a pressured best-match worker
  SPILLS to a cooler one — prefix affinity never overrides overload.

* **Page migration** — the disaggregated split: prefill-heavy workers
  compute prompts, decode workers hold the long tail of token
  generation. A finished prefill MOVES as a per-slot slice of the
  PR 6 snapshot (``PagedKVCache.export_slice``: content-addressed
  (hash, page) pairs), is adopted into the target pool's cached-free
  tier (``import_slice``), and the stream is RESUBMITTED there with
  ``resume=True`` (the pending-token handoff — the preemption
  re-admission path, so the migrated stream's bytes are identical to
  an unmigrated run); admission's normal prefix matching then adopts
  the migrated pages and prefills only the >= 2-row suffix. The old
  copy is released. The slice is journaled by the importing worker,
  so the pages survive ITS crashes independently of the donor.

* **The fault domain boundary** — workers DIE (process kill, detected
  as a dead pipe / failed call) and HANG (no answer inside the
  timeout). A dead worker's in-flight streams are resubmitted to
  survivors from the router's own record (prompt + every delivered
  token + the remaining deadline budget — never a fresh clock); a
  hung worker trips a circuit breaker (suspended, retried with
  exponential backoff, its stale copies released if it returns).
  ``FAILED_OOM`` outcomes auto-resubmit with a bounded retry budget;
  ``REJECTED_ADMISSION`` generalizes across hosts (the router
  delivers it only when EVERY live worker has proven it cannot ever
  serve the request); and when no worker is left the verdict is a
  deterministic terminal ``FAILED_UNROUTABLE`` within the configured
  patience — never a hang. Outcomes are delivered EXACTLY ONCE at
  the router (dedupe by rid across resubmissions and stale copies).

The worker side is ``EngineWorker`` — a thin op dispatcher over a
``RecoverableServer`` — behind either transport:

  ``InProcWorker``   the harness in this process (deterministic
                     storms; a kill abandons the object exactly like
                     a process death abandons its heap)
  ``PipeWorker``     a REAL child process (multiprocessing spawn)
                     speaking length-framed pickles over a pipe; a
                     kill is a real SIGKILL. The honest acceptance
                     rig for the protocol, same router code path.

Determinism: ``RouterFaultInjector`` (resilience.py) schedules kills
and hangs by (router tick, worker, op point), so a kill storm replays
identically; the headline guarantee — surviving streams BIT-IDENTICAL
to a single-engine run, every outcome exactly once, deep invariants
on every surviving pool — is proven in tests/test_router.py.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set

import numpy as np

from .paged_cache import chain_block_hashes
from .recovery import RecoverableServer, RequestJournal, read_journal
from .resilience import EngineCrash, RequestOutcome
from .telemetry import StatsBase

__all__ = ["Router", "RouterStats", "EngineWorker", "InProcWorker",
           "PipeWorker", "WorkerDied", "WorkerTimeout", "WorkerError",
           "build_model_from_spec", "build_server_from_spec",
           "token_chain_hashes"]


class WorkerDied(RuntimeError):
    """The worker process is gone (dead pipe, EngineCrash, injected
    kill): its engine object is unrecoverable from here — the router
    resubmits its in-flight streams to survivors."""


class WorkerTimeout(RuntimeError):
    """The worker did not answer inside the timeout. It MAY still be
    alive (hung, paused, partitioned) and MAY have processed the op —
    the router opens its circuit breaker and treats every copy it
    held as stale until it answers a ping again."""


class WorkerError(RuntimeError):
    """The worker answered with an application error (bad rid, slice
    geometry mismatch, ...). The worker itself is healthy."""


# ---------------------------------------------------------------------
# worker-side harness
# ---------------------------------------------------------------------

def token_chain_hashes(model, token_ids, block_size: int):
    """The chain-hash identity of a token stream as the POOLS compute
    it (hashes are over embedding rows, the serving engines' history
    unit): what a router's ``hash_fn`` should be, built from the same
    ``TokenServingModel`` the workers serve (identical weights =>
    identical hashes — the content address IS the embedded content).
    Returns one hash per FULL block."""
    toks = [int(t) for t in np.asarray(token_ids).reshape(-1)]
    if not toks:
        return []
    return chain_block_hashes(model.embed(toks), block_size)


def build_model_from_spec(spec: dict):
    """The MODEL half of ``build_server_from_spec``: bit-identical
    weights from the spec's seeds alone. Factored out so a fleet
    supervisor can rebuild a dead worker's model for
    ``RecoverableServer.recover`` — recovery needs the weights and the
    on-disk journal/snapshot, never a live object from the dead
    incarnation."""
    import paddle_tpu as paddle
    from ..incubate.nn import FusedMultiTransformer
    from .speculative import TokenServingModel

    paddle.seed(int(spec.get("model_seed", 0)))
    core = FusedMultiTransformer(
        int(spec.get("d_model", 32)), int(spec.get("heads", 4)),
        int(spec.get("ffn", 64)),
        num_layers=int(spec.get("layers", 2)))
    embed = np.random.RandomState(
        int(spec.get("embed_seed", 1234))).randn(
            int(spec.get("vocab", 50)),
            int(spec.get("d_model", 32))).astype(np.float32)
    # head_roll=N reads out against the embedding rolled N rows: the
    # greedy stream then WALKS the vocab instead of collapsing to the
    # tied readout's fixed point (argmax(h E^T) is stationary for a
    # random core) — a constant stream would let a wrong-handoff bug
    # hide inside a bit-identity assertion, a walking one cannot.
    roll = int(spec.get("head_roll", 0))
    head = (np.roll(embed, -roll, axis=0).T.copy() if roll else None)
    return TokenServingModel(core, embed, lm_head=head)


def build_server_from_spec(spec: dict) -> RecoverableServer:
    """Construct a worker's ``RecoverableServer`` from a PICKLABLE,
    data-only spec — the one constructor both transports share, so a
    spawned child process builds bit-identical weights from the same
    seeds the parent (or a single-engine baseline) uses.

    Keys (defaults in parens): model dims ``d_model`` (32), ``heads``
    (4), ``ffn`` (64), ``layers`` (2), ``vocab`` (50), seeds
    ``model_seed`` (0) / ``embed_seed`` (1234), ``head_roll`` (0 —
    see the note at the readout below); engine knobs ``k``
    (0), ``max_batch`` (2), ``block_size`` (4), ``num_blocks`` (60),
    ``max_blocks_per_seq`` (10), ``prefix_cache`` (True),
    ``chunk_tokens``, ``prefill_token_budget``, ``kv_dtype``,
    ``tenants``, ``max_preemptions``; ``monitor`` (False) wires a
    ``HealthMonitor`` (the scrape's health verdict source); host
    knobs ``journal_path`` / ``snapshot_path`` (required) and
    ``snapshot_every`` (0).

    ``recover=True`` (the fleet supervisor's respawn path) rebuilds
    the server FROM ITS FILES instead of fresh: same seeds, then
    ``RecoverableServer.recover`` restores the last snapshot and
    replays the journal suffix — the respawned incarnation holds
    bit-identical state to the dead one at its last journaled round."""
    from .monitor import HealthMonitor
    from .speculative import SpeculativeEngine

    tsm = build_model_from_spec(spec)
    if spec.get("recover"):
        return RecoverableServer.recover(
            tsm, None, journal_path=spec["journal_path"],
            snapshot_path=spec["snapshot_path"],
            monitor=HealthMonitor() if spec.get("monitor") else None)
    eng = SpeculativeEngine(
        tsm, None, k=int(spec.get("k", 0)),
        max_batch=int(spec.get("max_batch", 2)),
        block_size=int(spec.get("block_size", 4)),
        num_blocks=int(spec.get("num_blocks", 60)),
        max_blocks_per_seq=int(spec.get("max_blocks_per_seq", 10)),
        prefix_cache=bool(spec.get("prefix_cache", True)),
        chunk_tokens=spec.get("chunk_tokens"),
        prefill_token_budget=spec.get("prefill_token_budget"),
        kv_dtype=spec.get("kv_dtype", "float32"),
        max_preemptions=spec.get("max_preemptions"),
        tenants=spec.get("tenants"),
        monitor=HealthMonitor() if spec.get("monitor") else None)
    return RecoverableServer(
        eng, journal_path=spec["journal_path"],
        snapshot_path=spec["snapshot_path"],
        snapshot_every=int(spec.get("snapshot_every", 0)))


class EngineWorker:
    """Op dispatcher over one ``RecoverableServer`` — the entire
    worker-side protocol, shared verbatim by the in-process and
    child-process transports. Ops take/return plain picklable dicts:

      submit         {tokens, kw}        -> {rid, emitted, outcomes}
      round          {}                  -> {emitted, outcomes}
      release        {rid}               -> {emitted, outcomes}
      export_slice   {rid}               -> {slice | None}
      import_slice   {slice}             -> {imported}
      export_slices  {rids}              -> {slices: {rid: slice|None}}
      import_slices  {slices}            -> {imported}   (sum; each
                                            slice journals exactly as
                                            one import_slice)
      scrape         {}                  -> placement inputs (prefix
                                            index, pressure, queue,
                                            health report view)
      audit          {}                  -> {ok}   (deep invariants)
      ping           {}                  -> {}
      close          {}                  -> {}     (clean shutdown)

    ``emitted`` is ALWAYS the generated-stream DELTA since the last
    report, not ``step()``'s raw return: the admission-time first
    token never rides a round's return value, so a delta over
    ``generated(rid)`` is the only report that loses nothing."""

    def __init__(self, server: RecoverableServer, *,
                 name: str = "worker", role: str = "mixed"):
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(f"unknown worker role {role!r}")
        self.server = server
        self.name = str(name)
        self.role = role
        self._live: Set[int] = set()
        self._reported: Dict[int, int] = {}

    def _emissions(self) -> Dict[int, List[int]]:
        out: Dict[int, List[int]] = {}
        for rid in sorted(self._live):
            gen = self.server.generated(rid)
            n = self._reported.get(rid, 0)
            if len(gen) > n:
                out[rid] = [int(t) for t in gen[n:]]
                self._reported[rid] = len(gen)
        return out

    def _drain(self) -> List[dict]:
        out = [oc.as_dict() for oc in self.server.drain_outcomes()]
        for oc in out:
            if oc["status"] != RequestOutcome.FINISHED:
                # failed streams stay host-readable until release,
                # but they will never grow: stop polling them
                self._live.discard(oc["rid"])
                self._reported.pop(oc["rid"], None)
        return out

    def _scrape(self) -> dict:
        eng = self.server.engine          # SpeculativeEngine
        core = eng.engine                 # PagedServingEngine
        cache = core.cache
        occ = cache.pool_occupancy(tiers_only=True)
        health = None
        if core.monitor is not None:
            health = core.monitor.report().placement()
        return {
            "name": self.name, "role": self.role,
            "block_size": cache.block_size,
            # the advertised prefix index: every chain hash this pool
            # can adopt (live + cached-free pages). bytes16 per block
            # — a few KB even at production pool sizes.
            "index": list(cache._hash_to_block.keys()),
            "pressure": round(occ["active"] / max(1, occ["usable"]),
                              6),
            "free": occ["free"] + occ["cached_free"],
            "queued": int(core._queue_len),
            "active": int(core.active.sum() + core.prefilling.sum()),
            "health": health,
            "registry": core.registry.scrape(
                ("pool.", "queue.", "spec.acceptance", "journal.")),
        }

    def handle(self, op: str, payload: dict) -> dict:
        srv = self.server
        if op == "submit":
            rid = srv.submit(payload["tokens"],
                             **payload.get("kw", {}))
            self._live.add(rid)
            return {"rid": rid, "emitted": self._emissions(),
                    "outcomes": self._drain()}
        if op == "round":
            srv.step()
            return {"emitted": self._emissions(),
                    "outcomes": self._drain()}
        if op == "release":
            rid = int(payload["rid"])
            self._live.discard(rid)
            self._reported.pop(rid, None)
            srv.release(rid)
            return {"emitted": self._emissions(),
                    "outcomes": self._drain()}
        if op == "export_slice":
            return {"slice": srv.export_slice(int(payload["rid"]))}
        if op == "import_slice":
            return {"imported": srv.import_slice(payload["slice"])}
        if op == "export_slices":
            return {"slices": srv.export_slices(payload["rids"])}
        if op == "import_slices":
            return {"imported": srv.import_slices(payload["slices"])}
        if op == "scrape":
            return self._scrape()
        if op == "audit":
            return {"ok": bool(srv.check_invariants())}
        if op == "ping":
            return {}
        if op == "close":
            srv.close()
            return {}
        raise ValueError(f"unknown worker op {op!r}")


# ---------------------------------------------------------------------
# transports
# ---------------------------------------------------------------------

class WorkerHandle:
    """Transport-neutral face of one worker: ``request`` raises
    ``WorkerDied`` / ``WorkerTimeout`` / ``WorkerError``; ``kill``
    makes death REAL (SIGKILL / abandonment) — it is what the
    injector's scheduled kills call."""

    name: str
    role: str

    def request(self, op: str, payload: Optional[dict] = None,
                timeout: Optional[float] = None) -> dict:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    @property
    def alive(self) -> bool:
        raise NotImplementedError


class InProcWorker(WorkerHandle):
    """The worker harness in THIS process. Deterministic and cheap —
    the transport the seeded kill storms run on. ``kill()`` abandons
    the harness exactly like a process death abandons its heap: the
    object becomes unreachable through this handle, its journal /
    snapshot files stay on disk (forensics, or another incarnation's
    recovery), and every later request raises ``WorkerDied``."""

    def __init__(self, server_or_spec, *, name: str,
                 role: str = "mixed"):
        server = (build_server_from_spec(server_or_spec)
                  if isinstance(server_or_spec, dict)
                  else server_or_spec)
        self.name = str(name)
        self.role = role
        self.worker: Optional[EngineWorker] = EngineWorker(
            server, name=name, role=role)
        self._dead = False

    def request(self, op, payload=None, timeout=None) -> dict:
        if self._dead:
            raise WorkerDied(f"worker {self.name!r} is dead")
        try:
            return self.worker.handle(op, payload or {})
        except EngineCrash as e:
            # PR 6 semantics: an engine that raised EngineCrash is
            # abandoned, so the worker around it is dead
            self.kill()
            raise WorkerDied(
                f"worker {self.name!r} crashed: {e}") from e
        except (WorkerDied, WorkerTimeout):
            raise
        except Exception as e:
            raise WorkerError(f"{type(e).__name__}: {e}") from e

    def kill(self) -> None:
        self._dead = True
        self.worker = None          # abandoned, like a dead heap

    def close(self) -> None:
        if not self._dead:
            try:
                self.worker.handle("close", {})
            finally:
                self._dead = True
                self.worker = None

    @property
    def alive(self) -> bool:
        return not self._dead


def _pipe_worker_main(conn, spec: dict) -> None:
    """Child-process entry (multiprocessing spawn target): build the
    server from the data-only spec, answer framed ops until EOF /
    close / EngineCrash. Never raises out — every application error
    returns as ``{"_err": ...}`` so one bad op cannot kill a healthy
    worker; an ``EngineCrash`` reports ``{"_died": True}`` and exits
    (the engine must be abandoned — that IS a process death)."""
    try:
        worker = EngineWorker(build_server_from_spec(spec),
                              name=spec.get("name", "worker"),
                              role=spec.get("role", "mixed"))
        conn.send({"ready": True})
    except Exception as e:           # surface build failures loudly
        try:
            conn.send({"_err": f"{type(e).__name__}: {e}",
                       "_died": True})
        finally:
            return
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            break
        if msg is None:
            break
        seq, op, payload = msg
        try:
            out = worker.handle(op, payload or {})
        except EngineCrash as e:
            conn.send({"_err": f"EngineCrash: {e}", "_died": True,
                       "_seq": seq})
            break
        except Exception as e:
            out = {"_err": f"{type(e).__name__}: {e}"}
        conn.send(dict(out, _seq=seq))
        if op == "close":
            break


class PipeWorker(WorkerHandle):
    """A REAL worker process (multiprocessing ``spawn`` — a clean
    interpreter, nothing inherited but the spec) speaking the op
    protocol over a duplex pipe. ``kill()`` is a genuine SIGKILL.
    The honest multi-process acceptance rig: same router, same
    protocol, real process death."""

    def __init__(self, spec: dict, *, name: str, role: str = "mixed",
                 timeout: float = 120.0, start_method: str = "spawn",
                 wait_ready: bool = True):
        import multiprocessing as mp
        ctx = mp.get_context(start_method)
        self.name = str(name)
        self.role = role
        self.timeout = float(timeout)
        self._conn, child = ctx.Pipe()
        self.proc = ctx.Process(
            target=_pipe_worker_main,
            args=(child, dict(spec, name=name, role=role)),
            daemon=True)
        self.proc.start()
        child.close()
        self._killed = False
        self._seq = 0
        self._ready = False
        # wait_ready=False returns as soon as the process is spawned
        # (build failure then surfaces at the first request): an
        # N-worker fleet built in a loop overlaps the N model builds
        # instead of paying them sequentially
        if wait_ready:
            self._handshake()

    def _handshake(self) -> None:
        ready = self._recv(self.timeout, want_seq=None)
        if not ready.get("ready"):
            self._killed = True
            raise WorkerDied(f"worker {self.name!r} failed to "
                             f"build: {ready.get('_err')}")
        self._ready = True

    def _recv(self, timeout: float, want_seq) -> dict:
        """Receive the response to op ``want_seq``, DISCARDING stale
        answers: a real timeout abandons an op whose response may
        still arrive later — without the seq check that late answer
        would be read as the NEXT op's reply and every call after it
        would silently receive its predecessor's response (permanent
        protocol desync). ``want_seq=None`` accepts anything (the
        build handshake)."""
        import time as _time
        deadline = _time.monotonic() + timeout
        while True:
            # clamp the poll to the remaining budget: the final poll
            # must fire AT the deadline, not up to 50 ms past it
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise WorkerTimeout(
                    f"worker {self.name!r}: no answer in {timeout}s")
            try:
                if self._conn.poll(min(0.05, remaining)):
                    resp = self._conn.recv()
                    if want_seq is None or \
                            resp.get("_seq") == want_seq:
                        return resp
                    continue              # stale: a timed-out op's
                                          # answer arriving late
            except (EOFError, OSError) as e:
                raise WorkerDied(
                    f"worker {self.name!r} pipe closed: {e}") from e
            if not self.proc.is_alive():
                raise WorkerDied(f"worker {self.name!r} process died "
                                 f"(exitcode {self.proc.exitcode})")

    def request(self, op, payload=None, timeout=None) -> dict:
        if self._killed or not self.proc.is_alive():
            raise WorkerDied(f"worker {self.name!r} is dead")
        if not self._ready:
            self._handshake()       # deferred-build handshake
        self._seq += 1
        try:
            self._conn.send((self._seq, op, payload or {}))
        except (BrokenPipeError, OSError) as e:
            raise WorkerDied(
                f"worker {self.name!r} pipe broken: {e}") from e
        resp = self._recv(timeout if timeout is not None
                          else self.timeout, want_seq=self._seq)
        resp.pop("_seq", None)
        if resp.get("_died"):
            self._killed = True
            raise WorkerDied(f"worker {self.name!r}: {resp['_err']}")
        if "_err" in resp:
            raise WorkerError(resp["_err"])
        return resp

    def kill(self) -> None:
        self._killed = True
        if self.proc.is_alive():
            self.proc.kill()        # SIGKILL — a real process death
        self.proc.join(timeout=10)

    def close(self) -> None:
        if not self._killed and self.proc.is_alive():
            try:
                self.request("close", timeout=self.timeout)
            except (WorkerDied, WorkerTimeout, WorkerError):
                pass
        if self.proc.is_alive():
            self.proc.terminate()
        self.proc.join(timeout=10)
        self._killed = True

    @property
    def alive(self) -> bool:
        return not self._killed and self.proc.is_alive()


# ---------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------

class RouterStats(StatsBase):
    """Router-surface accounting, sibling of the engine stats.

      submitted          client submissions accepted (rid handed out)
      delivered          terminal outcomes delivered (exactly once)
      placed_prefix      placements won by a prefix-index match
      placed_fresh       placements by role/load (no match anywhere)
      spillovers         best-match worker over-pressure -> placed on
                         a cooler worker instead
      migrations         streams moved prefill -> decode worker
      migrated_blocks    pages imported by migration targets
      export_batches     batched export_slices ops issued (one per
                         donor per tick, N slots per slice — the
                         round-trip saving is migrations minus this)
      resubmissions      streams re-placed after a worker failure
      oom_resubmissions  FAILED_OOM outcomes retried elsewhere
      worker_deaths      workers detected dead
      worker_timeouts    calls that timed out (circuit-breaker opens)
      stale_released     stale copies released on a worker's rejoin
      unroutable         FAILED_UNROUTABLE verdicts delivered
      respawns           dead workers re-registered by a supervisor
                         (``register_respawn``)
      rebalances         policy-approved migrations, journaled as
                         "rebalance" records ``Router.recover``
                         replays (0 with no policy — pre-fleet
                         journals stay byte-identical)
      migrations_skipped streams a ``MigrationPolicy`` priced and
                         declined to move (zero slice bytes shipped)
      net_reconnects     session-transport reconnects the router has
                         OBSERVED via ``handle.net_stats()`` (the
                         degraded-state trigger; 0 on raw transports)
      degraded_transitions  up -> degraded transitions: a worker rode
                         out a network fault WITHOUT resubmission —
                         the cheap failure the session layer buys
    """

    __slots__ = FIELDS = (
        "submitted", "delivered", "placed_prefix", "placed_fresh",
        "spillovers", "migrations", "migrated_blocks",
        "export_batches",
        "resubmissions", "oom_resubmissions", "worker_deaths",
        "worker_timeouts", "stale_released", "unroutable",
        "respawns", "rebalances", "migrations_skipped",
        "net_reconnects", "degraded_transitions")
    REPR = ("submitted", "delivered", "migrations", "resubmissions",
            "worker_deaths", "unroutable")


class _RouterReq:
    """The router's own record of one client stream — the resubmission
    source of truth (prompt, every token delivered so far, remaining
    budgets). ``steps_used`` counts worker rounds the stream was
    assigned through: the deadline budget a resubmission carries is
    ``deadline_steps - steps_used``, REMAINING — a retry must never
    reset the clock."""

    __slots__ = ("rid", "tokens", "generated", "tenant_id",
                 "max_preemptions", "deadline_steps", "max_new_tokens",
                 "steps_used", "resubmissions", "oom_retries",
                 "worker", "wrid", "terminal", "status")

    def __init__(self, rid: int, tokens: List[int], *,
                 tenant_id=None, max_preemptions=None,
                 deadline_steps=None, max_new_tokens=None,
                 oom_retries: int = 0):
        self.rid = rid
        self.tokens = list(tokens)
        self.generated: List[int] = []
        self.tenant_id = tenant_id
        self.max_preemptions = max_preemptions
        self.deadline_steps = deadline_steps
        self.max_new_tokens = max_new_tokens
        self.steps_used = 0
        self.resubmissions = 0
        self.oom_retries = oom_retries
        self.worker: Optional[str] = None
        self.wrid: Optional[int] = None
        self.terminal = False
        self.status: Optional[str] = None


class _WorkerState:
    __slots__ = ("handle", "name", "role", "order", "status",
                 "backoff", "retry_at", "assigned", "by_rid", "stale",
                 "index", "pressure", "queued", "active", "health",
                 "respawned", "net_session", "net_mark",
                 "degraded_until")

    def __init__(self, handle: WorkerHandle, order: int,
                 backoff: int):
        self.handle = handle
        self.name = handle.name
        self.role = handle.role
        self.order = order
        self.status = "up"            # up | degraded | suspect | dead
        self.backoff = backoff
        self.retry_at = 0
        self.assigned: Dict[int, int] = {}    # worker rid -> client rid
        self.by_rid: Dict[int, int] = {}      # client rid -> worker rid
        self.stale: Set[int] = set()          # worker rids to release
        self.index: Set[bytes] = set()
        self.pressure = 0.0
        self.queued = 0
        self.active = 0
        self.health: Optional[dict] = None
        # set by Router.register_respawn: this incarnation was rebuilt
        # by a supervisor and its first successful ping IS the rejoin
        # (journaled so a WAL reader can pair spawn <-> rejoin)
        self.respawned = False
        # session-transport bookkeeping (_net_pass): whether the
        # handle's session has been journaled, the reconnect counter
        # high-water mark already accounted for, and the tick at
        # which a degraded worker (riding out a reconnect — streams
        # NOT resubmitted, copies NOT released) is promoted back up
        self.net_session = False
        self.net_mark = 0
        self.degraded_until = 0

    @property
    def load(self):
        return (self.queued + self.active, self.pressure)


class Router:
    """See the module docstring. Client surface mirrors the engines:
    ``submit(tokens, ...) -> rid``; ``step() -> {rid: [tokens]}`` (one
    router TICK: suspect retries, scrapes, migrations, then one round
    on every busy worker); ``drain_outcomes()`` — terminal verdicts,
    exactly once; ``tokens``/``generated`` from the router's own
    record; ``release(rid)``; ``close()``.

      workers             list of WorkerHandle (unique names)
      hash_fn             tokens -> chain hashes (see
                          ``token_chain_hashes``); None disables
                          prefix-aware placement (role/load only)
      injector            RouterFaultInjector (tests/benches)
      journal_path        the router's OWN WAL (submissions,
                          emissions, deliveries): ``Router.recover``
                          rebuilds the request table from it and
                          resubmits every non-terminal stream —
                          journal-backed resubmission survives the
                          ROUTER process too
      migrate             move streams off prefill-role workers onto
                          decode-role workers once their prefill is
                          done (needs both roles present)
      policy              MigrationPolicy (inference/fleet.py): price
                          each candidate move — remaining work x
                          pressure delta vs slice-transfer cost —
                          BEFORE any export op, so a skipped move
                          ships zero slice bytes. None (default)
                          keeps the unconditional
                          every-finished-prefill behaviour
      max_oom_resubmissions  FAILED_OOM retries per request before
                          the failure is delivered
      max_resubmissions   worker-failure resubmissions per request
                          before FAILED_UNROUTABLE
      unroutable_after    ticks a request may sit unplaceable (all
                          workers suspect/full) before the
                          deterministic FAILED_UNROUTABLE verdict
      backoff_ticks/backoff_max  circuit-breaker retry schedule for
                          suspect workers (exponential, capped)
      degraded_ticks      ticks a worker stays in the ``degraded``
                          state after its session transport reports a
                          reconnect with no NEW reconnects — degraded
                          workers keep serving their streams (nothing
                          is resubmitted or released; the WorkerDied
                          machinery engages only on real death) but
                          are folded into the hot set so NEW
                          placements prefer calmer workers, and they
                          neither donate nor receive migrations
      spill_pressure      pool-pressure fraction above which a
                          best-match / best-role worker is passed
                          over for a cooler one
      call_timeout        per-op transport timeout (pipes)
    """

    def __init__(self, workers, *, hash_fn: Optional[Callable] = None,
                 injector=None, journal_path: Optional[str] = None,
                 migrate: bool = True, policy=None,
                 max_oom_resubmissions: int = 2,
                 max_resubmissions: int = 4,
                 unroutable_after: int = 4,
                 backoff_ticks: int = 2, backoff_max: int = 16,
                 degraded_ticks: int = 2,
                 spill_pressure: float = 0.92,
                 scrape_every: int = 1,
                 call_timeout: float = 120.0,
                 _fresh: bool = True):
        if not workers:
            raise ValueError("a router needs at least one worker")
        names = [w.name for w in workers]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate worker names: {names}")
        self._workers: Dict[str, _WorkerState] = {
            w.name: _WorkerState(w, i, backoff_ticks)
            for i, w in enumerate(workers)}
        self.hash_fn = hash_fn
        self.injector = injector
        self.migrate = migrate
        self.policy = policy
        self.max_oom_resubmissions = int(max_oom_resubmissions)
        self.max_resubmissions = int(max_resubmissions)
        self.unroutable_after = int(unroutable_after)
        self.backoff_ticks = int(backoff_ticks)
        self.backoff_max = int(backoff_max)
        self.degraded_ticks = int(degraded_ticks)
        self.spill_pressure = float(spill_pressure)
        self.scrape_every = int(scrape_every)
        self.call_timeout = float(call_timeout)
        self.stats = RouterStats()
        self.tick = 0
        self.outcomes: List[RequestOutcome] = []
        self._reqs: Dict[int, _RouterReq] = {}
        self._delivered: Set[int] = set()
        self._pending: Dict[int, int] = {}     # rid -> tick queued
        self._emit_buffer: Dict[int, List[int]] = {}
        # outcomes handed to the client but not yet journaled: the
        # drain record is written at the START of the next router
        # call (the RecoverableServer recipe) — a router death
        # between calls leaves them unjournaled and recover()
        # RE-DELIVERS them to the rebuilt client, never loses them
        self._pending_drain: List[list] = []
        self._tick_stepped: Set[int] = set()
        self._next_rid = 0
        self.journal: Optional[RequestJournal] = None
        if journal_path is not None:
            self.journal = RequestJournal(journal_path, fresh=_fresh)
        self._scrape_pass(force=True)

    # -- plumbing -----------------------------------------------------
    def _jrec(self, kind: str, payload: dict) -> None:
        if self.journal is not None:
            self.journal.append(kind, payload)

    def _flush_drains(self) -> None:
        """Journal the verdicts the client has ALREADY drained —
        written at the start of the next call, not at drain time, so
        a death between calls re-delivers (the caller that held them
        died with the router) while a verdict journaled here can
        never deliver twice."""
        if self.journal is not None and self._pending_drain:
            self.journal.append("delivered",
                                {"rids": self._pending_drain})
            self._pending_drain = []

    def _op(self, ws: _WorkerState, op: str,
            payload: Optional[dict] = None,
            point: Optional[str] = None) -> dict:
        """One worker call behind the injector's kill/hang verdicts —
        the router-level crash points."""
        inj = self.injector
        if inj is not None and point is not None:
            v = inj.on_worker_op(ws.name, point)
            if v == "kill":
                ws.handle.kill()
                raise WorkerDied(f"worker {ws.name!r} killed by "
                                 f"injector at {point!r}")
            if v == "hang":
                raise WorkerTimeout(f"worker {ws.name!r} hung at "
                                    f"{point!r} (injected)")
        return ws.handle.request(op, payload or {},
                                 timeout=self.call_timeout)

    def _live(self) -> List[_WorkerState]:
        # degraded workers ARE live: they keep their streams and
        # serve their rounds — the state only biases NEW placement
        # and migration away from them while the network settles
        return [ws for ws in self._workers.values()
                if ws.status in ("up", "degraded")]

    def _all_dead(self) -> bool:
        return all(ws.status == "dead"
                   for ws in self._workers.values())

    # -- client surface -----------------------------------------------
    def submit(self, token_ids, *, max_new_tokens: Optional[int] = None,
               tenant_id: Optional[str] = None,
               deadline_steps: Optional[int] = None,
               max_preemptions: Optional[int] = None) -> int:
        """Accept a client stream and place it. Always returns a rid;
        every verdict — including rejection and unroutability — is a
        terminal outcome in ``drain_outcomes()``, never an exception
        (malformed submissions still raise, like the engines)."""
        toks = [int(t) for t in np.asarray(token_ids).reshape(-1)]
        if not toks:
            raise ValueError("empty prompt")
        self._flush_drains()
        rid = self._next_rid
        self._next_rid += 1
        req = _RouterReq(rid, toks, tenant_id=tenant_id,
                         max_preemptions=max_preemptions,
                         deadline_steps=deadline_steps,
                         max_new_tokens=max_new_tokens,
                         oom_retries=self.max_oom_resubmissions)
        self._reqs[rid] = req
        self.stats.submitted += 1
        self._jrec("submit", {
            "rid": rid, "tokens": toks,
            "kw": {"tenant_id": tenant_id,
                   "deadline_steps": deadline_steps,
                   "max_preemptions": max_preemptions,
                   "max_new_tokens": max_new_tokens}})
        self._try_place(req)
        return rid

    def step(self) -> Dict[int, List[int]]:
        """One router tick. Order: tick the injector clock, retry
        suspended workers, settle degraded session transports
        (``_net_pass``), scrape placement inputs, retry unplaced
        streams (or give the deterministic unroutable verdict),
        migrate finished prefills, then drive ONE round on every
        worker holding streams. Returns {rid: [tokens]} — every token
        delivered this tick (including admission tokens from
        placements that happened inside the tick)."""
        self._flush_drains()
        self.tick += 1
        if self.injector is not None:
            self.injector.begin_tick()
        self._retry_suspects()
        self._net_pass()
        self._scrape_pass()
        self._pending_pass()
        if self.migrate:
            self._migrate_pass()
        self._round_pass()
        if self._tick_stepped:
            # the deadline ledger: WHICH streams consumed a round
            # this tick (emissions alone undercount — prefill rounds
            # and worker-queued rounds emit nothing but still spend
            # deadline budget), so recover() rebuilds steps_used
            # exactly instead of guessing from emissions
            self._jrec("tick",
                       {"stepped": sorted(self._tick_stepped)})
            self._tick_stepped = set()
        out = self._emit_buffer
        self._emit_buffer = {}
        return out

    def drain_outcomes(self) -> List[RequestOutcome]:
        """Terminal verdicts not yet handed out — the exactly-once
        edge. The drain record reaches the journal at the start of
        the NEXT router call; see _flush_drains."""
        self._flush_drains()
        out = self.outcomes
        self.outcomes = []
        if out:
            self._pending_drain.extend(
                [oc.rid, oc.status] for oc in out)
        return out

    def tokens(self, rid: int) -> List[int]:
        req = self._reqs[rid]
        return list(req.tokens) + list(req.generated)

    def generated(self, rid: int) -> List[int]:
        req = self._reqs[rid]
        out = list(req.generated)
        if req.max_new_tokens is not None:
            out = out[:req.max_new_tokens]
        return out

    def release(self, rid: int) -> None:
        """Client-side finish: free the stream's worker copy and
        deliver its FINISHED outcome (exactly once)."""
        self._flush_drains()
        req = self._reqs[rid]
        self._jrec("release", {"rid": rid})
        self._release_copy(req)
        if not req.terminal:
            self._deliver(req, RequestOutcome.FINISHED, "released")

    def check_invariants(self) -> bool:
        """Deep pool + engine audit on every live worker."""
        for ws in self._live():
            assert self._op(ws, "audit")["ok"]
        return True

    def close(self) -> None:
        self._flush_drains()
        for ws in self._workers.values():
            try:
                ws.handle.close()
            except (WorkerDied, WorkerTimeout, WorkerError):
                pass
        if self.journal is not None:
            self.journal.close()

    # -- recovery (router journal) ------------------------------------
    @classmethod
    def recover(cls, workers, *, journal_path: str,
                **router_kw) -> "Router":
        """Rebuild a router from its own journal after the ROUTER
        process died: the request table (prompt + delivered tokens +
        verdicts) replays from the WAL, then every non-terminal
        stream is resubmitted on the next ``step()`` from its
        recorded frontier — the same pending-token resume handoff a
        worker death takes, so recovered streams continue
        bit-identically. Exactly-once holds across the router's own
        death in BOTH directions: verdicts the dead router's client
        DRAINED stay delivered (the drain record replays into the
        dedupe set), while verdicts enqueued but never drained were
        never journaled — the rebuilt router re-derives and
        RE-delivers them (already-complete streams immediately, the
        rest through resubmission). ``steps_used`` replays exactly
        from the per-tick "tick" records (which streams consumed a
        round), so deadline budgets stay spent, not reset."""
        records = read_journal(journal_path)
        router = cls(workers, journal_path=journal_path,
                     _fresh=False, **router_kw)
        for seq, kind, payload in records:
            if kind == "submit":
                kw = payload["kw"]
                req = _RouterReq(
                    payload["rid"], payload["tokens"],
                    tenant_id=kw.get("tenant_id"),
                    max_preemptions=kw.get("max_preemptions"),
                    deadline_steps=kw.get("deadline_steps"),
                    max_new_tokens=kw.get("max_new_tokens"),
                    oom_retries=router.max_oom_resubmissions)
                router._reqs[req.rid] = req
                router._next_rid = max(router._next_rid, req.rid + 1)
                router.stats.submitted += 1
            elif kind == "emit":
                req = router._reqs.get(payload["rid"])
                if req is not None:
                    req.generated.extend(int(t)
                                         for t in payload["toks"])
            elif kind == "tick":
                for rid in payload["stepped"]:
                    req = router._reqs.get(rid)
                    if req is not None:
                        req.steps_used += 1
            elif kind == "delivered":
                for rid, status in payload["rids"]:
                    req = router._reqs.get(rid)
                    if req is not None:
                        req.terminal = True
                        req.status = status
                        router._delivered.add(rid)
            elif kind == "release":
                req = router._reqs.get(payload["rid"])
                if req is not None and not req.terminal:
                    req.terminal = True
                    req.status = RequestOutcome.FINISHED
                    router._delivered.add(req.rid)
            elif kind == "respawn":
                # fleet lifecycle (supervisor spawn / circuit-breaker
                # rejoin pairs): placement is per-incarnation — the
                # rebuilt router starts from the workers it was GIVEN
                # — but the respawn count replays so capacity history
                # survives the router's own death
                if payload.get("event") == "spawn":
                    router.stats.respawns += 1
            elif kind == "rebalance":
                # policy/migration decisions replay into the ledger
                # deterministically; the moves themselves are
                # per-incarnation (the recovered streams resubmit
                # through the normal placement pass)
                router.stats.rebalances += 1
            elif kind == "net":
                # session-transport lane (reconnects and degraded
                # transitions): the worker states themselves are
                # per-incarnation — a rebuilt router starts from the
                # handles it was given — but the counters replay so
                # the flapping history survives the router's death
                if payload.get("event") == "reconnect":
                    router.stats.net_reconnects += \
                        int(payload.get("n", 1))
                elif payload.get("event") == "degraded":
                    router.stats.degraded_transitions += 1
        for req in router._reqs.values():
            if req.terminal:
                continue
            if req.max_new_tokens is not None and \
                    len(req.generated) >= req.max_new_tokens:
                # the stream is complete but its verdict was never
                # drained pre-death: the RE-delivery half of
                # exactly-once (the worker copy, if any survives, is
                # unknown to this incarnation and ages out with its
                # worker — a respawned fleet starts clean)
                router._deliver(req, RequestOutcome.FINISHED,
                                "max_new_tokens (recovered)")
            else:
                router._pending[req.rid] = router.tick
        return router

    # -- placement ----------------------------------------------------
    def _hashes_for(self, req: _RouterReq) -> List[bytes]:
        if self.hash_fn is None:
            return []
        stream = list(req.tokens) + list(req.generated)
        # hash what the TARGET worker will prefill: on a resume
        # handoff the pending token is not consumed at admission
        if req.generated:
            stream = stream[:-1]
        return list(self.hash_fn(stream))

    def _match_len(self, ws: _WorkerState,
                   hashes: List[bytes]) -> int:
        n = 0
        for h in hashes:
            if h not in ws.index:
                break
            n += 1
        return n

    def _hot(self, ws: _WorkerState) -> bool:
        if ws.status == "degraded":
            return True               # flapping network: place cooler
        if ws.pressure >= self.spill_pressure:
            return True
        h = ws.health
        return bool(h) and h.get("verdict") == "critical"

    def _choose(self, req: _RouterReq, hashes: List[bytes],
                tried: Set[str]) -> Optional[_WorkerState]:
        cands = [ws for ws in self._live() if ws.name not in tried]
        if not cands:
            return None
        by_match = sorted(
            cands, key=lambda ws: (-self._match_len(ws, hashes),
                                   ws.load, ws.order))
        best = by_match[0]
        if hashes and self._match_len(best, hashes) > 0:
            if self._hot(best):
                cool = [ws for ws in cands if not self._hot(ws)]
                if cool:
                    self.stats.spillovers += 1
                    return sorted(
                        cool,
                        key=lambda ws: (-self._match_len(ws, hashes),
                                        ws.load, ws.order))[0]
            return best
        # no prefix anywhere: fresh prompts want prefill capacity,
        # resumed streams want decode capacity
        pref = (("decode", "mixed", "prefill") if req.generated
                else ("prefill", "mixed", "decode"))
        rank = {r: i for i, r in enumerate(pref)}
        cool = [ws for ws in cands if not self._hot(ws)] or cands
        return sorted(cool, key=lambda ws: (rank.get(ws.role, 1),
                                            ws.load, ws.order))[0]

    def _submit_kw(self, req: _RouterReq, resume: bool) -> dict:
        kw: dict = {}
        if req.tenant_id is not None:
            kw["tenant_id"] = req.tenant_id
        if req.max_preemptions is not None:
            kw["max_preemptions"] = req.max_preemptions
        if req.deadline_steps is not None:
            # REMAINING budget only — rebased like a snapshot
            # restore's wall-clock deadlines, never a fresh clock
            kw["deadline_steps"] = (req.deadline_steps
                                    - req.steps_used)
        if resume:
            kw["resume"] = True
        return kw

    def _place_and_submit(self, req: _RouterReq,
                          exclude: Set[str] = frozenset()) -> str:
        """Try to place one stream: "placed", "rejected" (every live
        worker PROVED it can never serve it), or "none" (no live
        candidate took it)."""
        hashes = self._hashes_for(req)
        tried: Set[str] = set(exclude)
        rejections: List[str] = []
        resume = bool(req.generated)
        payload = {"tokens": list(req.tokens) + list(req.generated),
                   "kw": self._submit_kw(req, resume)}
        while True:
            ws = self._choose(req, hashes, tried)
            if ws is None:
                break
            tried.add(ws.name)
            try:
                resp = self._op(ws, "submit", payload, point="submit")
            except WorkerDied:
                self._on_worker_failure(ws, died=True)
                continue
            except WorkerTimeout:
                self._on_worker_failure(ws, died=False)
                continue
            wrid = int(resp["rid"])
            ws.assigned[wrid] = req.rid
            ws.by_rid[req.rid] = wrid
            req.worker, req.wrid = ws.name, wrid
            rej = self._process_response(ws, resp,
                                         intercept_rid=req.rid)
            if rej is not None:
                rejections.append(f"{ws.name}: {rej.get('reason')}")
                continue
            if hashes and self._match_len(ws, hashes) > 0:
                self.stats.placed_prefix += 1
            else:
                self.stats.placed_fresh += 1
            return "placed"
        if rejections and not exclude and \
                not any(ws.status == "suspect"
                        for ws in self._workers.values()):
            # the loop tried every live worker (only rejections come
            # back here — an acceptance returned above), none is
            # merely suspended, and dead workers can never serve: the
            # refusal is PROVEN fleet-wide, the cross-host
            # REJECTED_ADMISSION
            return "rejected"
        return "none"

    def _try_place(self, req: _RouterReq) -> None:
        """Place (or queue, or terminally fail) one unassigned
        stream."""
        if req.terminal:
            return
        if req.deadline_steps is not None and \
                req.deadline_steps - req.steps_used <= 0:
            self._deliver(req, RequestOutcome.FAILED_DEADLINE,
                          "deadline budget exhausted across "
                          "resubmission")
            return
        verdict = self._place_and_submit(req)
        if verdict == "placed":
            self._pending.pop(req.rid, None)
            return
        if verdict == "rejected":
            self._deliver(
                req, RequestOutcome.REJECTED_ADMISSION,
                "no worker can ever serve this request under its "
                "current tenant/pool contracts")
            return
        if self._all_dead():
            self._deliver(req, RequestOutcome.FAILED_UNROUTABLE,
                          "all workers down")
            return
        self._pending.setdefault(req.rid, self.tick)

    # -- failure domain -----------------------------------------------
    def _on_worker_failure(self, ws: _WorkerState,
                           died: bool) -> None:
        """A worker stopped answering: dead (resubmit everything) or
        hung (suspend behind the circuit breaker, treat its copies as
        stale, resubmit everything)."""
        if ws.status == "dead":
            return
        moved = sorted(set(ws.assigned.values()))
        if died:
            ws.status = "dead"
            self.stats.worker_deaths += 1
            # a respawned incarnation's journal replay rebuilds these
            # very copies — stale-marked NOW so the rejoin ping
            # releases them (their streams are resubmitted elsewhere
            # below); workers that never come back simply keep an
            # inert stale set
            ws.stale.update(ws.assigned.keys())
            try:
                ws.handle.kill()
            except Exception:
                pass
        else:
            ws.status = "suspect"
            self.stats.worker_timeouts += 1
            ws.retry_at = self.tick + ws.backoff
            ws.backoff = min(ws.backoff * 2, self.backoff_max)
            # the hung worker may still hold (and grow) its copies:
            # stale from here — released if it ever answers again
            ws.stale.update(ws.assigned.keys())
        ws.assigned.clear()
        ws.by_rid.clear()
        for rid in moved:
            req = self._reqs[rid]
            if req.terminal:
                continue
            req.worker = req.wrid = None
            req.resubmissions += 1
            self.stats.resubmissions += 1
            if req.resubmissions > self.max_resubmissions:
                self._deliver(req, RequestOutcome.FAILED_UNROUTABLE,
                              f"resubmission budget "
                              f"({self.max_resubmissions}) exhausted")
                continue
            self._try_place(req)

    def register_respawn(self, name: str, handle) -> None:
        """A supervisor rebuilt a DEAD worker (same name, fresh
        process/handle, state recovered from its journal+snapshot):
        swap the handle in and route the incarnation through the
        circuit-breaker rejoin path — suspect first, pinged next
        tick, stale copies (journal-replayed duplicates of streams
        already resubmitted elsewhere) released at rejoin. The router
        never trusts a respawn blindly: a corpse that cannot answer
        the rejoin ping goes straight back to dead."""
        ws = self._workers.get(name)
        if ws is None:
            raise KeyError(f"unknown worker {name!r}")
        if ws.status != "dead":
            raise ValueError(f"worker {name!r} is {ws.status!r}, not "
                             f"dead — respawn replaces corpses only")
        ws.handle = handle
        ws.status = "suspect"
        ws.backoff = self.backoff_ticks
        ws.retry_at = self.tick + 1
        ws.respawned = True
        # scraped placement signals are from the dead incarnation:
        # zero them until the rejoined worker is scraped for real
        ws.index = set()
        ws.pressure = 0.0
        ws.queued = ws.active = 0
        ws.health = None
        self.stats.respawns += 1
        self._jrec("respawn", {"worker": name, "event": "spawn",
                               "tick": self.tick})

    def _retry_suspects(self) -> None:
        for ws in self._workers.values():
            if ws.status != "suspect" or self.tick < ws.retry_at:
                continue
            try:
                self._op(ws, "ping", point="ping")
            except WorkerDied:
                ws.status = "dead"
                self.stats.worker_deaths += 1
                continue
            except WorkerTimeout:
                ws.retry_at = self.tick + ws.backoff
                ws.backoff = min(ws.backoff * 2, self.backoff_max)
                continue
            # the circuit closes: the worker is back, but every copy
            # it held was resubmitted elsewhere — release the stale
            # ones so they stop consuming its pool
            ws.status = "up"
            ws.backoff = self.backoff_ticks
            if ws.respawned:
                # a supervisor-rebuilt incarnation just answered its
                # first ping: THIS is the rejoin — journaled so the
                # WAL pairs it with the earlier "spawn" record
                ws.respawned = False
                self._jrec("respawn", {"worker": ws.name,
                                       "event": "rejoin",
                                       "tick": self.tick})
            self._release_stale(ws)

    def _net_pass(self) -> None:
        """Poll each live handle's session-transport counters
        (``net_stats`` — {} or absent on raw transports: this pass is
        DARK without the session layer). A reconnect since the last
        look marks the worker ``degraded`` for ``degraded_ticks``:
        its streams stay put and its copies stay held — a network
        blip must not engage the resubmission machinery — but new
        placement and migration route around it until it holds a
        quiet transport for the full window. Transitions and
        reconnect deltas are journaled as "net" records so a WAL
        reader (tools/fleet_doctor.py) can audit the lane and
        ``Router.recover`` replays the counters."""
        for name in sorted(self._workers):
            ws = self._workers[name]
            if ws.status not in ("up", "degraded"):
                continue
            fn = getattr(ws.handle, "net_stats", None)
            if fn is None:
                continue
            d = fn()
            if not d:
                continue
            if not ws.net_session:
                ws.net_session = True
                self._jrec("net", {"worker": ws.name,
                                   "event": "session",
                                   "tick": self.tick})
            rec = int(d.get("reconnects", 0))
            if rec > ws.net_mark:
                delta = rec - ws.net_mark
                ws.net_mark = rec
                self.stats.net_reconnects += delta
                ws.degraded_until = self.tick + self.degraded_ticks
                self._jrec("net", {"worker": ws.name,
                                   "event": "reconnect", "n": delta,
                                   "tick": self.tick})
                if ws.status == "up":
                    ws.status = "degraded"
                    self.stats.degraded_transitions += 1
                    self._jrec("net", {"worker": ws.name,
                                       "event": "degraded",
                                       "tick": self.tick})
            elif ws.status == "degraded" and \
                    self.tick >= ws.degraded_until:
                ws.status = "up"
                self._jrec("net", {"worker": ws.name,
                                   "event": "recovered",
                                   "tick": self.tick})

    def _release_stale(self, ws: _WorkerState) -> None:
        for wrid in sorted(ws.stale):
            try:
                resp = self._op(ws, "release", {"rid": int(wrid)})
                self._process_response(ws, resp)
                self.stats.stale_released += 1
            except WorkerError:
                pass                  # already gone worker-side
            except WorkerDied:
                self._on_worker_failure(ws, died=True)
                return
            except WorkerTimeout:
                self._on_worker_failure(ws, died=False)
                return
            ws.stale.discard(wrid)

    # -- scrape / pending / migration / rounds ------------------------
    def _scrape_pass(self, force: bool = False) -> None:
        if not force and self.scrape_every > 1 and \
                self.tick % self.scrape_every:
            return
        for ws in self._live():
            try:
                resp = self._op(ws, "scrape", point="scrape")
            except WorkerDied:
                self._on_worker_failure(ws, died=True)
                continue
            except WorkerTimeout:
                self._on_worker_failure(ws, died=False)
                continue
            except WorkerError:
                # a worker dying BETWEEN the ping and the scrape can
                # surface as a transport-wrapped application error
                # (half-dead harness, torn response) rather than a
                # clean WorkerDied — it must NOT escape into the
                # placement pass. Scrape is a pure read, so the
                # circuit breaker owns the verdict: suspect now, and
                # the rejoin ping resolves dead-vs-alive next tick.
                self._on_worker_failure(ws, died=False)
                continue
            ws.index = set(resp.get("index", ()))
            ws.pressure = float(resp.get("pressure", 0.0))
            ws.queued = int(resp.get("queued", 0))
            ws.active = int(resp.get("active", 0))
            ws.health = resp.get("health")

    def _pending_pass(self) -> None:
        for rid, since in sorted(self._pending.items()):
            req = self._reqs[rid]
            if req.terminal:
                self._pending.pop(rid, None)
                continue
            if self._all_dead():
                self._pending.pop(rid, None)
                self._deliver(req, RequestOutcome.FAILED_UNROUTABLE,
                              "all workers down")
                continue
            if self._live():
                self._try_place(req)
                if req.worker is not None or req.terminal:
                    continue
            if self.tick - since >= self.unroutable_after:
                self._pending.pop(rid, None)
                self._deliver(
                    req, RequestOutcome.FAILED_UNROUTABLE,
                    f"unplaceable for {self.unroutable_after} "
                    f"tick(s) (workers suspended or full)")

    def _migrate_pass(self) -> None:
        """SLICE-BATCHED migration: per donor (prefill worker) per
        tick, ONE ``export_slices`` op ships every finished-prefill
        slot's pages (N slots, one round trip — not one export per
        slot), destinations are chosen per stream exactly as before,
        the slices bound for each destination land as ONE
        ``import_slices`` op, and only then do the per-stream
        resume-submit handoffs run. Failure semantics are unchanged
        from the per-slot pass: a donor lost at export resubmits its
        streams cold; a target lost at import leaves ITS streams on
        the donor (other destinations and the remaining donors still
        migrate the same tick); a target lost at a
        handoff leaves the remaining streams on the donor for the
        next tick."""
        targets = [ws for ws in self._live() if ws.role == "decode"
                   and not self._hot(ws)]
        if not targets:
            return
        for src in [ws for ws in self._live()
                    if ws.role == "prefill"
                    and ws.status == "up"]:
            moved = [(wrid, rid) for wrid, rid
                     in sorted(src.assigned.items())
                     if not self._reqs[rid].terminal
                     and self._reqs[rid].generated]
            if not moved:
                continue
            if self.policy is not None:
                # price every candidate BEFORE the export op: a
                # declined move never ships a byte. The benefit side
                # is the stream's remaining decode work weighted by
                # the scraped pressure delta toward the coolest live
                # target (the same worker the per-stream choice below
                # would pick this tick).
                live_targets = [ws for ws in targets
                                if ws.status == "up"]
                if not live_targets:
                    return
                dst0 = sorted(live_targets,
                              key=lambda ws: (ws.load, ws.order))[0]
                priced = []
                for wrid, rid in moved:
                    req = self._reqs[rid]
                    pos = len(req.tokens) + len(req.generated)
                    rem = (None if req.max_new_tokens is None else
                           req.max_new_tokens - len(req.generated))
                    if self.policy.should_move(
                            position=pos, remaining=rem,
                            src_pressure=src.pressure,
                            dst_pressure=dst0.pressure):
                        priced.append((wrid, rid))
                    else:
                        self.stats.migrations_skipped += 1
                moved = priced
                if not moved:
                    continue
            # one export per donor per tick — the whole batch of
            # finished prefills rides a single round trip
            try:
                slices = self._op(
                    src, "export_slices",
                    {"rids": [int(w) for w, _ in moved]},
                    point="export").get("slices", {})
            except WorkerDied:
                self._on_worker_failure(src, died=True)
                continue
            except WorkerTimeout:
                self._on_worker_failure(src, died=False)
                continue
            self.stats.export_batches += 1
            # destination per stream (the same least-loaded choice
            # the per-slot pass made), then one import per chosen
            # destination carrying all its slices
            plan: List[tuple] = []      # (wrid, rid, slice, dst)
            by_dst: Dict[str, List[dict]] = {}
            for wrid, rid in moved:
                live_targets = [ws for ws in targets
                                if ws.status == "up"]
                if not live_targets:
                    return
                dst = sorted(live_targets,
                             key=lambda ws: (ws.load, ws.order))[0]
                slc = slices.get(int(wrid))
                plan.append((wrid, rid, slc, dst))
                if slc is not None:
                    by_dst.setdefault(dst.name, []).append(slc)
            for dname, batch in by_dst.items():
                dst = self._workers[dname]
                try:
                    got = self._op(dst, "import_slices",
                                   {"slices": batch},
                                   point="import")
                    self.stats.migrated_blocks += int(
                        got.get("imported", 0))
                except WorkerDied:
                    # this target's streams stay on the donor (its
                    # handoffs are skipped below); other destinations
                    # and the remaining donors still migrate this tick
                    self._on_worker_failure(dst, died=True)
                    continue
                except WorkerTimeout:
                    self._on_worker_failure(dst, died=False)
                    continue
                except WorkerError:
                    pass              # e.g. geometry drift: go cold
            for wrid, rid, _slc, dst in plan:
                req = self._reqs[rid]
                if req.terminal or dst.status != "up":
                    continue
                before = self.stats.migrations
                self._handoff(req, src, dst)
                if self.policy is not None and \
                        self.stats.migrations > before:
                    # the policy's decision became a real move:
                    # journal it so Router.recover replays the
                    # rebalance ledger deterministically (no-policy
                    # routers journal nothing here — pre-fleet WALs
                    # stay byte-identical)
                    self.stats.rebalances += 1
                    self._jrec("rebalance",
                               {"rid": int(rid), "src": src.name,
                                "dst": dst.name, "tick": self.tick})
                if src.status != "up":
                    break             # src died mid-handoff

    def _handoff(self, req: _RouterReq, src: _WorkerState,
                 dst: _WorkerState) -> None:
        """Hand one exported stream off: pending-token resume submit
        on the target (whose pool already holds the imported pages),
        then release the donor copy. Every leg can lose a worker —
        the stream survives every case (the donor's death resubmits
        it cold; the target's death leaves it on the donor)."""
        old_wrid = req.wrid
        resume_payload = {
            "tokens": list(req.tokens) + list(req.generated),
            "kw": self._submit_kw(req, resume=True)}
        try:
            resp = self._op(dst, "submit", resume_payload,
                            point="submit")
        except WorkerDied:
            self._on_worker_failure(dst, died=True)
            return                    # stream stays on src
        except WorkerTimeout:
            self._on_worker_failure(dst, died=False)
            return
        wrid = int(resp["rid"])
        # move the assignment BEFORE processing, so emissions map to
        # the new copy and the donor's release below reads as stale
        src.assigned.pop(old_wrid, None)
        src.by_rid.pop(req.rid, None)
        ws_assigned_prev = (req.worker, req.wrid)
        dst.assigned[wrid] = req.rid
        dst.by_rid[req.rid] = wrid
        req.worker, req.wrid = dst.name, wrid
        rej = self._process_response(dst, resp,
                                     intercept_rid=req.rid)
        if rej is not None:
            # target refused (quota/pool contract): stream stays on
            # the donor — restore the assignment
            req.worker, req.wrid = ws_assigned_prev
            src.assigned[old_wrid] = req.rid
            src.by_rid[req.rid] = old_wrid
            return
        self.stats.migrations += 1
        # release the donor copy; if the donor fails HERE the moved
        # stream is already safe on dst (the failure handler only
        # resubmits streams still assigned to src). Stale-marked
        # across the call so a timeout cannot orphan the copy.
        src.stale.add(int(old_wrid))
        try:
            resp = self._op(src, "release", {"rid": int(old_wrid)})
            self._process_response(src, resp)
            src.stale.discard(int(old_wrid))
        except WorkerError:
            src.stale.discard(int(old_wrid))
        except WorkerDied:
            self._on_worker_failure(src, died=True)
        except WorkerTimeout:
            self._on_worker_failure(src, died=False)

    def _round_pass(self) -> None:
        for ws in list(self._workers.values()):
            if ws.status not in ("up", "degraded"):
                continue
            if ws.stale:
                self._release_stale(ws)
                if ws.status not in ("up", "degraded"):
                    continue
            if not ws.assigned:
                continue
            stepped = sorted(set(ws.assigned.values()))
            try:
                resp = self._op(ws, "round", {},
                                point="before_round")
            except WorkerDied:
                self._on_worker_failure(ws, died=True)
                continue
            except WorkerTimeout:
                self._on_worker_failure(ws, died=False)
                continue
            killed_after = False
            if self.injector is not None:
                v = self.injector.on_worker_op(ws.name, "after_round")
                if v == "kill":
                    ws.handle.kill()
                    killed_after = True
            for rid in stepped:
                req = self._reqs[rid]
                if not req.terminal:
                    req.steps_used += 1
                    self._tick_stepped.add(rid)
            self._process_response(ws, resp)
            if killed_after:
                # the round's emissions were seen (the kill landed
                # after the answer) — the death is handled now
                self._on_worker_failure(ws, died=True)

    # -- response / outcome processing --------------------------------
    def _process_response(self, ws: _WorkerState, resp: dict,
                          intercept_rid: Optional[int] = None
                          ) -> Optional[dict]:
        """Fold one worker answer into the router's record: emissions
        append to streams (and the tick's emit buffer), outcomes
        deliver/retry/reject. ``intercept_rid``: a placement in
        flight — ITS REJECTED_ADMISSION is returned to the caller
        instead of delivered (the router keeps trying other
        workers)."""
        intercepted = None
        for wrid, toks in sorted(
                (resp.get("emitted") or {}).items()):
            rid = ws.assigned.get(int(wrid))
            if rid is None:
                continue              # stale copy: drop on the floor
            req = self._reqs[rid]
            if req.terminal:
                continue
            self._record_emission(req, toks)
        for oc in resp.get("outcomes") or ():
            wrid = int(oc["rid"])
            rid = ws.assigned.get(wrid)
            if rid is None:
                continue
            req = self._reqs[rid]
            ws.assigned.pop(wrid, None)
            ws.by_rid.pop(rid, None)
            if req.wrid == wrid and req.worker == ws.name:
                req.worker = req.wrid = None
            if req.terminal:
                continue
            if intercept_rid == rid and \
                    oc["status"] == RequestOutcome.REJECTED_ADMISSION:
                intercepted = oc
                continue
            self._worker_outcome(ws, req, oc)
        return intercepted

    def _record_emission(self, req: _RouterReq,
                         toks: List[int]) -> None:
        toks = [int(t) for t in toks]
        self._jrec("emit", {"rid": req.rid, "toks": toks})
        for t in toks:
            req.generated.append(t)
            if req.max_new_tokens is None or \
                    len(req.generated) <= req.max_new_tokens:
                self._emit_buffer.setdefault(req.rid, []).append(t)
        if req.max_new_tokens is not None and \
                len(req.generated) >= req.max_new_tokens and \
                not req.terminal:
            self._release_copy(req)
            self._deliver(req, RequestOutcome.FINISHED,
                          "max_new_tokens")

    def _release_copy(self, req: _RouterReq) -> None:
        """Best-effort release of the stream's current worker copy
        (unassigned FIRST, so the release's own FINISHED outcome
        reads as stale and cannot double-deliver). The wrid sits in
        ``ws.stale`` ACROSS the release call: a timeout mid-release
        would otherwise orphan a copy that is neither assigned nor
        stale — never released on rejoin, generating into the pool
        forever."""
        if req.worker is None:
            return
        ws = self._workers[req.worker]
        wrid = int(req.wrid)
        ws.assigned.pop(wrid, None)
        ws.by_rid.pop(req.rid, None)
        req.worker = req.wrid = None
        ws.stale.add(wrid)
        try:
            resp = self._op(ws, "release", {"rid": wrid})
            self._process_response(ws, resp)
            ws.stale.discard(wrid)
        except WorkerError:
            ws.stale.discard(wrid)    # already gone worker-side
        except WorkerDied:
            self._on_worker_failure(ws, died=True)
        except WorkerTimeout:
            self._on_worker_failure(ws, died=False)

    def _worker_outcome(self, ws: _WorkerState, req: _RouterReq,
                        oc: dict) -> None:
        status = oc["status"]
        reason = oc.get("reason", "")
        if status == RequestOutcome.FINISHED:
            # a capacity-finish freed the slot but the worker's
            # host-side stream record lives until released: queue the
            # release so a long-running worker doesn't accumulate one
            # record per finished stream
            ws.stale.add(int(oc["rid"]))
            self._deliver(req, status, reason or "finished at worker")
        elif status == RequestOutcome.FAILED_OOM:
            if req.oom_retries > 0:
                req.oom_retries -= 1
                self.stats.oom_resubmissions += 1
                verdict = self._place_and_submit(
                    req, exclude={ws.name} if len(self._live()) > 1
                    else frozenset())
                if verdict != "placed":
                    self._pending.setdefault(req.rid, self.tick)
            else:
                self._deliver(req, status, reason)
        elif status in (RequestOutcome.FAILED_NUMERIC,
                        RequestOutcome.FAILED_DEADLINE,
                        RequestOutcome.REJECTED_ADMISSION,
                        RequestOutcome.FAILED_UNROUTABLE,
                        RequestOutcome.CANCELLED):
            # deadline / numeric / (late) rejection: the verdict is
            # the worker's to make — forward it exactly once. Members
            # are NAMED (not a catch-all) so a future outcome kind
            # must be consciously routed here — enforced statically
            # by tools/check_static.py (journal-coverage)
            self._deliver(req, status, reason)
        else:
            # RequestOutcome.__init__ validates against STATUSES, so
            # an unknown status cannot reach a worker outcome dict;
            # forward defensively rather than hang the stream
            self._deliver(req, status, reason)

    def _deliver(self, req: _RouterReq, status: str,
                 reason: str) -> None:
        if req.terminal or req.rid in self._delivered:
            return
        req.terminal = True
        req.status = status
        self._pending.pop(req.rid, None)
        self._delivered.add(req.rid)
        # NOT journaled here: the verdict only becomes durable once
        # the client has actually drained it (_flush_drains) — a
        # verdict enqueued but undrained at a router death must
        # RE-deliver after recovery, not vanish into the dedupe set
        self.outcomes.append(RequestOutcome(
            req.rid, status, reason=reason,
            tokens=len(req.tokens) + len(req.generated),
            preemptions=req.resubmissions, step=self.tick))
        self.stats.delivered += 1
        if status == RequestOutcome.FAILED_UNROUTABLE:
            self.stats.unroutable += 1
