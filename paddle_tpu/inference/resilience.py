"""Resilience layer for the paged serving stack: request-level failure
isolation, deterministic fault injection, and terminal outcomes.

The serving engines built in the earlier serving PRs had all-or-nothing
failure semantics: a ``BlockOOM`` that survived preemption raised
``RuntimeError`` out of ``PagedServingEngine.step()`` and killed every
in-flight request, a NaN in one slot's hidden silently corrupted that
request forever, and a preempted request could thrash through
re-prefill with no retry bound. Serving systems in the
vLLM/Ragged-Paged-Attention lineage treat eviction and readmission as
routine events; this module makes FAILURE routine too — a per-request
outcome, never an engine crash:

* ``RequestOutcome`` — the terminal record of one request:
  ``FINISHED`` (caller release / capacity retire) or one of the
  failure statuses ``FAILED_OOM`` (pool dry even after preempting
  every other request, or the re-prefill retry budget exhausted),
  ``FAILED_NUMERIC`` (non-finite hidden detected by the per-slot
  guard), ``FAILED_DEADLINE`` (per-request step or wall-clock budget
  blown, admitted or still queued), ``REJECTED_ADMISSION``
  (health-based admission control refused the request at submit —
  multi-tenant isolation, see scheduler.submit). Engines append these
  to an ``outcomes`` event list the caller drains, exactly like
  ``admitted``/``finished``/``preempted``.

* ``FaultInjector`` — deterministic, schedule-driven fault injection
  with hook points wired into ``BlockAllocator.alloc`` (forced
  ``BlockOOM``), the fused model call (NaN planted in chosen slots'
  output rows), and the speculative engine's draft roll (forced
  draft-pool OOM mid-roll, corrupted draft logits to storm the
  rollback path). Hooks are consulted ONLY when an injector was passed
  to the engine — the no-injector hot path carries zero overhead.
  Schedules are keyed by the engine step counter, so a storm replays
  identically run after run; the headline guarantee (asserted in
  tests/test_resilience.py) is that under a storm of injected OOMs and
  NaNs, surviving requests' decoded tokens are BIT-IDENTICAL to a
  fault-free run and no exception escapes ``step()``/``step_multi()``.

* ``CrashInjector`` — the crash-recovery extension (PR 6): on top of
  the fault schedules it KILLS the engine (raises ``EngineCrash`` out
  of the current call, simulating process death) at scheduled live
  rounds and sub-phases — step top (``begin``), after an admission
  pass (``post_admission``), after a prefill completes
  (``post_prefill``), between a speculative draft roll and its verify
  (``mid_spec_round``), and around the recovery host's journal append
  (``pre_journal``/``post_journal``). Recovery = last snapshot +
  journal replay (inference/recovery.py); crash points are keyed by a
  LIVE-round clock and disarmed during replay, while the fault
  schedules stay keyed by the (restored) engine step clock so a
  replayed step re-injects the same faults — deterministic replay.

Pool invariant auditing lives on ``PagedKVCache.check_invariants``
(paged_cache.py) and is surfaced per engine via
``PagedServingEngine.check_invariants`` / ``SpeculativeEngine.
check_invariants``; the ``--audit-invariants`` pytest flag
(tests/conftest.py) runs it after every engine step.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Union

import numpy as np

from .paged_cache import BlockOOM

__all__ = ["RequestOutcome", "FaultInjector", "CrashInjector",
           "EngineCrash", "RouterFaultInjector",
           "NetworkFaultInjector"]


class EngineCrash(RuntimeError):
    """Injected process death (CrashInjector): the engine object that
    raised this is to be ABANDONED — nothing in it may be trusted or
    reused — and rebuilt from the last snapshot plus journal replay
    (inference/recovery.py). Deliberately NOT a BlockOOM subclass, so
    no engine-internal handler can swallow it."""


class RequestOutcome:
    """Terminal record of one serving request. ``status`` is one of
    the four class constants; anything but FINISHED means the engine
    shed the request (its pages are freed, its slot re-usable) while
    every other request kept stepping."""

    FINISHED = "finished"
    FAILED_OOM = "failed_oom"            # pool dry / retry budget blown
    FAILED_NUMERIC = "failed_numeric"    # non-finite hidden in the slot
    FAILED_DEADLINE = "failed_deadline"  # step / wall-clock budget blown
    # health-based admission control (multi-tenant isolation): the
    # request was refused AT SUBMIT because it provably can never be
    # served — its prompt exceeds its tenant's block quota, the pool
    # minus other tenants' reserved floors, or (prefill-token-budget
    # mode) its deadline_steps is below the prefill-step lower bound.
    # Delivered as a terminal outcome, never an exception: submit()
    # still returns a rid and the verdict rides ``outcomes``.
    REJECTED_ADMISSION = "rejected_admission"
    # router-level terminal verdict (inference/router.py): no live
    # worker could take the request — every worker is dead/suspended
    # (all-workers-down degrades to THIS, deterministically, instead
    # of hanging) or every placement/retry attempt was exhausted.
    # Engines never emit it; it exists so the fleet boundary speaks
    # the same outcome taxonomy as the engines behind it.
    FAILED_UNROUTABLE = "failed_unroutable"
    # deliberate early stop (best-of-n loser pruning, beam-search
    # branch cuts, caller cancel): the stream was healthy but the
    # caller no longer wants it. NOT a failure in the health sense —
    # the ledger attributes its pending work to ``bestof_pruned``
    # waste, and resilience stats count it separately from sheds.
    CANCELLED = "cancelled"

    STATUSES = (FINISHED, FAILED_OOM, FAILED_NUMERIC, FAILED_DEADLINE,
                REJECTED_ADMISSION, FAILED_UNROUTABLE, CANCELLED)

    __slots__ = ("rid", "status", "reason", "tokens", "preemptions",
                 "step")

    def __init__(self, rid: int, status: str, reason: str = "",
                 tokens: int = 0, preemptions: int = 0, step: int = 0):
        if status not in self.STATUSES:
            raise ValueError(f"unknown outcome status {status!r}")
        self.rid = int(rid)
        self.status = status
        self.reason = reason
        self.tokens = int(tokens)        # consumed rows at termination
        self.preemptions = int(preemptions)
        self.step = int(step)            # engine step of the verdict

    @property
    def failed(self) -> bool:
        return self.status != self.FINISHED

    def as_dict(self) -> dict:
        return {"rid": self.rid, "status": self.status,
                "reason": self.reason, "tokens": self.tokens,
                "preemptions": self.preemptions, "step": self.step}

    def __repr__(self):
        tail = f", reason={self.reason!r}" if self.reason else ""
        return (f"RequestOutcome(rid={self.rid}, status={self.status}, "
                f"tokens={self.tokens}, step={self.step}{tail})")


def _norm_oom(sched) -> Dict[int, int]:
    """{step: count} with count < 0 meaning every alloc that step; a
    bare iterable of steps means 'every alloc' at each."""
    if sched is None:
        return {}
    if isinstance(sched, dict):
        return {int(s): int(n) for s, n in sched.items()}
    return {int(s): FaultInjector.ALL for s in sched}


def _norm_nan(sched) -> Dict[int, tuple]:
    if sched is None:
        return {}
    return {int(s): tuple(int(x) for x in np.atleast_1d(slots))
            for s, slots in sched.items()}


class FaultInjector:
    """Deterministic fault schedules, keyed by the serving engine's
    step counter (1-indexed; ``begin_step`` is called by the engine at
    the top of every ``step``/``step_multi``, and by the speculative
    engine at the top of every round with the upcoming verify step's
    index, so draft-phase faults share the same clock).

      oom_at        {step: n}: the first n ``BlockAllocator.alloc``
                    calls of the TARGET pool at that step raise
                    BlockOOM. n < 0 (``FaultInjector.ALL``, also what
                    a bare list of steps means) fails EVERY alloc that
                    step — preemption then cannot help, which forces a
                    SHED (the engine fails the growing request instead
                    of raising). n = 1 exercises the preempt-retry
                    path without shedding.
      nan_at        {step: [slots]}: after the fused model call at
                    that step, those slots' output rows are replaced
                    with NaN — the per-slot numeric guard then fails
                    the occupying request (FAILED_NUMERIC), never the
                    engine. Rows of other slots round-trip bitwise
                    untouched.
      draft_oom_at  same shape as oom_at, wired to the DRAFT pool's
                    allocator (SpeculativeEngine): a mid-roll hit
                    rolls the partial draft roll back page-wise and
                    serves the round without speculation.
      draft_nan_at  {step: [slots]}: corrupt those slots' DRAFT logits
                    during the roll — proposals turn to noise and the
                    verify step rejects them, storming the
                    truncate/rollback path (greedy bit-identity is
                    unaffected: every emitted token is target-derived).

    ``seed`` drives the ``storm`` constructor (random schedules that
    replay identically for a given seed) and is kept for schedule
    authoring; the injector itself is pure schedule playback.
    Counters (``injected_oom`` etc.) record what actually fired.
    """

    ALL = -1

    def __init__(self, seed: int = 0,
                 oom_at: Union[Dict[int, int], Iterable[int], None] = None,
                 nan_at: Optional[Dict[int, Iterable[int]]] = None,
                 draft_oom_at: Union[Dict[int, int], Iterable[int],
                                     None] = None,
                 draft_nan_at: Optional[Dict[int, Iterable[int]]] = None):
        self.seed = int(seed)
        self._oom = {"target": _norm_oom(oom_at),
                     "draft": _norm_oom(draft_oom_at)}
        self.nan_at = _norm_nan(nan_at)
        self.draft_nan_at = _norm_nan(draft_nan_at)
        self.step = 0
        self.injected_oom = 0
        self.injected_draft_oom = 0
        self.injected_nan = 0
        self.injected_draft_nan = 0

    @classmethod
    def storm(cls, seed: int, steps: int, *, oom_sheds: int = 3,
              nan_events: int = 2, max_batch: int = 4,
              first_step: int = 2) -> "FaultInjector":
        """A seed-driven random storm: ``oom_sheds`` whole-step forced
        OOMs (guaranteed shed pressure) and ``nan_events`` single-slot
        NaN plantings, at distinct steps in [first_step, steps). Same
        seed -> same schedule -> same storm, run after run."""
        rng = np.random.RandomState(seed)
        n = oom_sheds + nan_events
        if steps - first_step < n:
            raise ValueError("not enough steps for the requested storm")
        picks = rng.choice(np.arange(first_step, steps), size=n,
                           replace=False)
        oom_at = {int(s): cls.ALL for s in picks[:oom_sheds]}
        nan_at = {int(s): [int(rng.randint(max_batch))]
                  for s in picks[oom_sheds:]}
        return cls(seed=seed, oom_at=oom_at, nan_at=nan_at)

    # -- engine-facing hooks ------------------------------------------
    def begin_step(self, step: int) -> None:
        self.step = int(step)

    def begin_round(self) -> None:
        """Live-round clock tick (crash schedules): no-op in the base
        injector — CrashInjector overrides. Called by the recovery
        host at the top of every LIVE round, never during replay."""

    def crash_point(self, phase: str) -> None:
        """Crash-schedule consultation: no-op in the base injector —
        CrashInjector overrides and may raise EngineCrash. Engines
        call this at step boundaries and sub-phases whenever an
        injector is present."""

    def on_alloc(self, pool: str, n: int = 1) -> None:
        """BlockAllocator.alloc hook: raise BlockOOM when the schedule
        says so (consuming one scheduled failure unless unbounded)."""
        sched = self._oom[pool]
        rem = sched.get(self.step)
        if rem is None or rem == 0:
            return
        if rem > 0:
            sched[self.step] = rem - 1
        if pool == "draft":
            self.injected_draft_oom += 1
        else:
            self.injected_oom += 1
        raise BlockOOM(f"injected fault: forced {pool}-pool OOM at "
                       f"step {self.step}",
                       details={"injected": True, "pool": pool,
                                "step": self.step})

    def _corrupt(self, out, slots) -> object:
        """Replace ``slots``' rows of a [B, ...] Tensor with NaN; all
        other rows round-trip bitwise unchanged (float32 numpy
        round-trips are exact)."""
        from ..framework.tensor import Tensor
        arr = np.array(np.asarray(out.numpy()), np.float32, copy=True)
        hit = 0
        for s in slots:
            if 0 <= s < arr.shape[0]:
                arr[s] = np.nan
                hit += 1
        return Tensor(arr), hit

    def corrupt_hidden(self, out):
        """Plant scheduled NaNs into the fused step's output rows.
        Returns ``out`` untouched (same object) on steps with nothing
        scheduled."""
        slots = self.nan_at.get(self.step)
        if not slots:
            return out
        out, hit = self._corrupt(out, slots)
        self.injected_nan += hit
        return out

    def corrupt_draft_logits(self, logits):
        """Plant scheduled NaNs into draft sampling logits (rollback
        storm: the corrupted proposals verify-fail)."""
        slots = self.draft_nan_at.get(self.step)
        if not slots:
            return logits
        logits, hit = self._corrupt(logits, slots)
        self.injected_draft_nan += hit
        return logits

    def as_dict(self) -> dict:
        return {"seed": self.seed, "step": self.step,
                "injected_oom": self.injected_oom,
                "injected_draft_oom": self.injected_draft_oom,
                "injected_nan": self.injected_nan,
                "injected_draft_nan": self.injected_draft_nan}

    def __repr__(self):
        return (f"FaultInjector(seed={self.seed}, "
                f"oom={self.injected_oom}, nan={self.injected_nan}, "
                f"draft_oom={self.injected_draft_oom}, "
                f"draft_nan={self.injected_draft_nan})")


class CrashInjector(FaultInjector):
    """FaultInjector that additionally KILLS the engine: at scheduled
    (live round, phase) points it raises ``EngineCrash`` out of
    whatever call is running, leaving the engine object mid-mutation —
    exactly what a process death does. The test/recovery harness
    catches it, abandons the engine, and rebuilds from snapshot +
    journal (inference/recovery.py).

    ``crash_at``: {round: phase or iterable of phases}. Rounds are the
    LIVE-round clock — ``begin_round()`` is called by the recovery
    host at the top of every live round and NOT during journal replay,
    so a recovered engine re-running journaled rounds cannot re-die at
    the same schedule entry (the round counter, which lives in this
    object and survives the "process", has already moved past it).
    Each scheduled (round, phase) fires at most once. Phases:

      begin           step top, right after the fault clock ticks
      post_admission  an admission pass completed (every step, and
                      inside submit())
      post_prefill    a prefill completed and the admitted event fired
      mid_spec_round  between the speculative draft roll and the ONE
                      target verify call (draft advanced, target not)
      pre_journal     after the engine round, BEFORE the emissions hit
                      the journal (the round must replay from scratch)
      post_journal    after the journal append, before the caller sees
                      the emissions (replay must NOT re-emit)

    ``arm(False)`` disarms crash points during journal replay; the
    inherited FAULT schedules stay live throughout — they are keyed by
    the engine step clock, which the snapshot restores, so a replayed
    step re-injects the same OOM/NaN the live step saw (without that,
    replay would diverge from the journal). Consumable ``{step: n}``
    OOM budgets mutate injector state that snapshots do NOT capture —
    compose crashes only with whole-step (``ALL`` / bare-list)
    schedules and ``nan_at``, which are pure playback."""

    PHASES = ("begin", "post_admission", "post_prefill",
              "mid_spec_round", "pre_journal", "post_journal")

    def __init__(self, crash_at=None, seed: int = 0, **fault_kw):
        super().__init__(seed=seed, **fault_kw)
        sched: Dict[int, set] = {}
        for r, p in (crash_at or {}).items():
            phases = (p,) if isinstance(p, str) else tuple(p)
            for ph in phases:
                if ph not in self.PHASES:
                    raise ValueError(f"unknown crash phase {ph!r} "
                                     f"(one of {self.PHASES})")
            sched[int(r)] = set(phases)
        self.crash_at = sched
        self.round = 0
        self.crashes = 0
        self._armed = True

    @classmethod
    def storm(cls, seed: int, rounds: int, *, crashes: int = 4,
              phases=None, first_round: int = 2,
              **fault_kw) -> "CrashInjector":
        """Seeded random crash storm: ``crashes`` kills at distinct
        live rounds in [first_round, rounds), each at a random phase.
        Defaults to the phases that fire every round (begin /
        post_admission / pre_journal / post_journal) so the scheduled
        kill count is exact; pass ``phases`` to aim at conditional
        ones (post_prefill, mid_spec_round)."""
        rng = np.random.RandomState(seed)
        phases = tuple(phases) if phases is not None else \
            ("begin", "post_admission", "pre_journal", "post_journal")
        if rounds - first_round < crashes:
            raise ValueError("not enough rounds for the crash storm")
        picks = rng.choice(np.arange(first_round, rounds),
                           size=crashes, replace=False)
        return cls(crash_at={int(r): phases[rng.randint(len(phases))]
                             for r in picks},
                   seed=seed, **fault_kw)

    def begin_round(self) -> None:
        self.round += 1

    def arm(self, on: bool) -> None:
        self._armed = bool(on)

    def crash_point(self, phase: str) -> None:
        if not self._armed:
            return
        sched = self.crash_at.get(self.round)
        if sched and phase in sched:
            sched.discard(phase)
            self.crashes += 1
            raise EngineCrash(f"injected crash at live round "
                              f"{self.round}, phase {phase!r} "
                              f"(engine step {self.step})")

    def as_dict(self) -> dict:
        d = super().as_dict()
        d.update({"round": self.round, "crashes": self.crashes})
        return d

    def __repr__(self):
        return (f"CrashInjector(seed={self.seed}, round={self.round}, "
                f"crashes={self.crashes}, oom={self.injected_oom}, "
                f"nan={self.injected_nan})")


class RouterFaultInjector(CrashInjector):
    """CrashInjector extended one fault domain up: deterministic
    WORKER kills and hangs, keyed by the router's TICK clock (one
    tick per ``Router.step``; ``begin_tick`` is called at the top of
    every tick, the router-level mirror of ``begin_round``). The
    router consults ``on_worker_op(worker, point)`` immediately
    before each operation it is about to issue to a worker:

      "kill"  the worker dies AT that point — the handle is killed
              (a pipes worker takes a real SIGKILL; an in-process
              worker is abandoned) and the op fails with WorkerDied.
              Each scheduled (tick, worker) kill fires at most once.
      "hang"  the worker goes silent: this op (and every op until the
              scheduled hang expires) fails with WorkerTimeout while
              the worker itself stays alive and UNAWARE — exactly a
              hung/partitioned process. The router's circuit breaker
              owns the consequence.

      kill_at   {tick: {worker_name: point}} — point is one of
                ``ROUTER_POINTS`` ("submit", "before_round",
                "after_round", "export", "import", "scrape", "ping"),
                matched against the op the router is about to issue;
                "before_round"/"after_round" bracket the worker's
                serving round ("after_round" kills AFTER the round
                call returned, so the round's emissions were seen —
                the death is detected at the next tick's op).
      hang_at   {tick: {worker_name: n_ticks}} — from that tick the
                worker times out for n_ticks ticks, then answers
                again (the stale-copy release path).

    The engine-level schedules (``oom_at``/``nan_at``/``crash_at``)
    are inherited but belong to PER-WORKER injectors — this object
    rides the router, which never owns an engine step clock. Pure
    schedule playback like every injector: zero overhead when absent,
    identical storms run after run."""

    ROUTER_POINTS = ("submit", "before_round", "after_round",
                     "export", "import", "scrape", "ping")

    def __init__(self, kill_at=None, hang_at=None, seed: int = 0,
                 **fault_kw):
        super().__init__(seed=seed, **fault_kw)
        self.kill_at: Dict[int, dict] = {}
        for t, m in (kill_at or {}).items():
            for w, point in m.items():
                if point not in self.ROUTER_POINTS:
                    raise ValueError(
                        f"unknown router kill point {point!r} (one "
                        f"of {self.ROUTER_POINTS})")
            self.kill_at[int(t)] = dict(m)
        self.hang_at: Dict[int, dict] = {
            int(t): {str(w): int(n) for w, n in m.items()}
            for t, m in (hang_at or {}).items()}
        self.tick = 0
        self.killed = 0
        self.hung_ops = 0
        self._hang_until: Dict[str, int] = {}

    @classmethod
    def kill_storm(cls, seed: int, ticks: int, workers, *,
                   kills: int = 2, hangs: int = 0,
                   first_tick: int = 2,
                   points=("before_round",)) -> "RouterFaultInjector":
        """Seeded random router storm: ``kills`` worker deaths and
        ``hangs`` transient silences at distinct ticks in
        [first_tick, ticks), each aimed at a random worker. Same seed
        -> same storm."""
        rng = np.random.RandomState(seed)
        workers = list(workers)
        n = kills + hangs
        if ticks - first_tick < n:
            raise ValueError("not enough ticks for the router storm")
        picks = rng.choice(np.arange(first_tick, ticks), size=n,
                           replace=False)
        kill_at = {int(t): {workers[rng.randint(len(workers))]:
                            points[rng.randint(len(points))]}
                   for t in picks[:kills]}
        hang_at = {int(t): {workers[rng.randint(len(workers))]:
                            int(rng.randint(1, 3))}
                   for t in picks[kills:]}
        return cls(kill_at=kill_at, hang_at=hang_at, seed=seed)

    def begin_tick(self) -> None:
        self.tick += 1

    def on_worker_op(self, worker: str, point: str) -> Optional[str]:
        """Verdict for the op the router is about to issue: None
        (proceed), "kill" (kill the worker first), or "hang" (the op
        times out; the worker never sees it)."""
        if not self._armed:
            return None
        sched = self.hang_at.get(self.tick)
        if sched and worker in sched:
            self._hang_until[worker] = self.tick + sched.pop(worker)
        until = self._hang_until.get(worker)
        if until is not None:
            if self.tick < until:
                self.hung_ops += 1
                return "hang"
            del self._hang_until[worker]
        sched = self.kill_at.get(self.tick)
        if sched and sched.get(worker) == point:
            del sched[worker]
            self.killed += 1
            return "kill"
        return None

    def as_dict(self) -> dict:
        d = super().as_dict()
        d.update({"tick": self.tick, "killed": self.killed,
                  "hung_ops": self.hung_ops})
        return d

    def __repr__(self):
        return (f"RouterFaultInjector(seed={self.seed}, "
                f"tick={self.tick}, killed={self.killed}, "
                f"hung_ops={self.hung_ops})")


class NetworkFaultInjector:
    """Deterministic NETWORK faults for the session transport
    (inference/net.py) — the fault domain below RouterFaultInjector's
    kills and hangs: the worker process stays healthy, only the wire
    lies. Schedules are keyed by (worker name, op seq) — the
    transport's per-op sequence number is its deterministic clock, so
    two identical runs take identical faults, recover through
    identical reconnect sequences, and report identical ``net.*``
    counters. Each scheduled fault fires at most once (the verdict is
    consumed on first consult), so the retry that follows runs clean.

    Fault kinds (``plan = {worker: {seq: kind}}``):

      drop_before       the connection drops before the op frame is
                        delivered — the worker never saw it; the
                        resend after reconnect executes it (once).
      drop_after        the connection drops after delivery — the
                        worker executed and cached the reply; the
                        resend is answered from the reply cache, NOT
                        re-executed (the idempotency contract).
      truncate_header   the reply frame is torn mid-header (EOF after
                        4 of the 8 header bytes).
      truncate_payload  the reply frame is torn mid-payload.
      corrupt           one payload byte is flipped — the CRC check
                        rejects the frame.
      duplicate         the reply frame arrives twice; the second
                        copy must be discarded by the want-seq check.
      blackhole         every byte of the reply is swallowed until
                        the op deadline expires — a silent peer; the
                        liveness probe then proves the worker alive
                        and the resend resolves the op.

    Like every injector here: pure schedule playback, zero overhead
    when absent (the transport consults it only when one was passed),
    ``arm(False)`` disarms during replay."""

    FAULTS = ("drop_before", "drop_after", "truncate_header",
              "truncate_payload", "corrupt", "duplicate", "blackhole")
    SEND_FAULTS = ("drop_before", "drop_after", "blackhole")
    FRAME_FAULTS = ("truncate_header", "truncate_payload", "corrupt",
                    "duplicate")

    def __init__(self, plan: Optional[Dict[str, dict]] = None,
                 seed: int = 0):
        self.seed = int(seed)
        self.plan: Dict[str, Dict[int, str]] = {}
        for worker, sched in (plan or {}).items():
            for s, kind in sched.items():
                if kind not in self.FAULTS:
                    raise ValueError(f"unknown network fault {kind!r} "
                                     f"(one of {self.FAULTS})")
            self.plan[str(worker)] = {int(s): str(k)
                                      for s, k in sched.items()}
        self._armed = True
        self.fired: Dict[str, int] = {k: 0 for k in self.FAULTS}

    @classmethod
    def storm(cls, seed: int, workers, *, span=(2, 30), drops: int = 3,
              frames: int = 2, blackholes: int = 1
              ) -> "NetworkFaultInjector":
        """Seeded random network storm: ``drops`` connection drops
        (before/after delivery), ``frames`` torn/corrupt/duplicate
        reply frames and ``blackholes`` silent-peer timeouts, each
        aimed at a random (worker, op seq) in ``span``. Same seed ->
        same storm — the acceptance-criteria generator."""
        rng = np.random.RandomState(seed)
        workers = list(workers)
        n = drops + frames + blackholes
        lo, hi = int(span[0]), int(span[1])
        if hi - lo < n:
            raise ValueError("not enough op seqs for the net storm")
        kinds = (list(rng.choice(["drop_before", "drop_after"],
                                 size=drops))
                 + list(rng.choice(["truncate_header",
                                    "truncate_payload", "corrupt",
                                    "duplicate"], size=frames))
                 + ["blackhole"] * blackholes)
        plan: Dict[str, Dict[int, str]] = {}
        # distinct seqs per worker so two faults never collide on one op
        seqs = {w: list(rng.choice(np.arange(lo, hi), size=n,
                                   replace=False)) for w in workers}
        for kind in kinds:
            w = workers[rng.randint(len(workers))]
            plan.setdefault(w, {})[int(seqs[w].pop())] = str(kind)
        return cls(plan=plan, seed=seed)

    def arm(self, on: bool) -> None:
        self._armed = bool(on)

    def _take(self, worker: str, seq: int, kinds) -> Optional[str]:
        if not self._armed:
            return None
        sched = self.plan.get(worker)
        if not sched:
            return None
        kind = sched.get(int(seq))
        if kind is None or kind not in kinds:
            return None
        del sched[int(seq)]           # fires at most once
        self.fired[kind] += 1
        return kind

    def on_send(self, worker: str, seq: int) -> Optional[str]:
        """Verdict consulted by the transport as it is about to send
        op ``seq``: None (clean), "drop_before", "drop_after" or
        "blackhole"."""
        return self._take(worker, seq, self.SEND_FAULTS)

    def on_reply(self, worker: str, seq: int) -> Optional[str]:
        """Verdict consulted when a complete reply frame for op
        ``seq`` is buffered: None, "truncate_header",
        "truncate_payload", "corrupt" or "duplicate"."""
        return self._take(worker, seq, self.FRAME_FAULTS)

    @property
    def pending(self) -> int:
        return sum(len(s) for s in self.plan.values())

    def as_dict(self) -> dict:
        return {"seed": self.seed, "pending": self.pending,
                "fired": dict(self.fired)}

    def __repr__(self):
        shot = {k: v for k, v in self.fired.items() if v}
        return (f"NetworkFaultInjector(seed={self.seed}, "
                f"pending={self.pending}, fired={shot})")
