"""Paged KV cache: block pool + free-list allocator + cache-protocol views.

The reference serving path (fused_multi_transformer_op.cu.h decode) and
the round-5 ContinuousBatchingEngine both pre-allocate a dense
[2, B, H, max_len, D] cache row per slot, so HBM — not compute — caps
concurrency. Here K/V live in a per-layer POOL of fixed-size blocks
[num_blocks, 2, H, block_size, D] (PAPERS.md "Ragged Paged Attention",
arxiv 2604.15464); each sequence owns a block table (int32 row of pool
indices) and grows allocate-on-write, one block at a time. Blocks are
refcounted so a forked request can share its prefix pages and split
them copy-on-write at the first divergent append.

The cache layout is a PROTOCOL, not a tensor shape:
``FusedMultiTransformer.forward(..., caches=..., time_step=...)``
accepts either dense per-layer Tensors or the `PagedLayerCache` views
below (duck-typed via ``is_paged``), so dense and paged serving are
interchangeable — see the shim in incubate/nn/fused_transformer.py.

Block 0 of every pool is reserved as the TRASH block: inactive batch
rows in a fused decode step scatter their (ignored) k/v there, and
block-table entries past a sequence's allocation point at it so the
kernel's gather always reads a valid pool row (masked by length).

Cross-request PREFIX CACHING (``prefix_cache=True``) layers a content
index over the pool: every full prompt block gets a chained hash
``h_i = H(h_{i-1}, tokens_in_block_i)`` (vLLM-style block identity —
the chain makes the hash position- and prefix-dependent, so a match on
h_i proves the whole prefix up to block i is identical). A new
request's prompt is matched block-by-block against the index
(``match_prefix``/``adopt_prefix``) and shares the hit pages by
refcount — the existing copy-on-write split handles later divergence.
Freed blocks whose hash is still indexed don't return to the free
list: they park in a CACHED-FREE second-chance tier
(``release_to_cache``), resurrectable on a later hit, and are
reclaimed least-recently-used only when the free list runs dry. Block
lifecycle: free -> active -> cached-free -> (resurrect -> active |
reclaim -> free).

QUANTIZED SERVING (``dtype="int8"``): K/V pages store int8 payload
with per-(position, head) float32 scales in per-block metadata
arrays (``scales``) that ride next to the pools — quantized at
page-write time inside every append op, dequantized on every read
(in-register on the ragged kernel's scalar-prefetch path, inside
``gather_pages`` on the jnp fallbacks). ~1.88x KV density vs bf16 at
head_dim 64 (4/head_dim scale overhead), which at a fixed HBM budget
~1.88x's the block pool and therefore admission concurrency. The
whole page lifecycle below — COW fork, prefix-hash sharing,
cached-free resurrection, quarantine, tenant charge,
snapshot/restore — operates on quantized payloads unchanged: scales
move with their page through COW copies and snapshots, the deep
audit fingerprints payload + scales, and because each position's
quantized bytes are a pure function of that token's K/V (see
``_quant_rows``), prefix adoption of a quantized page is EXACT.

TENSOR-PARALLEL SHARDING (``mp`` > 1): the pool partitions over
attention heads — shard s stores ``pools[layer * mp + s]`` of shape
``[num_blocks, 2, H/mp, bs, D]`` on its own device (scale pages
sharded identically on int8 pools), while the allocator, block
tables, refcounts, chain-hash index, tenant charges and decode mask
stay host-side and REPLICATED: block ids and lifecycle are
shard-invariant, so admission, quotas, WFQ, prefix caching, COW,
snapshots and the journal run byte-for-byte unchanged at any mesh
width. Each layer's views grow a ``shard(s)`` accessor; the
per-shard model (inference/serving.py ``ShardedServingCore``) drives
shard s with its own head slice of q/k/v and closes each layer with
ONE all-reduce. Snapshots and migration slices stay CANONICAL
(full-head pages): shards concatenate on the head axis going out and
re-slice coming in, which is what makes checkpoints and kv_slices
portable across mesh widths (mp=N <-> mp=1) — the content address of
a page never depends on how it is sharded.

CRASH RECOVERY (``snapshot``/``restore``): because every block is
content-addressed by its chain hash, a pool checkpoint is "serialize
the live + cached-free pages plus the allocator's exact state"
(refcounts, free-list order, cached-free LRU order, hash index). A
same-geometry restore is a perfect round trip — block ids, free-list
order and LRU order are preserved, so the restored pool allocates
bit-identically to the uninterrupted one. A restore into a DIFFERENT
``num_blocks`` pool rehomes the content-addressed blocks under fresh
ids through the same hash index (cached-free blocks are dropped
least-recently-used first when the target is smaller; a live set that
cannot fit raises a precise ``BlockOOM`` with the occupancy
breakdown). Restore re-runs the deep ``check_invariants`` audit
before handing the pool back.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..framework.op import apply
from ..framework.tensor import Tensor

__all__ = ["BlockOOM", "BlockAllocator", "PagedKVCache",
           "PagedLayerCache", "PagedPrefillView", "PagedRaggedView",
           "chain_hash", "chain_block_hashes"]


def chain_hash(parent: bytes, block_tokens) -> bytes:
    """One link of the block-identity chain: hash of the parent block's
    chained hash + this block's token content (prompt rows are
    embeddings here, so content identity is float32 byte identity)."""
    arr = np.ascontiguousarray(np.asarray(block_tokens, np.float32))
    return hashlib.blake2b(parent + arr.tobytes(),
                           digest_size=16).digest()


def chain_block_hashes(tokens, block_size: int,
                       parent: bytes = b"") -> List[bytes]:
    """Chained hashes for every FULL block of ``tokens`` ([T, ...]).
    Partial trailing blocks are never indexed — their content is not
    yet block-identity-stable (the owner keeps appending into them)."""
    arr = np.asarray(tokens)
    out: List[bytes] = []
    h = parent
    for i in range(arr.shape[0] // block_size):
        h = chain_hash(h, arr[i * block_size:(i + 1) * block_size])
        out.append(h)
    return out


class BlockOOM(RuntimeError):
    """No free blocks in the pool (the scheduler preempts on this).

    ``details`` is the STRUCTURED occupancy breakdown the message
    string is composed from (``PagedKVCache.pool_occupancy()``:
    tier counts, owning-slot histogram, per-tenant blocks-held
    histogram) — machine-readable for telemetry (every shed/OOM
    emits it as an event, inference/telemetry.py) instead of
    regex-mining the message. Injected faults
    (``FaultInjector.on_alloc``) carry ``{"injected": True, ...}``;
    an OOM raised before any pool exists carries ``{}``."""

    def __init__(self, *args, details: Optional[dict] = None):
        super().__init__(*args)
        self.details: dict = dict(details) if details else {}


class BlockAllocator:
    """Free-list allocator over pool rows 1..num_blocks-1 with
    refcounts (row 0 is the reserved trash block). Shared-prefix
    blocks hold refcount > 1 and are split copy-on-write by the
    cache.

    With prefix caching the allocator grows a SECOND-CHANCE tier:
    refcount-0 blocks whose content is still hash-indexed park in
    ``_cached`` (cached-free) instead of the free list. They count as
    free — ``alloc`` drains the true free list first, then reclaims
    cached-free blocks least-recently-used, announcing each reclaim
    through ``on_reclaim`` so the owner drops its index entry. A
    BlockOOM therefore means BOTH tiers are dry (callers preempt)."""

    def __init__(self, num_blocks: int, on_reclaim=None):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is reserved)")
        self.num_blocks = int(num_blocks)
        # pop() from the end -> lowest ids first (stable tests)
        self._free = list(range(self.num_blocks - 1, 0, -1))
        # cached-free tier: insertion order == release order, so
        # popitem(last=False) evicts the least-recently-released block
        self._cached: "OrderedDict[int, bool]" = OrderedDict()
        self.on_reclaim = on_reclaim
        self.reclaimed = 0
        self.refcount = np.zeros(self.num_blocks, np.int32)
        self.refcount[0] = 1  # trash block: never allocated, never freed
        # diagnostics + fault injection, wired by the owning cache:
        #   context()       -> str appended to BlockOOM messages (pool
        #                      occupancy breakdown, owning-slot histogram)
        #   context_data()  -> dict carried on BlockOOM.details (the
        #                      same breakdown, machine-readable —
        #                      telemetry events ride it)
        #   describe(block) -> str appended to ref/free misuse errors
        #                      (who owns the block)
        #   fault_hook(n)   -> may raise BlockOOM (FaultInjector);
        #                      consulted first so a forced OOM fires
        #                      even with free blocks in the pool
        self.context = None
        self.context_data = None
        self.describe = None
        self.fault_hook = None

    def _blurb(self, block: int) -> str:
        if self.describe is None:
            return ""
        return f" ({self.describe(int(block))})"

    @property
    def num_free(self) -> int:
        return len(self._free) + len(self._cached)

    @property
    def num_cached(self) -> int:
        return len(self._cached)

    def alloc(self, n: int = 1) -> List[int]:
        if self.fault_hook is not None:
            self.fault_hook(n)
        if n > self.num_free:
            raise BlockOOM(
                f"need {n} block(s), {self.num_free} free "
                f"({len(self._free)} free-list + {len(self._cached)} "
                f"cached-free reclaimable)"
                + (self.context() if self.context is not None else ""),
                details=dict(
                    self.context_data()
                    if self.context_data is not None else {},
                    blocks_needed=int(n),
                    blocks_free=int(self.num_free)))
        blocks = []
        for _ in range(n):
            if self._free:
                b = self._free.pop()
            else:
                # LRU reclaim from the second-chance tier
                b, _ = self._cached.popitem(last=False)
                self.reclaimed += 1
                if self.on_reclaim is not None:
                    self.on_reclaim(b)
            self.refcount[b] = 1
            blocks.append(b)
        return blocks

    def ref(self, blocks) -> None:
        """Share blocks (forked prefix): one more owner each."""
        for b in blocks:
            if self.refcount[b] <= 0:
                raise ValueError(f"ref of unallocated block {b}"
                                 + self._blurb(b))
            self.refcount[b] += 1

    def free(self, blocks, to_cache: bool = False) -> None:
        """Drop one owner per block. A block reaching refcount 0 goes
        to the free list — or, with ``to_cache``, to the cached-free
        tier (still-indexed content, resurrectable on a prefix hit)."""
        for b in blocks:
            if b == 0:
                raise ValueError("block 0 is reserved")
            if self.refcount[b] <= 0:
                raise ValueError(f"double free of block {b}"
                                 + self._blurb(b))
            self.refcount[b] -= 1
            if self.refcount[b] == 0:
                if to_cache:
                    self._cached[int(b)] = True
                else:
                    self._free.append(int(b))

    def resurrect(self, block: int) -> None:
        """cached-free -> active again (a prefix hit adopted it)."""
        if block not in self._cached:
            raise ValueError(f"block {block} is not cached-free")
        del self._cached[block]
        self.refcount[block] = 1


# --- int8 KV quantization (``dtype="int8"`` pools) --------------------
# Symmetric per-position-per-head scales: each written K/V row
# quantizes over its head_dim with its own scale, stored in the pool's
# per-block scale metadata [num_blocks, 2, heads, block_size]. Row
# granularity (not one scalar per block) is load-bearing twice over:
# (1) appends into a partially-filled block never re-quantize earlier
# positions — no read-modify-write on the hot append path, and shared
# / hash-indexed pages stay immutable (the deep audit's contract);
# (2) a position's quantized bytes are a pure function of that
# token's K/V — which chunking cannot change (per-row invariance of
# multi-row calls, the established chunked-prefill contract) — so the
# int8 payload + scales of a full block are a deterministic function
# of the prefix token stream, and prefix-hash adoption of a quantized
# page is EXACT (the adopter shares the very bytes it would have
# written). Scale overhead: 4 bytes per (position, head, K|V) next to
# head_dim int8 payload bytes — 4/head_dim relative (6.25% at
# head_dim 64), leaving ~1.88x density vs bf16 pools.

KV_QMAX = 127.0


def _merge_delta_snapshot(snap: dict, base: dict,
                          referenced: List[int]) -> dict:
    """Reconstitute a FULL snapshot from a delta and the base it was
    taken against: the delta's own (dirty) payload rows plus the
    base's rows for every ``base_blocks`` id. Refuses a mismatched
    base (different geometry, or missing a referenced block) — a
    wrong base would scatter wrong bytes under valid block ids, the
    exact corruption content addressing exists to prevent."""
    if base.get("geometry") != snap["geometry"]:
        raise ValueError("delta snapshot: base geometry mismatch")
    row_of = {int(b): i for i, b in enumerate(base["blocks"])}
    missing = [b for b in referenced if b not in row_of]
    if missing:
        raise ValueError(f"delta snapshot references block(s) "
                         f"{missing} the base does not carry — "
                         f"wrong base checkpoint")
    take = [row_of[b] for b in referenced]
    merged = dict(snap)
    merged["blocks"] = [int(b) for b in snap["blocks"]] + \
        [int(b) for b in referenced]
    merged["payload"] = np.concatenate(
        [np.asarray(snap["payload"]),
         np.asarray(base["payload"])[take]], axis=0)
    if "scale_payload" in snap:
        merged["scale_payload"] = np.concatenate(
            [np.asarray(snap["scale_payload"]),
             np.asarray(base["scale_payload"])[take]], axis=0)
    merged["base_blocks"] = []
    return merged


def _quant_rows(x):
    """x [..., D] float -> (int8 payload [..., D], float32 scale
    [...]): symmetric round-to-nearest at amax/127 per row. All-zero
    rows quantize to zeros with scale 0 (dequantizes to exact 0)."""
    x = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(x), axis=-1) / KV_QMAX
    q = jnp.clip(jnp.round(x / jnp.maximum(scale, 1e-30)[..., None]),
                 -KV_QMAX, KV_QMAX).astype(jnp.int8)
    return q, scale


# --- per-op impls at module scope: the factory closures carry only ----
# --- hashable ints, so framework/op.py's executable cache hits --------

def _make_append(block_size):
    def paged_cache_kv(pool, k, v, t, bt):
        # pool [NB, 2, H, bs, D]; k/v [B, 1, H, D]; t int32 [B]; bt
        # [B, MB]. Write row b's k/v at position t[b] through its block
        # table. Inactive rows point at the trash block — duplicate
        # scatter indices there are fine, nothing reads it unmasked.
        blk = jnp.take_along_axis(bt, (t // block_size)[:, None],
                                  axis=1)[:, 0]
        off = t % block_size
        pool = pool.at[blk, 0, :, off, :].set(
            k[:, 0].astype(pool.dtype))
        return pool.at[blk, 1, :, off, :].set(
            v[:, 0].astype(pool.dtype))
    return paged_cache_kv


def _make_append_multi(block_size, n_tokens):
    def paged_cache_kv_multi(pool, k, v, t, bt):
        # multi-token append (speculative-decode verification): k/v
        # [B, L, H, D] land at positions t[b] .. t[b]+L-1 through the
        # block table. Positions within an active row are distinct, so
        # the scatter never collides; inactive rows (t == 0, table all
        # trash) duplicate-write block 0, which nothing reads unmasked.
        pos = t[:, None] + jnp.arange(n_tokens, dtype=t.dtype)[None, :]
        blk = jnp.take_along_axis(bt, pos // block_size, axis=1)
        off = pos % block_size                        # [B, L]
        pool = pool.at[blk, 0, :, off, :].set(k.astype(pool.dtype))
        return pool.at[blk, 1, :, off, :].set(v.astype(pool.dtype))
    return paged_cache_kv_multi


def _make_append_q(block_size):
    def paged_cache_kv_q(pool, scales, k, v, t, bt):
        # quantized twin of paged_cache_kv: the int8 payload and the
        # per-(position, head) scale scatter through the same routing
        blk = jnp.take_along_axis(bt, (t // block_size)[:, None],
                                  axis=1)[:, 0]
        off = t % block_size
        kq, ks = _quant_rows(k[:, 0])
        vq, vs = _quant_rows(v[:, 0])
        pool = pool.at[blk, 0, :, off, :].set(kq)
        pool = pool.at[blk, 1, :, off, :].set(vq)
        scales = scales.at[blk, 0, :, off].set(ks)
        scales = scales.at[blk, 1, :, off].set(vs)
        return pool, scales
    return paged_cache_kv_q


def _make_append_multi_q(block_size, n_tokens):
    def paged_cache_kv_multi_q(pool, scales, k, v, t, bt):
        pos = t[:, None] + jnp.arange(n_tokens, dtype=t.dtype)[None, :]
        blk = jnp.take_along_axis(bt, pos // block_size, axis=1)
        off = pos % block_size                        # [B, L]
        kq, ks = _quant_rows(k)                 # [B, L, H, D], [B, L, H]
        vq, vs = _quant_rows(v)
        pool = pool.at[blk, 0, :, off, :].set(kq)
        pool = pool.at[blk, 1, :, off, :].set(vq)
        scales = scales.at[blk, 0, :, off].set(ks)
        scales = scales.at[blk, 1, :, off].set(vs)
        return pool, scales
    return paged_cache_kv_multi_q


def _make_append_chunk_q(block_size, n_tokens):
    def paged_prefill_chunk_kv_q(pool, scales, k, v, t, bt, ws):
        # quantized twin of paged_prefill_chunk_kv: adopted-prefix
        # positions (< ws) route payload AND scale to the trash block
        pos = t[:, None] + jnp.arange(n_tokens, dtype=t.dtype)[None, :]
        blk = jnp.take_along_axis(bt, pos // block_size, axis=1)
        blk = jnp.where(pos >= ws, blk, 0)
        off = pos % block_size                        # [1, C]
        kq, ks = _quant_rows(k)
        vq, vs = _quant_rows(v)
        pool = pool.at[blk, 0, :, off, :].set(kq)
        pool = pool.at[blk, 1, :, off, :].set(vq)
        scales = scales.at[blk, 0, :, off].set(ks)
        scales = scales.at[blk, 1, :, off].set(vs)
        return pool, scales
    return paged_prefill_chunk_kv_q


def _make_prefill_scatter_q(start_block, n_blocks, block_size):
    def paged_prefill_scatter_q(pool, scales, row_cache, blks):
        lo = start_block * block_size
        seg = row_cache[:, 0, :, lo:lo + n_blocks * block_size, :]
        two, H, _, D = seg.shape
        seg = seg.reshape(two, H, n_blocks, block_size, D)
        seg = jnp.transpose(seg, (2, 0, 1, 3, 4))  # [n, 2, H, bs, D]
        q, s = _quant_rows(seg)
        return pool.at[blks].set(q), scales.at[blks].set(s)
    return paged_prefill_scatter_q


def _ragged_append_q(pool, scales, k, v, blk, off):
    # quantized twin of _ragged_append (packed mixed-batch scatter)
    kq, ks = _quant_rows(k[0])
    vq, vs = _quant_rows(v[0])
    pool = pool.at[blk, 0, :, off, :].set(kq)
    pool = pool.at[blk, 1, :, off, :].set(vq)
    scales = scales.at[blk, 0, :, off].set(ks)
    scales = scales.at[blk, 1, :, off].set(vs)
    return pool, scales


def _block_copy(pool, src, dst):
    # copy-on-write split: pool[dst[i]] = pool[src[i]] (shared by the
    # payload pools AND, on quantized pools, the scale arrays — a COW
    # split must move the page's scales with its bytes)
    return pool.at[dst].set(pool[src])


def _make_append_chunk(block_size, n_tokens):
    def paged_prefill_chunk_kv(pool, k, v, t, bt, ws):
        # chunked-prefill append: k/v [1, C, H, D] land at positions
        # t[0] .. t[0]+C-1 through the slot's block-table row bt
        # [1, MB]. Positions below ws (a prefix-cache hit's adopted
        # region, whose pages already hold these exact values and may
        # be SHARED) route to the trash block instead of rewriting —
        # duplicate trash indices are fine, nothing reads it unmasked.
        pos = t[:, None] + jnp.arange(n_tokens, dtype=t.dtype)[None, :]
        blk = jnp.take_along_axis(bt, pos // block_size, axis=1)
        blk = jnp.where(pos >= ws, blk, 0)
        off = pos % block_size                        # [1, C]
        pool = pool.at[blk, 0, :, off, :].set(k.astype(pool.dtype))
        return pool.at[blk, 1, :, off, :].set(v.astype(pool.dtype))
    return paged_prefill_chunk_kv


def _make_prefill_scatter(start_block, n_blocks, block_size):
    def paged_prefill_scatter(pool, row_cache, blks):
        # row_cache [2, 1, H, S, D] (dense single-row scratch) -> pages
        # [start_block, start_block + n_blocks) of this sequence (a
        # prefix-cache hit skips the shared prefix pages)
        lo = start_block * block_size
        seg = row_cache[:, 0, :, lo:lo + n_blocks * block_size, :]
        two, H, _, D = seg.shape
        seg = seg.reshape(two, H, n_blocks, block_size, D)
        seg = jnp.transpose(seg, (2, 0, 1, 3, 4))  # [n, 2, H, bs, D]
        return pool.at[blks].set(seg.astype(pool.dtype))
    return paged_prefill_scatter


class PagedLayerCache:
    """One layer's view of the paged cache — the object that rides in
    the ``caches=`` list of FusedMultiTransformer.forward. Duck-typed
    protocol: ``is_paged`` marks it, ``decode(q, k, v, t)`` appends one
    token per row through the block table and returns the attention
    output [B, 1, nh, hd]."""

    is_paged = True

    def __init__(self, cache: "PagedKVCache", layer: int,
                 shard: int = 0):
        self._cache = cache
        self._layer = layer
        self._shard = int(shard)
        self._pi = cache.pool_index(layer, self._shard)

    def shard(self, s: int) -> "PagedLayerCache":
        """This layer's view of mp shard ``s`` — the per-shard cache
        object a ShardedServingCore drives with its own head slice of
        q/k/v (replicated metadata, shard-local pages)."""
        return PagedLayerCache(self._cache, self._layer, shard=s)

    @property
    def pool(self) -> Tensor:
        return self._cache.pools[self._pi]

    @property
    def kv_scales(self) -> Optional[Tensor]:
        """Per-page dequantization scales (int8 pools), else None."""
        c = self._cache
        return c.scales[self._pi] if c.quantized else None

    @property
    def shape(self):
        return self.pool.shape

    def decode(self, q, k, v, t, use_kernel: bool = False):
        """q/k/v: [B, L, H, D] Tensors (L == 1 is the plain decode
        step; L > 1 is the multi-query speculative-verification step —
        row b's L tokens land at positions t[b] .. t[b]+L-1 and each
        query attends causally up to its own position). t: traced
        int32 [B] per-row START positions (== current length). Appends
        k/v in place (the pool Tensor is rebound) and returns the
        attention output [B, L, nh, hd]. PRECONDITION:
        ``ensure(row, t[row]+L, write_from=t[row])`` for every active
        row — every write position must be covered by the row's block
        table (and shared pages in the write range COW-split).
        use_kernel routes to the Pallas paged kernel (TPU); otherwise
        a pure-jnp gather + the SAME masked-sdpa codepath the dense
        ragged decode uses, so paged and dense CPU decode are
        bit-identical when page capacity == dense max_len."""
        import jax as _jax
        c = self._cache
        B, L = q.shape[0], q.shape[1]
        if B != c.max_seqs:
            raise ValueError(f"batch {B} != cache max_seqs {c.max_seqs}")
        if c.mp > 1 and int(q.shape[2]) != c.heads_per_shard:
            # a full-head call against a sharded pool would scatter
            # H rows into an H/mp page (or, worse, read as GQA in the
            # kernel): fail loudly with the fix
            raise ValueError(
                f"sharded pool (mp={c.mp}) expects the per-shard "
                f"head slice ({c.heads_per_shard} heads), got "
                f"{int(q.shape[2])} — drive a sharded cache through "
                f"a ShardedServingCore (per-shard qkv), not a "
                f"single-chip model")
        if self._pi == 0 and not isinstance(t, _jax.core.Tracer):
            # eager: catch a forgotten ensure() — the write would land
            # in the shared trash block and silently corrupt this
            # row's attention (rows with NO blocks at t == 0 are
            # inactive by convention and write trash on purpose).
            # Layer 0 only: every layer shares t and the tables, and
            # reading t costs a device->host sync on TPU. Under jit t
            # is a tracer and the precondition is the caller's
            # contract.
            tv = np.asarray(t)
            for row in range(B):
                if c._decode_masked is not None and \
                        c._decode_masked[row]:
                    continue  # row presents a trash table this step
                have = len(c.seq_blocks[row])
                pos = int(tv[row])
                if (have and c.blocks_needed(pos + L) > have) or \
                        (not have and pos > 0):
                    raise ValueError(
                        f"decode of {L} token(s) at position {pos} of "
                        f"row {row} is not covered by its {have} "
                        f"allocated block(s); call "
                        f"ensure(row, position+{L}) first")
        bt = c.bt_tensor()
        tt = Tensor(t)
        new_sc = None
        if c.quantized:
            impl = (_make_append_q(c.block_size) if L == 1
                    else _make_append_multi_q(c.block_size, L))
            new_pool, new_sc = apply(
                impl, (self.pool, self.kv_scales, k, v, tt, bt),
                op_name="paged_cache_kv_q" if L == 1
                else "paged_cache_kv_multi_q")
            c.scales[self._pi] = new_sc
        elif L == 1:
            new_pool = apply(_make_append(c.block_size),
                             (self.pool, k, v, tt, bt),
                             op_name="paged_cache_kv")
        else:
            new_pool = apply(_make_append_multi(c.block_size, L),
                             (self.pool, k, v, tt, bt),
                             op_name="paged_cache_kv_multi")
        c.pools[self._pi] = new_pool

        if use_kernel:
            if c.quantized:
                if L == 1:
                    def dec_q(p, sc, q_, tv, bta):
                        from ..ops.pallas.paged_attention import \
                            paged_attention
                        return paged_attention(q_[:, 0], p, bta,
                                               tv + 1,
                                               kv_scales=sc)[:, None]
                    return apply(dec_q, (new_pool, new_sc, q, tt, bt),
                                 op_name="paged_attention_q")

                def dec_multi_q(p, sc, q_, tv, bta):
                    from ..ops.pallas.paged_attention import \
                        paged_attention_multi
                    return paged_attention_multi(q_, p, bta, tv + L,
                                                 kv_scales=sc)
                return apply(dec_multi_q,
                             (new_pool, new_sc, q, tt, bt),
                             op_name="paged_attention_multi_q")
            if L == 1:
                def dec(p, q_, tv, bta):
                    from ..ops.pallas.paged_attention import \
                        paged_attention
                    return paged_attention(q_[:, 0], p, bta,
                                           tv + 1)[:, None]
                return apply(dec, (new_pool, q, tt, bt),
                             op_name="paged_attention")

            def dec_multi(p, q_, tv, bta):
                from ..ops.pallas.paged_attention import \
                    paged_attention_multi
                return paged_attention_multi(q_, p, bta, tv + L)
            return apply(dec_multi, (new_pool, q, tt, bt),
                         op_name="paged_attention_multi")

        # CPU / fallback: gather pages dense (the kernel module's
        # gather, so both paths share one layout definition —
        # quantized pools dequantize inside the gather), then
        # mirror the dense ragged decode branch (same mask, same sdpa
        # op executable). For L > 1 the L axis FOLDS INTO THE BATCH
        # axis (virtual rows [b*L+i] share slot b's pages, query i at
        # position t[b]+i): the sdpa executable then has the exact
        # q-length-1 shape of the plain decode step, which is what
        # makes a multi-token verification bit-identical to L single
        # steps — an [L, S] attention fuses with different reduction
        # grouping than L [1, S] attentions (~1 ulp), the same
        # lowering trap as scheduler.MIN_PREFILL_SUFFIX_ROWS.
        from ..nn import functional as F
        from ..ops.pallas.paged_attention import gather_pages
        gargs = (new_pool, bt) if new_sc is None \
            else (new_pool, bt, new_sc)
        k_full, v_full = apply(gather_pages, gargs,
                               op_name="paged_gather")
        S = k_full.shape[1]
        if L == 1:
            qpos = (t[:, None, None, None]
                    + jnp.arange(1)[None, None, :, None])
            kpos = jnp.arange(S)[None, None, None, :]
            mask = Tensor(jnp.where(kpos <= qpos, 0.0, -1e30)
                          .astype(jnp.float32))
            return F.scaled_dot_product_attention(q, k_full, v_full,
                                                  attn_mask=mask)

        qf = apply(lambda a: a.reshape((B * L, 1) + a.shape[2:]),
                   (q,), op_name="spec_fold_q")
        kf = apply(lambda a: jnp.repeat(a, L, axis=0), (k_full,),
                   op_name="spec_fold_kv")
        vf = apply(lambda a: jnp.repeat(a, L, axis=0), (v_full,),
                   op_name="spec_fold_kv")
        tf = (jnp.repeat(t, L) + jnp.tile(jnp.arange(L, dtype=t.dtype),
                                          B))
        qpos = tf[:, None, None, None]
        kpos = jnp.arange(S)[None, None, None, :]
        mask = Tensor(jnp.where(kpos <= qpos, 0.0, -1e30)
                      .astype(jnp.float32))
        out = F.scaled_dot_product_attention(qf, kf, vf, attn_mask=mask)
        return apply(lambda a: a.reshape((B, L) + a.shape[2:]),
                     (out,), op_name="spec_unfold")


class PagedPrefillView:
    """One layer's CHUNKED-PREFILL view of a single slot — the object
    that rides in ``caches=`` for a batch-1 chunk call
    (``PagedKVCache.prefill_views``). Same duck-typed protocol as
    PagedLayerCache (``is_paged`` + ``decode``), but the chunk's C
    rows append STRAIGHT INTO the slot's pages (no dense scratch, no
    scatter pass) and then attend over them with a per-row causal
    mask at absolute positions ``t[0] + i``.

    Numerics contract (what keeps chunked prefill bit-identical to
    dense scratch prefill): the CPU path runs the chunk as ONE
    multi-row masked sdpa — the same executable family as the dense
    prefill branch — and must NOT fold rows into the batch axis the
    way the speculative multi path does: a row computed at q-length 1
    lowers to a GEMV with different accumulation than the same row
    inside a multi-row call (scheduler.MIN_PREFILL_SUFFIX_ROWS), while
    multi-row sdpa results are per-row invariant to BOTH the chunk
    length and the masked key extent. On TPU the Pallas
    ``paged_attention_prefill`` kernel serves the same contract
    through the scalar-prefetch block table."""

    is_paged = True

    def __init__(self, cache: "PagedKVCache", layer: int, slot: int,
                 write_start: int = 0, shard: int = 0):
        self._cache = cache
        self._layer = layer
        self._slot = slot
        self._shard = int(shard)
        self._pi = cache.pool_index(layer, self._shard)
        # positions below write_start are an adopted (possibly shared)
        # prefix whose pages already hold these exact K/V — recomputed
        # rows there attend but do not write (see _make_append_chunk)
        self._write_start = int(write_start)

    def shard(self, s: int) -> "PagedPrefillView":
        """This (layer, slot) chunk view of mp shard ``s``."""
        return PagedPrefillView(self._cache, self._layer, self._slot,
                                write_start=self._write_start, shard=s)

    @property
    def pool(self) -> Tensor:
        return self._cache.pools[self._pi]

    @property
    def kv_scales(self) -> Optional[Tensor]:
        c = self._cache
        return c.scales[self._pi] if c.quantized else None

    @property
    def shape(self):
        return self.pool.shape

    def decode(self, q, k, v, t, use_kernel: bool = False):
        """q/k/v: [1, C, H, D] — one prompt chunk for this view's
        slot, starting at absolute position t[0] (traced int32 [1]).
        Appends the chunk's K/V through the slot's block-table row
        (skipping positions below ``write_start``) and returns the
        chunk's attention output [1, C, nh, hd]. PRECONDITION:
        ``ensure(slot, t[0]+C, write_from=t[0], start_block=...)`` —
        every write position covered and COW-split."""
        import jax as _jax
        c = self._cache
        B, C = q.shape[0], q.shape[1]
        if B != 1:
            raise ValueError(
                f"chunk prefill is a batch-1 call, got batch {B}")
        if c.mp > 1 and int(q.shape[2]) != c.heads_per_shard:
            raise ValueError(
                f"sharded pool (mp={c.mp}) expects the per-shard "
                f"head slice ({c.heads_per_shard} heads), got "
                f"{int(q.shape[2])} — drive a sharded cache through "
                f"a ShardedServingCore")
        if self._pi == 0 and not isinstance(t, _jax.core.Tracer):
            pos = int(np.asarray(t).reshape(-1)[0])
            have = len(c.seq_blocks[self._slot])
            if c.blocks_needed(pos + C) > have:
                raise ValueError(
                    f"prefill chunk [{pos}, {pos + C}) of slot "
                    f"{self._slot} is not covered by its {have} "
                    f"allocated block(s); call ensure() first")
        bt = c.bt_row_tensor(self._slot)
        tt = Tensor(t)
        ws = Tensor(jnp.asarray([self._write_start], jnp.int32))
        new_sc = None
        if c.quantized:
            new_pool, new_sc = apply(
                _make_append_chunk_q(c.block_size, C),
                (self.pool, self.kv_scales, k, v, tt, bt, ws),
                op_name="paged_prefill_chunk_kv_q")
            c.scales[self._pi] = new_sc
        else:
            new_pool = apply(_make_append_chunk(c.block_size, C),
                             (self.pool, k, v, tt, bt, ws),
                             op_name="paged_prefill_chunk_kv")
        c.pools[self._pi] = new_pool

        if use_kernel:
            if c.quantized:
                def att_q(p, sc, q_, tv, bta):
                    from ..ops.pallas.paged_attention import \
                        paged_attention_prefill
                    return paged_attention_prefill(q_, p, bta, tv,
                                                   kv_scales=sc)
                return apply(att_q, (new_pool, new_sc, q, tt, bt),
                             op_name="paged_attention_prefill_q")

            def att(p, q_, tv, bta):
                from ..ops.pallas.paged_attention import \
                    paged_attention_prefill
                return paged_attention_prefill(q_, p, bta, tv)
            return apply(att, (new_pool, q, tt, bt),
                         op_name="paged_attention_prefill")

        # CPU / fallback: gather the slot's pages dense and run the
        # chunk as ONE multi-row masked sdpa (see class docstring; the
        # mask mirrors the dense prefill branch's construction)
        from ..nn import functional as F
        from ..ops.pallas.paged_attention import gather_pages
        gargs = (new_pool, bt) if new_sc is None \
            else (new_pool, bt, new_sc)
        k_full, v_full = apply(gather_pages, gargs,
                               op_name="paged_gather")
        S = k_full.shape[1]
        qpos = t[0] + jnp.arange(C)[:, None]
        kpos = jnp.arange(S)[None, :]
        mask = Tensor(jnp.where(kpos <= qpos, 0.0, -1e30)
                      .astype(jnp.float32))
        return F.scaled_dot_product_attention(q, k_full, v_full,
                                              attn_mask=mask)


def _ragged_append(pool, k, v, blk, off):
    # packed mixed-batch append: row r of k/v [1, R, H, D] lands at
    # pool[blk[r], :, :, off[r], :] — every segment's writes (prefill
    # chunks through their slots' tables, decode rows through the
    # masked batch table) in ONE scatter. Rows routed to the trash
    # block (adopted-prefix positions, masked decode rows) may collide
    # there; nothing reads it unmasked.
    pool = pool.at[blk, 0, :, off, :].set(k[0].astype(pool.dtype))
    return pool.at[blk, 1, :, off, :].set(v[0].astype(pool.dtype))


class _RaggedLayout:
    """Host-side descriptors for ONE mixed ragged model call, shared
    by every layer's PagedRaggedView: the packed append routing
    (blk/off per row), the per-sequence (q_len, kv_len, block-table
    row) descriptors the kernel consumes, and the segment spans the
    CPU path decomposes along. Built once per launch from the cache's
    CURRENT tables — the caller must have ensure()d coverage and set
    the decode mask first."""

    __slots__ = ("segs", "q_lens", "blk", "off", "kv_lens", "bt_all",
                 "tile_q", "tile_kv", "total_rows", "blk_np",
                 "off_np")

    def __init__(self, cache: "PagedKVCache", segments, tile_q=None,
                 tile_kv=None):
        bs = cache.block_size
        tbl = cache.block_tables
        masked_tbl = tbl
        if cache._decode_masked is not None and \
                cache._decode_masked.any():
            masked_tbl = tbl.copy()
            masked_tbl[cache._decode_masked] = 0
        self.segs: List[tuple] = []
        q_lens: List[int] = []
        kv_lens: List[int] = []
        bt_rows: List[np.ndarray] = []
        blk: List[np.ndarray] = []
        off: List[np.ndarray] = []
        lo = 0
        for seg in segments:
            kind = seg[0]
            if kind == "prefill":
                _, slot, start, length, write_start = seg
                pos = np.arange(start, start + length)
                b = tbl[slot][pos // bs]
                # adopted shared-prefix positions route to trash: the
                # pages already hold these exact values and may be
                # shared (same rule as _make_append_chunk)
                blk.append(np.where(pos >= write_start, b, 0))
                off.append(pos % bs)
                q_lens.append(int(length))
                kv_lens.append(int(start) + int(length))
                bt_rows.append(tbl[slot])
                self.segs.append(("prefill", lo, lo + length, slot,
                                  int(start)))
                lo += length
            elif kind == "decode":
                _, lens, L = seg
                if L < 1:
                    raise ValueError("decode segments carry >= 1 "
                                     "query row per slot")
                lens = np.asarray(lens, np.int64)
                B = lens.shape[0]
                # masked rows (mid-prefill / fresh slots riding along
                # at their real lens) may sit at page capacity: clamp
                # their table column — they present all-trash rows, so
                # any in-range column lands the write in block 0, and
                # covered (unmasked) rows are never clamped
                cols = masked_tbl.shape[1]
                if L == 1:
                    b = masked_tbl[np.arange(B),
                                   np.minimum(lens // bs, cols - 1)]
                    blk.append(b)
                    off.append(lens % bs)
                else:
                    # multi-query verify rows: slot b's L tokens land
                    # at positions lens[b] .. lens[b]+L-1 through the
                    # DECODE-MASKED table (masked rows write trash),
                    # packed row-major as [b*L + i]
                    pos = lens[:, None] + np.arange(L)[None, :]
                    b = masked_tbl[np.arange(B)[:, None],
                                   np.minimum(pos // bs, cols - 1)]
                    blk.append(b.reshape(-1))
                    off.append((pos % bs).reshape(-1))
                q_lens.extend([L] * B)
                kv_lens.extend((lens + L).tolist())
                bt_rows.extend(masked_tbl)
                self.segs.append(("decode", lo, lo + B * L,
                                  lens.astype(np.int32), L))
                lo += B * L
            else:
                raise ValueError(f"unknown ragged segment kind {kind!r}")
        self.total_rows = lo
        self.q_lens = tuple(q_lens)
        # host copies of the scatter routing, kept for the compiled
        # sharded step: it re-packs them with bucket-pad rows (routed
        # to the trash block) BEFORE feeding them in as operands, so
        # the padding never touches device data
        self.blk_np = np.concatenate(blk).astype(np.int32)
        self.off_np = np.concatenate(off).astype(np.int32)
        self.blk = Tensor(jnp.asarray(self.blk_np))
        self.off = Tensor(jnp.asarray(self.off_np))
        self.kv_lens = Tensor(jnp.asarray(kv_lens, jnp.int32))
        self.bt_all = Tensor(jnp.asarray(np.stack(bt_rows), jnp.int32))
        self.tile_q = tile_q
        self.tile_kv = tile_kv


class PagedRaggedView:
    """One layer's MIXED-BATCH view: the object that rides in
    ``caches=`` for the scheduler's ragged step — prefill chunks of
    several slots AND the fused decode rows packed into one
    [1, total_rows, d] model call. Same duck-typed protocol as
    PagedLayerCache (``is_paged`` + ``decode``): the packed K/V append
    is ONE scatter through the precomputed routing, and the attention
    is ONE ``paged_attention_ragged`` launch on the kernel path — the
    dispatch-count collapse this view exists for.

    Numerics contract (CPU bit-identity — the folding rules hoisted
    from PagedLayerCache/PagedPrefillView): on the CPU fallback the
    packed batch DECOMPOSES back into exactly the executables the
    per-phase paths run — each prefill segment one multi-row masked
    sdpa over its slot's gathered pages (never folded to 1-row calls:
    the GEMV trap of scheduler.MIN_PREFILL_SUFFIX_ROWS), the decode
    rows one batch-of-1-row sdpa over the masked batch table (the
    plain decode executable) — so a ragged step's streams are
    BIT-IDENTICAL to the per-phase launches. The non-attention ops
    (LN/QKV/FFN) ride the packed row batch, which is safe by the same
    established invariance chunked prefill rests on: per-row results
    of multi-row calls do not depend on how many rows share the
    call."""

    is_paged = True

    def __init__(self, cache: "PagedKVCache", layer: int,
                 layout: _RaggedLayout, shard: int = 0):
        self._cache = cache
        self._layer = layer
        self._shard = int(shard)
        self._pi = cache.pool_index(layer, self._shard)
        self._layout = layout

    def shard(self, s: int) -> "PagedRaggedView":
        """This layer's ragged view of mp shard ``s`` — the SAME
        layout object rides along (the routing descriptors are
        replicated metadata, shard-invariant by construction)."""
        return PagedRaggedView(self._cache, self._layer, self._layout,
                               shard=s)

    @property
    def pool(self) -> Tensor:
        return self._cache.pools[self._pi]

    @property
    def kv_scales(self) -> Optional[Tensor]:
        c = self._cache
        return c.scales[self._pi] if c.quantized else None

    @property
    def shape(self):
        return self.pool.shape

    def decode(self, q, k, v, t, use_kernel: bool = False):
        """q/k/v: [1, R, H, D] — the packed mixed batch. ``t`` is
        ignored: the layout carries every row's absolute position.
        PRECONDITION: every segment's write range is covered and
        COW-split (the scheduler's planning pass ensure()s chunk by
        chunk) and the decode mask is set."""
        c = self._cache
        lay = self._layout
        if q.shape[0] != 1 or q.shape[1] != lay.total_rows:
            raise ValueError(
                f"ragged call expects [1, {lay.total_rows}, H, D], "
                f"got {tuple(q.shape)}")
        if c.mp > 1 and int(q.shape[2]) != c.heads_per_shard:
            raise ValueError(
                f"sharded pool (mp={c.mp}) expects the per-shard "
                f"head slice ({c.heads_per_shard} heads), got "
                f"{int(q.shape[2])} — drive a sharded cache through "
                f"a ShardedServingCore")
        new_sc = None
        if c.quantized:
            new_pool, new_sc = apply(
                _ragged_append_q,
                (self.pool, self.kv_scales, k, v, lay.blk, lay.off),
                op_name="paged_ragged_append_q")
            c.scales[self._pi] = new_sc
        else:
            new_pool = apply(_ragged_append,
                             (self.pool, k, v, lay.blk, lay.off),
                             op_name="paged_ragged_append")
        c.pools[self._pi] = new_pool

        if use_kernel:
            q_lens, tile_q, tile_kv = (lay.q_lens, lay.tile_q,
                                       lay.tile_kv)

            if c.quantized:
                def att_q(p, sc, q_, kvl, bts):
                    from ..ops.pallas.paged_attention import \
                        paged_attention_ragged
                    return paged_attention_ragged(
                        q_[0], p, bts, q_lens, kvl, tile_q=tile_q,
                        tile_kv=tile_kv, kv_scales=sc)[None]
                return apply(att_q, (new_pool, new_sc, q, lay.kv_lens,
                                     lay.bt_all),
                             op_name="paged_attention_ragged_q")

            def att(p, q_, kvl, bts):
                from ..ops.pallas.paged_attention import \
                    paged_attention_ragged
                return paged_attention_ragged(
                    q_[0], p, bts, q_lens, kvl, tile_q=tile_q,
                    tile_kv=tile_kv)[None]
            return apply(att, (new_pool, q, lay.kv_lens, lay.bt_all),
                         op_name="paged_attention_ragged")

        # CPU / fallback: decompose into the per-phase executables
        # (see class docstring) and re-pack the outputs in row order
        from ..nn import functional as F
        from ..ops.pallas.paged_attention import gather_pages
        outs = []
        for seg in lay.segs:
            kind, lo, hi = seg[0], seg[1], seg[2]
            if kind == "prefill":
                slot, start = seg[3], seg[4]
                C = hi - lo
                qs = Tensor(q.data[:, lo:hi])
                bt = c.bt_row_tensor(slot)
                gargs = (new_pool, bt) if new_sc is None \
                    else (new_pool, bt, new_sc)
                k_full, v_full = apply(gather_pages, gargs,
                                       op_name="paged_gather")
                S = k_full.shape[1]
                qpos = start + jnp.arange(C)[:, None]
                kpos = jnp.arange(S)[None, :]
                mask = Tensor(jnp.where(kpos <= qpos, 0.0, -1e30)
                              .astype(jnp.float32))
                out = F.scaled_dot_product_attention(
                    qs, k_full, v_full, attn_mask=mask)
                outs.append(out.data[0])
            else:
                lens, L = seg[3], seg[4]
                bt = c.bt_tensor()
                gargs = (new_pool, bt) if new_sc is None \
                    else (new_pool, bt, new_sc)
                k_full, v_full = apply(gather_pages, gargs,
                                       op_name="paged_gather")
                S = k_full.shape[1]
                if L == 1:
                    B = hi - lo
                    qd = Tensor(q.data[0, lo:hi][:, None])  # [B,1,H,D]
                    tj = jnp.asarray(lens, jnp.int32)
                    qpos = (tj[:, None, None, None]
                            + jnp.arange(1)[None, None, :, None])
                    kpos = jnp.arange(S)[None, None, None, :]
                    mask = Tensor(jnp.where(kpos <= qpos, 0.0, -1e30)
                                  .astype(jnp.float32))
                    out = F.scaled_dot_product_attention(
                        qd, k_full, v_full, attn_mask=mask)
                    outs.append(out.data[:, 0])
                else:
                    # multi-query verify rows: fold the L axis into
                    # the batch axis, exactly the PagedLayerCache
                    # L > 1 fallback — same q-length-1 sdpa
                    # executable, so a packed verify stays
                    # bit-identical to the per-phase step_multi call
                    B = (hi - lo) // L
                    qd = Tensor(q.data[0, lo:hi][:, None])  # [B*L,1,..]
                    kf = Tensor(jnp.repeat(k_full.data, L, axis=0))
                    vf = Tensor(jnp.repeat(v_full.data, L, axis=0))
                    tj = jnp.asarray(lens, jnp.int32)
                    tf = (jnp.repeat(tj, L)
                          + jnp.tile(jnp.arange(L, dtype=jnp.int32),
                                     B))
                    qpos = tf[:, None, None, None]
                    kpos = jnp.arange(S)[None, None, None, :]
                    mask = Tensor(jnp.where(kpos <= qpos, 0.0, -1e30)
                                  .astype(jnp.float32))
                    out = F.scaled_dot_product_attention(
                        qd, kf, vf, attn_mask=mask)
                    outs.append(out.data[:, 0])
        return Tensor(jnp.concatenate(outs, axis=0)[None])


class PagedKVCache:
    """Per-layer block pools + one block allocator + per-sequence block
    tables. ``views`` is the list consumed as ``caches=`` by the fused
    decoder; allocation/free/fork are host-side (numpy free list), the
    pool writes are jnp scatters."""

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 block_size: int, num_blocks: int, max_seqs: int,
                 max_blocks_per_seq: Optional[int] = None,
                 dtype: str = "float32", prefix_cache: bool = False,
                 mp: int = 1, shard_devices=None):
        import paddle_tpu as paddle
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.max_seqs = int(max_seqs)
        # TENSOR-PARALLEL SHARDING (``mp`` > 1): the pool is
        # partitioned over attention heads — shard s stores
        # [num_blocks, 2, H/mp, bs, D] (pools[layer * mp + s]), the
        # head slice [s*H/mp, (s+1)*H/mp). EVERYTHING ELSE in this
        # class — allocator, block tables, refcounts, chain-hash
        # index, tenant charges, decode mask — is host-side metadata
        # REPLICATED across shards: block ids and lifecycle are
        # shard-invariant, so admission, quotas, WFQ, prefix hashing,
        # COW, snapshots and the journal run byte-for-byte unchanged.
        # Each shard's pages live on its own device
        # (``shard_devices``, parallel/mesh.py serving_shard_devices)
        # and only the per-shard model (ShardedServingCore) writes /
        # reads them, with its own head-slice of q/k/v. The SNAPSHOT
        # and MIGRATION wire formats stay CANONICAL (full-head pages,
        # the mp=1 layout): shards concatenate on the head axis going
        # out and re-slice coming in, which is what makes snapshots
        # and kv_slices portable across mesh widths (mp=N <-> mp=1).
        self.mp = int(mp)
        if self.mp < 1:
            raise ValueError(f"mp must be >= 1, got {mp}")
        if self.num_heads % self.mp:
            raise ValueError(
                f"num_heads {self.num_heads} must divide evenly over "
                f"mp={self.mp} tensor-parallel shards")
        if self.mp > 1 and shard_devices is None:
            from ..parallel.mesh import serving_shard_devices
            shard_devices = serving_shard_devices(self.mp)
        if shard_devices is not None and len(shard_devices) < self.mp:
            raise ValueError(
                f"need {self.mp} shard devices, got "
                f"{len(shard_devices)}")
        self.shard_devices = (list(shard_devices[:self.mp])
                              if shard_devices is not None else None)
        if max_blocks_per_seq is None:
            max_blocks_per_seq = self.num_blocks - 1
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.dtype = dtype
        # QUANTIZED POOLS (``dtype="int8"``): payload pages hold int8
        # and every page carries per-(position, head) dequantization
        # scales in ``self.scales`` — allocator metadata that moves
        # with the page through COW copies, snapshots and restores.
        # Quantization happens at page-write time inside the append
        # ops (_make_append_q and friends); every read path
        # dequantizes (the ragged kernel in-register via scalar
        # prefetch, the jnp fallbacks inside gather_pages). See the
        # module-level note above _quant_rows for why scales are
        # per-row: it is what keeps the quantized payload a pure
        # function of the token stream, so chunking cannot change the
        # bytes and prefix-hash adoption stays exact.
        self.quantized = (str(dtype) == "int8")
        self.prefix_cache = bool(prefix_cache)
        # chained-hash block index (prefix caching): both maps stay in
        # lockstep — a block is indexed iff hash_to_block[h] == b and
        # block_hash[b] == h. Reclaim drops both via _on_reclaim.
        self._hash_to_block: Dict[bytes, int] = {}
        self._block_hash: Dict[int, bytes] = {}
        self.allocator = BlockAllocator(self.num_blocks,
                                        on_reclaim=self._on_reclaim)
        # actionable allocator errors: BlockOOM carries the occupancy
        # breakdown (string AND the structured pool_occupancy dict on
        # .details), ref/free misuse names the owning slot(s)
        self.allocator.context = self._pool_context
        self.allocator.context_data = self.pool_occupancy
        self.allocator.describe = self._describe_block
        # content fingerprints for the "never written in place" audit
        # (check_invariants): blocks that must be immutable — shared
        # (refcount >= 2), hash-indexed, or parked cached-free — are
        # hashed at audit time and re-verified while they stay in that
        # state; fork/adopt re-shares drop the entry (fresh epoch)
        self._audit_fp: Dict[int, bytes] = {}
        # pool storage: ``pools[layer * mp + shard]`` — for mp == 1
        # exactly the old one-entry-per-layer list (shape and device
        # placement untouched), for mp > 1 each entry is one shard's
        # head slice committed to its shard device. The flat list
        # keeps every uniform whole-pool pass (COW copy, snapshot
        # pull, deep-audit fingerprint) working unchanged over all
        # layer x shard entries.
        Hs = self.heads_per_shard
        self.pools: List[Tensor] = [
            self._place(paddle.zeros(
                [self.num_blocks, 2, Hs, self.block_size,
                 self.head_dim], dtype=dtype), pi)
            for pi in range(self.num_layers * self.mp)]
        # per-page dequantization scales (int8 pools only):
        # [num_blocks, 2, heads/mp, block_size] float32 per
        # layer x shard — zero-init dequantizes to exact zeros,
        # matching a zeroed pool
        self.scales: Optional[List[Tensor]] = [
            self._place(paddle.zeros(
                [self.num_blocks, 2, Hs, self.block_size],
                dtype="float32"), pi)
            for pi in range(self.num_layers * self.mp)] \
            if self.quantized else None
        # all entries at the trash block until allocated
        self.block_tables = np.zeros(
            (self.max_seqs, self.max_blocks_per_seq), np.int32)
        self.seq_blocks: List[List[int]] = [[] for _ in
                                            range(self.max_seqs)]
        self.views = [PagedLayerCache(self, i)
                      for i in range(self.num_layers)]
        self._bt_cached: Optional[Tensor] = None
        self._bt_rows_cached: Dict[int, Tensor] = {}
        # rows whose table presents as ALL-TRASH to the fused decode
        # step (mid-prefill slots: they own real pages, but a decode
        # append at lens==0 through them would corrupt position 0)
        self._decode_masked: Optional[np.ndarray] = None
        self.peak_blocks_used = 0
        # multi-tenant attribution (scheduler.py): which tenant each
        # slot is serving, and the per-tenant block CHARGE. The charge
        # policy is ONE CHARGE PER TABLE REFERENCE — a block shared by
        # k slots charges each sharer's tenant 1 (not 1/k, not
        # owner-only), so a tenant's charge is a pure function of ITS
        # OWN slots' tables: no neighbor's adopt/release/preempt can
        # ever move it (fractional charging would raise your charge
        # when a sharer releases; owner-pays would transfer a block
        # onto you when the owner leaves — both are cross-tenant
        # interference channels). Ground truth audited by
        # check_invariants: charge[t] == sum of len(seq_blocks[s])
        # over slots with seq_tenant[s] == t, and the total equals the
        # allocator's total refcount over usable blocks.
        self.seq_tenant: List[Optional[str]] = [None] * self.max_seqs
        self._tenant_charge: Dict[Optional[str], int] = {}

    # -- construction -------------------------------------------------
    @classmethod
    def for_model(cls, model, block_size, num_blocks, max_seqs,
                  max_blocks_per_seq=None, dtype="float32",
                  prefix_cache=False):
        """Build a pool matching ``model``'s geometry — INCLUDING its
        tensor-parallel layout: a ShardedServingCore carries ``mp``
        and ``shard_devices``, so the engines get a matching sharded
        pool without a single signature change."""
        return cls(model.num_layers, model.num_heads, model.head_dim,
                   block_size, num_blocks, max_seqs,
                   max_blocks_per_seq=max_blocks_per_seq, dtype=dtype,
                   prefix_cache=prefix_cache,
                   mp=getattr(model, "mp", 1),
                   shard_devices=getattr(model, "shard_devices", None))

    def _place(self, t: Tensor, pi: int) -> Tensor:
        """Commit a pool/scale entry to its shard's device (mp > 1);
        the mp == 1 path is byte-for-byte the old single-chip one —
        uncommitted, exactly as paddle.zeros made it."""
        if self.mp == 1 or self.shard_devices is None:
            return t
        import jax as _jax
        dev = self.shard_devices[pi % self.mp]
        return Tensor(_jax.device_put(t.data, dev))

    # -- geometry -----------------------------------------------------
    @property
    def heads_per_shard(self) -> int:
        """Attention heads each mp shard stores (== num_heads at
        mp 1); shard s holds heads [s*H/mp, (s+1)*H/mp)."""
        return self.num_heads // self.mp

    def pool_index(self, layer: int, shard: int = 0) -> int:
        """Index of (layer, shard)'s entry in the flat ``pools`` /
        ``scales`` lists."""
        return layer * self.mp + shard

    def rebind_shard_pools(self, layer: int, global_pool,
                           global_scales=None) -> None:
        """Rebind this layer's per-shard pool entries from a GLOBAL
        head-sharded array (the compiled step's donated output on the
        serving ``Mesh(("mp",))``). Zero-copy both directions: the
        global array's addressable shards ARE per-device buffers, so
        unwrapping them back into the flat ``pools`` list hands every
        eager path between compiled calls (COW block splits, prefill
        scatters, snapshot/export readback) ordinary committed
        per-shard arrays — the device-resident pool protocol with
        host readback only at those boundaries. MUST run immediately
        after the compiled call: donation invalidated the previous
        buffers. Shards sort by their head-axis slice start so entry
        ``pool_index(layer, s)`` always holds heads [s*Hs, (s+1)*Hs).
        """
        shards = sorted(global_pool.addressable_shards,
                        key=lambda sh: sh.index[2].start or 0)
        for s, sh in enumerate(shards):
            self.pools[self.pool_index(layer, s)] = Tensor(sh.data)
        if global_scales is not None:
            sshards = sorted(global_scales.addressable_shards,
                             key=lambda sh: sh.index[2].start or 0)
            for s, sh in enumerate(sshards):
                self.scales[self.pool_index(layer, s)] = \
                    Tensor(sh.data)

    @property
    def capacity_per_seq(self) -> int:
        return self.max_blocks_per_seq * self.block_size

    def blocks_needed(self, length: int) -> int:
        return -(-int(length) // self.block_size)

    @property
    def blocks_in_use(self) -> int:
        return self.num_blocks - 1 - self.allocator.num_free

    def pool_bytes(self) -> int:
        """PER-SHARD pool bytes — what ONE device's HBM actually
        holds. At mp == 1 this is the whole pool (unchanged); on a
        sharded pool each device holds 1/mp of the payload (the
        headroom multiplication the sharding buys — a cost report
        that summed all shards would overstate per-chip HBM by mp x;
        ``pool_bytes_total()`` gives the whole-mesh sum).

        itemsize off the array's own dtype: np.dtype(str(...)) has no
        parse for ml_dtypes names, so a bfloat16 pool would raise.
        Quantized pools count the scale metadata too — the honest
        byte model (a stale bf16 model would overstate density ~2x)."""
        return self.pool_bytes_total() // self.mp

    def pool_bytes_total(self) -> int:
        """Pool bytes summed across every mp shard (the whole-mesh
        footprint; == pool_bytes() at mp 1)."""
        n = sum(int(np.prod(p.shape)) * p.data.dtype.itemsize
                for p in self.pools)
        if self.quantized:
            n += sum(int(np.prod(s.shape)) * s.data.dtype.itemsize
                     for s in self.scales)
        return n

    def kv_bytes_per_token(self) -> int:
        """PER-SHARD HBM bytes one token's K/V occupies across every
        layer (2 x heads/mp x (head_dim x payload itemsize + scale
        bytes) x layers) — the KV-traffic unit of the analytic work
        model (inference/accounting.py), per DEVICE: each shard reads
        and writes only its own head slice, so MBU paired against one
        chip's peak bandwidth must price one chip's traffic. int8
        pools carry 4 scale bytes per (position, head, K|V) next to
        the int8 payload."""
        per_head = self.head_dim * self.pools[0].data.dtype.itemsize
        if self.quantized:
            per_head += self.scales[0].data.dtype.itemsize
        return int(2 * self.heads_per_shard * per_head
                   * self.num_layers)

    # -- tenant accounting --------------------------------------------
    def _charge(self, slot: int, delta: int) -> None:
        """Move ``slot``'s tenant's block charge by ``delta`` table
        references. Called by every table mutation (alloc growth,
        prefix adoption, fork, truncate, free, quarantine); a COW swap
        is charge-neutral (one reference out, one in)."""
        if delta == 0:
            return
        t = self.seq_tenant[slot]
        self._tenant_charge[t] = self._tenant_charge.get(t, 0) + delta

    def set_seq_tenant(self, slot: int, tenant: Optional[str]) -> None:
        """Attribute ``slot`` to ``tenant`` (None = unattributed). Any
        blocks the slot already holds move their charge with it."""
        old = self.seq_tenant[slot]
        if old == tenant:
            return
        held = len(self.seq_blocks[slot])
        if held:
            self._tenant_charge[old] = \
                self._tenant_charge.get(old, 0) - held
        self.seq_tenant[slot] = tenant
        if held:
            self._tenant_charge[tenant] = \
                self._tenant_charge.get(tenant, 0) + held

    def tenant_charge(self, tenant: Optional[str]) -> int:
        """Blocks currently charged to ``tenant`` (one per table
        reference its slots hold — see the policy note in __init__)."""
        return self._tenant_charge.get(tenant, 0)

    def tenant_blocks_held(self) -> Dict[Optional[str], int]:
        """{tenant: charged blocks}, nonzero entries only — the
        per-tenant occupancy histogram OOM messages and the offline
        doctor print."""
        return {t: n for t, n in self._tenant_charge.items() if n}

    # -- diagnostics ---------------------------------------------------
    def owners_of(self, block: int) -> List[int]:
        """Slots whose table holds ``block`` (error/audit paths only —
        O(max_seqs * blocks_per_seq))."""
        return [s for s in range(self.max_seqs)
                if block in self.seq_blocks[s]]

    def pool_occupancy(self, tiers_only: bool = False) -> dict:
        """STRUCTURED occupancy breakdown — the single source behind
        BlockOOM messages (``_pool_context`` renders it), the
        exception's machine-readable ``details``, the telemetry
        events every shed/OOM emits, and the engines'
        MetricsRegistry pool gauges: tier counts, owning-slot
        histogram, per-tenant blocks-held histogram.
        ``tiers_only`` skips the two histograms (an O(max_seqs) scan)
        — the per-step gauge path wants just the O(1) tier scalars."""
        a = self.allocator
        out = {
            "active": self.num_blocks - 1 - a.num_free,
            "cached_free": a.num_cached,
            "free": len(a._free),
            "usable": self.num_blocks - 1,
        }
        if self.mp > 1:
            # sharded pools report bytes HONESTLY per shard: the
            # metadata above is replicated (every shard sees the same
            # tiers), the payload is divided — a reader summing
            # per-worker reports must not count HBM mp x over
            out["mp"] = self.mp
            out["pool_bytes_per_shard"] = self.pool_bytes()
        if not tiers_only:
            out["blocks_per_slot"] = {
                s: len(bl) for s, bl in enumerate(self.seq_blocks)
                if bl}
            out["blocks_per_tenant"] = {
                t: n for t, n in self._tenant_charge.items()
                if n and t is not None}
        return out

    def _pool_context(self) -> str:
        """Occupancy breakdown appended to BlockOOM messages so an OOM
        report is actionable — ``pool_occupancy()`` rendered: tier
        counts + owning-slot histogram + (multi-tenant serving) the
        per-tenant blocks-held histogram, so the message names WHICH
        TENANT holds the pool."""
        occ = self.pool_occupancy()
        out = (f"; pool: {occ['active']} active / "
               f"{occ['cached_free']} cached-free"
               f" / {occ['free']} free of {occ['usable']}"
               f" usable; blocks per slot: "
               f"{occ['blocks_per_slot'] or '{}'}")
        if occ["blocks_per_tenant"]:
            out += f"; blocks per tenant: {occ['blocks_per_tenant']}"
        return out

    def _describe_block(self, block: int) -> str:
        owners = self.owners_of(block)
        state = ("cached-free" if block in self.allocator._cached
                 else f"refcount {int(self.allocator.refcount[block])}")
        tail = ", hash-indexed" if block in self._block_hash else ""
        own = f"owned by slot(s) {owners}" if owners else "no owner"
        tnts = sorted({self.seq_tenant[s] for s in owners
                       if self.seq_tenant[s] is not None})
        if tnts:
            own += f" of tenant(s) {tnts}"
        return f"{state}, {own}{tail}"

    def _fingerprint(self, block: int, pool_arrs) -> bytes:
        h = hashlib.blake2b(digest_size=16)
        for arr in pool_arrs:
            h.update(np.ascontiguousarray(arr[block]).tobytes())
        return h.digest()

    def check_invariants(self, lens=None, active=None,
                         deep: bool = True) -> bool:
        """Audit the pool's bookkeeping; raises AssertionError naming
        the violated invariant, returns True when clean. Verified:

          1. refcounts == block-table references: every usable block's
             refcount equals the number of slot tables holding it (a
             block appears at most once per table).
          2. partition: free list, cached-free tier and the active set
             (refcount > 0) are pairwise disjoint and together cover
             every usable block exactly once.
          3. trash block 0: refcount pinned at 1, never in a table,
             never in either free tier, never hash-indexed.
          4. device tables mirror host state: block_tables[slot] is
             seq_blocks[slot] then trash.
          5. hash index: _hash_to_block and _block_hash are inverse
             maps, and every indexed block is live (refcount > 0) or
             parked cached-free — the index never points at a
             free-list block.
          6. cached-free blocks are refcount-0 and hash-indexed (the
             second-chance tier exists only for resurrectable content).
          7. with ``lens``/``active`` (the engine's view): every
             active slot's table covers blocks_needed(lens[slot]).
          8. ``deep``: immutable-content audit — blocks that must not
             be written in place (refcount >= 2 shared pages, hash-
             indexed pages, cached-free pages) are content-fingerprinted
             and re-verified against the previous audit while they
             remain in that state; an in-place write to a shared or
             indexed page trips it. (Writers must COW-split first —
             ensure()'s write-range split.)
          9. tenant quota bookkeeping: the incremental per-tenant
             block charges (_tenant_charge) equal the slot tables'
             ground truth (one charge per reference held by each
             tenant's slots) and their total equals the allocator's
             total refcount over usable blocks — a growth path that
             skipped the charge update cannot survive an audit.
        """
        a = self.allocator
        counts: Dict[int, int] = {}
        for slot in range(self.max_seqs):
            blocks = self.seq_blocks[slot]
            assert len(blocks) == len(set(blocks)), \
                f"slot {slot} table holds duplicate blocks: {blocks}"
            assert len(blocks) <= self.max_blocks_per_seq, \
                f"slot {slot} table over capacity"
            assert 0 not in blocks, \
                f"slot {slot} table holds the trash block"
            for b in blocks:
                counts[int(b)] = counts.get(int(b), 0) + 1
            row = self.block_tables[slot]
            assert list(row[:len(blocks)]) == [int(b) for b in blocks] \
                and not row[len(blocks):].any(), \
                f"slot {slot} device table diverges from seq_blocks"
        free_set, cached_set = set(a._free), set(a._cached)
        active_set = {b for b in range(1, self.num_blocks)
                      if a.refcount[b] > 0}
        assert a.refcount[0] == 1 and 0 not in free_set \
            and 0 not in cached_set and 0 not in self._block_hash, \
            "trash block 0 left its reserved state"
        for b in range(1, self.num_blocks):
            assert int(a.refcount[b]) == counts.get(b, 0), \
                (f"block {b} refcount {int(a.refcount[b])} != "
                 f"{counts.get(b, 0)} table reference(s) "
                 f"(slots {self.owners_of(b)})")
        assert not (free_set & cached_set) \
            and not (free_set & active_set) \
            and not (cached_set & active_set), \
            "free / cached-free / active sets overlap"
        assert free_set | cached_set | active_set \
            == set(range(1, self.num_blocks)), \
            "free / cached-free / active sets do not cover the pool"
        for h, b in self._hash_to_block.items():
            assert self._block_hash.get(b) == h, \
                f"hash index asymmetry at block {b}"
            assert b in active_set or b in cached_set, \
                f"hash index points at free-list block {b}"
        for b, h in self._block_hash.items():
            assert self._hash_to_block.get(h) == b, \
                f"block-hash asymmetry at block {b}"
        for b in cached_set:
            assert a.refcount[b] == 0, f"cached-free block {b} has owners"
            assert b in self._block_hash, \
                f"cached-free block {b} is not hash-indexed"
        # 9. tenant quota bookkeeping vs the allocator's ground truth:
        #    the incremental per-tenant charge must equal the table
        #    references actually held by each tenant's slots (one
        #    charge per reference — the policy note in __init__), and
        #    the grand total must equal the allocator's total refcount
        #    over usable blocks (every reference attributed once).
        truth: Dict[Optional[str], int] = {}
        for slot in range(self.max_seqs):
            n = len(self.seq_blocks[slot])
            if n:
                t = self.seq_tenant[slot]
                truth[t] = truth.get(t, 0) + n
        charged = {t: n for t, n in self._tenant_charge.items() if n}
        assert charged == truth, \
            (f"tenant block charges {charged} diverge from the "
             f"tables' ground truth {truth}")
        assert all(n >= 0 for n in self._tenant_charge.values()), \
            f"negative tenant charge: {self._tenant_charge}"
        total_refs = int(a.refcount[1:].sum())
        assert sum(truth.values()) == total_refs, \
            (f"tenant charges cover {sum(truth.values())} references "
             f"but the allocator counts {total_refs}")
        if lens is not None and active is not None:
            lens = np.asarray(lens)
            for slot in np.flatnonzero(np.asarray(active)):
                need = self.blocks_needed(int(lens[slot]))
                assert need <= len(self.seq_blocks[int(slot)]), \
                    (f"active slot {int(slot)} length "
                     f"{int(lens[slot])} not covered by its "
                     f"{len(self.seq_blocks[int(slot)])} block(s)")
        if deep:
            frozen = {b for b in range(1, self.num_blocks)
                      if a.refcount[b] >= 2 or b in self._block_hash
                      or b in cached_set}
            for b in list(self._audit_fp):
                if b not in frozen:
                    del self._audit_fp[b]
            if frozen:
                # ONE device->host pull per pool, shared by every
                # fingerprint (not one whole-pool copy per block).
                # Quantized pools fingerprint the int8 payload AND the
                # scale pages — an in-place scale rewrite corrupts a
                # shared page as surely as a payload write
                arrs = [np.asarray(p.numpy()) for p in self.pools]
                if self.quantized:
                    arrs += [np.asarray(s.numpy()) for s in self.scales]
                for b in frozen:
                    fp = self._fingerprint(b, arrs)
                    old = self._audit_fp.get(b)
                    assert old is None or old == fp, \
                        (f"immutable block {b} was written in place "
                         f"({self._describe_block(b)})")
                    self._audit_fp[b] = fp
        return True

    # -- checkpoint / restore -----------------------------------------
    def snapshot(self, base: Optional[dict] = None) -> dict:
        """Host-side checkpoint of the whole pool: geometry, the
        allocator's EXACT state (refcounts, free-list order,
        cached-free LRU order), block tables, the chain-hash index,
        and the content of every block that is live (refcount > 0) or
        parked cached-free. Free-list blocks carry no content worth
        keeping — a quarantined page, for instance, is already free
        here and therefore never rides a snapshot. ONE device->host
        pull per layer pool, independent of the live-block count.
        The result is a plain picklable dict (numpy + ints + bytes);
        ``restore`` rebuilds an identical pool from it.

        ``base`` (a previous snapshot of the SAME pool geometry)
        makes this a DELTA: pages whose content the base provably
        already carries — the block is chain-hash indexed, the base's
        index binds the same hash to the same block id, and the base
        holds that block's payload row — ride as ``base_blocks`` ids
        only, no bytes. The content address justifies the skip:
        indexed blocks are immutable in place (the deep audit
        enforces it), so same (id, hash) == same bytes. Unhashed
        blocks (open tails, mid-prefill pages) are always dirty and
        always ship. All ALLOCATOR metadata stays complete either
        way — only payload rows are elided — and ``restore(...,
        base=...)`` reconstitutes the full pool."""
        a = self.allocator
        cached_order = [int(b) for b in a._cached]
        keep = sorted({b for b in range(1, self.num_blocks)
                       if a.refcount[b] > 0} | set(cached_order))
        geometry = {
            "num_layers": self.num_layers,
            "num_heads": self.num_heads,
            "head_dim": self.head_dim,
            "block_size": self.block_size,
            "num_blocks": self.num_blocks,
            "max_seqs": self.max_seqs,
            "max_blocks_per_seq": self.max_blocks_per_seq,
            "dtype": self.dtype,
            "prefix_cache": self.prefix_cache,
            # recorded so tooling names the source mesh width; the
            # PAYLOAD is canonical (full heads) regardless, and
            # restore(mp=...) re-slices for any target width
            "mp": self.mp,
        }
        clean = set()
        if base is not None:
            if base.get("geometry") != geometry:
                raise ValueError(
                    "delta snapshot: base comes from a different "
                    "pool geometry — content addresses do not "
                    "transfer across geometries")
            base_rows = {int(b) for b in base["blocks"]}
            base_index = base.get("hash_index", {})
            for b in keep:
                h = self._block_hash.get(b)
                if h is not None and base_index.get(h) == b \
                        and b in base_rows:
                    clean.add(b)
        dirty = [b for b in keep if b not in clean]
        arrs = [np.asarray(p.numpy()) for p in self.pools]
        if self.mp > 1:
            # CANONICAL wire format: full-head pages, the mp=1 layout
            # — shard slices concatenate back on the head axis, so a
            # snapshot taken at mp=N restores at ANY width (mp=1
            # included) and vice versa; content-addressing stays over
            # the canonical bytes, identical across mesh widths
            arrs = [np.concatenate(
                arrs[i * self.mp:(i + 1) * self.mp], axis=2)
                for i in range(self.num_layers)]
        if dirty:
            # one fancy-index gather per layer, not a Python loop per
            # block — snapshots sit on the serving hot path
            payload = np.stack([arr[dirty] for arr in arrs],
                               axis=1)                 # [n, L, 2, H, bs, D]
        else:
            payload = np.zeros((0, self.num_layers, 2, self.num_heads,
                                self.block_size, self.head_dim),
                               arrs[0].dtype)
        scale_payload = None
        if self.quantized:
            # content-addressing over QUANTIZED bytes: the snapshot
            # carries each kept page's int8 payload plus its scales —
            # together they ARE the page's content, so a restore (same
            # or different geometry) reproduces dequantized values
            # bit-exactly
            sarrs = [np.asarray(s.numpy()) for s in self.scales]
            if self.mp > 1:
                sarrs = [np.concatenate(
                    sarrs[i * self.mp:(i + 1) * self.mp], axis=2)
                    for i in range(self.num_layers)]
            if dirty:
                scale_payload = np.stack([a[dirty] for a in sarrs],
                                         axis=1)   # [n, L, 2, H, bs]
            else:
                scale_payload = np.zeros(
                    (0, self.num_layers, 2, self.num_heads,
                     self.block_size), np.float32)
        return {
            "kind": "paged_kv_cache",
            "geometry": geometry,
            "refcount": {int(b): int(a.refcount[b]) for b in keep},
            "free_order": [int(b) for b in a._free],
            "cached_order": cached_order,       # oldest (LRU) first
            "reclaimed": int(a.reclaimed),
            "hash_index": dict(self._hash_to_block),
            "seq_blocks": [[int(b) for b in bl]
                           for bl in self.seq_blocks],
            "seq_tenant": list(self.seq_tenant),
            "peak_blocks_used": int(self.peak_blocks_used),
            "blocks": [int(b) for b in dirty],
            "payload": payload,
            # content the BASE checkpoint already carries (empty on a
            # full snapshot): restore(base=...) pulls these rows from
            # the base instead of the wire
            "base_blocks": sorted(int(b) for b in clean),
            **({"scale_payload": scale_payload}
               if scale_payload is not None else {}),
        }

    @classmethod
    def restore(cls, snap: dict, *,
                num_blocks: Optional[int] = None,
                mp: Optional[int] = None,
                shard_devices=None,
                base: Optional[dict] = None) -> "PagedKVCache":
        """Rebuild a pool from a ``snapshot`` dict. With the default
        (same ``num_blocks``) every block keeps its id and the
        allocator's free-list and LRU orders round-trip EXACTLY, so
        post-restore allocation behavior is bit-identical to the
        uninterrupted pool. ``num_blocks`` rehomes the
        content-addressed blocks into a larger or smaller pool:
        live blocks move first (oldest ids first), then cached-free
        blocks newest-first — the least-recently-used cached-free
        blocks are DROPPED (their index entries with them) when the
        target cannot hold everything, exactly the LRU-reclaim policy
        the live allocator applies. A live set that cannot fit raises
        ``BlockOOM`` carrying the snapshot's occupancy breakdown.

        ``mp`` retargets the tensor-parallel width: the snapshot's
        payload is canonical (full-head pages) whatever mesh it was
        taken on, so a snapshot from an mp=N fleet restores onto a
        single chip (mp=1) and vice versa — each target shard takes
        its own head slice of every page. Default: the snapshot's
        recorded width. Ends with the deep ``check_invariants``
        audit.

        A DELTA snapshot (non-empty ``base_blocks``; see
        ``snapshot(base=...)``) additionally needs ``base`` — the
        checkpoint it was taken against — to reconstitute the elided
        payload rows; restoring one without its base refuses rather
        than silently dropping pages. Pre-delta snapshots carry no
        ``base_blocks`` key and restore exactly as before."""
        referenced = [int(b) for b in snap.get("base_blocks", ())]
        if referenced:
            if base is None:
                raise ValueError(
                    f"delta snapshot references {len(referenced)} "
                    f"block(s) from its base checkpoint — restore "
                    f"needs base=...")
            snap = _merge_delta_snapshot(snap, base, referenced)
        g = snap["geometry"]
        nb = g["num_blocks"] if num_blocks is None else int(num_blocks)
        mp_t = int(g.get("mp", 1)) if mp is None else int(mp)
        cache = cls(g["num_layers"], g["num_heads"], g["head_dim"],
                    g["block_size"], nb, g["max_seqs"],
                    max_blocks_per_seq=g["max_blocks_per_seq"],
                    dtype=g["dtype"], prefix_cache=g["prefix_cache"],
                    mp=mp_t, shard_devices=shard_devices)
        refcount = {int(b): int(n) for b, n in snap["refcount"].items()}
        cached = [int(b) for b in snap["cached_order"]]
        live = sorted(b for b, n in refcount.items() if n > 0)
        usable = nb - 1
        if len(live) > usable:
            hist = {s: len(bl) for s, bl in
                    enumerate(snap["seq_blocks"]) if bl}
            raise BlockOOM(
                f"restore needs {len(live)} live block(s) but the "
                f"target pool has only {usable} usable"
                f"; snapshot pool: {len(live)} active / {len(cached)} "
                f"cached-free of {g['num_blocks'] - 1} usable; "
                f"blocks per slot: {hist or '{}'}",
                details={"active": len(live),
                         "cached_free": len(cached),
                         "usable": g["num_blocks"] - 1,
                         "target_usable": usable,
                         "blocks_per_slot": hist})
        # cached-free blocks that fit, newest (most recently released)
        # kept — dropping the LRU end is the reclaim order the live
        # allocator uses
        n_cached = min(len(cached), usable - len(live))
        dropped, kept_cached = (cached[:len(cached) - n_cached],
                                cached[len(cached) - n_cached:])
        a = cache.allocator
        if nb == g["num_blocks"] and not dropped:
            remap = {b: b for b in live + kept_cached}
            a._free = [int(b) for b in snap["free_order"]]
        else:
            order = live + kept_cached   # canonical rehoming order
            remap = {old: new for new, old in enumerate(order, start=1)}
            # fresh-pool free-list convention: pop() from the end
            # hands out the lowest remaining id first
            a._free = list(range(nb - 1, len(order), -1))
        for old, n in refcount.items():
            if old in remap:
                a.refcount[remap[old]] = n
        a._cached = OrderedDict((remap[b], True) for b in kept_cached)
        a.reclaimed = int(snap["reclaimed"]) + len(dropped)
        # pre-PR-7 snapshots carry no tenant attribution: version-gate
        # to an unattributed pool instead of crashing on the old format
        tenants = snap.get("seq_tenant",
                           [None] * g["max_seqs"])
        for slot, blocks in enumerate(snap["seq_blocks"]):
            mapped = [remap[int(b)] for b in blocks]
            cache.seq_tenant[slot] = tenants[slot]
            cache.seq_blocks[slot] = mapped
            cache._charge(slot, len(mapped))
            cache.block_tables[slot, :len(mapped)] = mapped
        for h, b in snap["hash_index"].items():
            b = remap.get(int(b))
            if b is not None:     # dropped cached-free: index entry too
                cache._hash_to_block[h] = b
                cache._block_hash[b] = h
        payload = np.asarray(snap["payload"])
        rows = [i for i, b in enumerate(snap["blocks"])
                if int(b) in remap]             # dropped blocks: no scatter
        if rows:
            ids = jnp.asarray([remap[int(snap["blocks"][i])]
                               for i in rows], jnp.int32)
            payload = payload[rows]
            Hs = cache.heads_per_shard
            for i in range(cache.num_layers):
                for s in range(cache.mp):
                    # each target shard takes its head slice of the
                    # canonical page (the whole page at mp == 1)
                    pi = cache.pool_index(i, s)
                    seg = jnp.asarray(
                        payload[:, i, :, s * Hs:(s + 1) * Hs])
                    cache.pools[pi] = Tensor(
                        cache.pools[pi].data.at[ids].set(
                            seg.astype(cache.pools[pi].data.dtype)))
            if cache.quantized:
                spay = np.asarray(snap["scale_payload"])[rows]
                for i in range(cache.num_layers):
                    for s in range(cache.mp):
                        pi = cache.pool_index(i, s)
                        cache.scales[pi] = Tensor(
                            cache.scales[pi].data.at[ids].set(
                                jnp.asarray(
                                    spay[:, i, :, s * Hs:(s + 1) * Hs],
                                    jnp.float32)))
        cache.peak_blocks_used = int(snap["peak_blocks_used"])
        cache._tables_dirty()
        cache.check_invariants(deep=True)
        return cache

    def bt_tensor(self) -> Tensor:
        """Device copy of the block tables; rebuilt only after a
        host-side table mutation. Rows in the decode mask (slots
        mid-chunked-prefill) present as all-trash so a fused decode
        step cannot write into their half-built pages."""
        if self._bt_cached is None:
            tbl = self.block_tables
            if self._decode_masked is not None and \
                    self._decode_masked.any():
                tbl = tbl.copy()
                tbl[self._decode_masked] = 0
            self._bt_cached = Tensor(jnp.asarray(tbl, jnp.int32))
        return self._bt_cached

    def bt_row_tensor(self, slot: int) -> Tensor:
        """Device copy of ONE slot's (unmasked) block-table row
        [1, MB] — the indirection a chunked-prefill call rides;
        invalidated with the full table."""
        t = self._bt_rows_cached.get(slot)
        if t is None:
            t = Tensor(jnp.asarray(self.block_tables[slot:slot + 1],
                                   jnp.int32))
            self._bt_rows_cached[slot] = t
        return t

    def set_decode_mask(self, rows: Optional[np.ndarray]) -> None:
        """Mark rows whose pages a fused DECODE step must not touch
        (slots mid-chunked-prefill; see bt_tensor). ``rows``: bool
        [max_seqs] or None to clear."""
        new = None if rows is None or not rows.any() else rows.copy()
        old = self._decode_masked
        if (old is None) != (new is None) or \
                (old is not None and not np.array_equal(old, new)):
            self._decode_masked = new
            self._bt_cached = None

    def _tables_dirty(self):
        self._bt_cached = None
        self._bt_rows_cached.clear()
        self.peak_blocks_used = max(self.peak_blocks_used,
                                    self.blocks_in_use)

    # -- allocation ---------------------------------------------------
    def ensure(self, slot: int, length: int,
               start_block: int = 0,
               write_from: Optional[int] = None) -> None:
        """Grow slot's table to cover ``length`` tokens
        (allocate-on-write) and copy-on-write split every shared block
        the coming write touches. ``write_from``: first position the
        caller will write (defaults to ``length - 1``, the single-token
        append); a multi-token append passes its start position so a
        shared page in the MIDDLE of the write range splits too.
        ``start_block``: table positions below it are adopted prefix
        pages the caller will never write (suffix-only prefill) — the
        COW split is skipped there, so a fully cached prompt keeps its
        last page shared instead of paying a pointless pool copy.
        Raises BlockOOM when the pool is exhausted (callers preempt)
        and ValueError past the per-seq table capacity."""
        if length <= 0:
            return  # nothing to cover (and no write block to COW)
        need = self.blocks_needed(length)
        if need > self.max_blocks_per_seq:
            raise ValueError(
                f"sequence length {length} exceeds per-seq capacity "
                f"{self.capacity_per_seq} (max_blocks_per_seq="
                f"{self.max_blocks_per_seq})")
        have = self.seq_blocks[slot]
        if need > len(have):
            new = self.allocator.alloc(need - len(have))
            self.block_tables[slot, len(have):need] = new
            have.extend(new)
            self._charge(slot, len(new))
            self._tables_dirty()
        # COW: every block the write range [write_from, length) lands in
        if write_from is None:
            write_from = int(length) - 1
        lo = max(int(write_from), 0) // self.block_size
        hi = (int(length) - 1) // self.block_size
        for bpos in range(max(lo, start_block), hi + 1):
            if self.allocator.refcount[have[bpos]] > 1:
                self._copy_block(slot, bpos)

    def truncate(self, slot: int, length: int) -> None:
        """Roll the slot back to ``length`` tokens (speculative-decode
        rejection): every block past ``blocks_needed(length)`` leaves
        the table, tail-first. Refcount-aware: a fork-shared page just
        drops one owner (the peer keeps it); a hash-indexed page
        reaching refcount 0 parks in the cached-free tier
        (resurrectable by a later ``match_prefix`` hit) instead of
        freeing — the same second-chance path ``free_seq`` takes. The
        kept partial last block is NOT cleared: positions past
        ``length`` are stale but masked by length everywhere, and the
        next append overwrites them (COW-splitting first if the block
        is shared, via ``ensure``'s write-range split)."""
        if length < 0:
            raise ValueError(f"negative truncate length {length}")
        have = self.seq_blocks[slot]
        keep = self.blocks_needed(length)
        if keep >= len(have):
            return  # nothing past the boundary
        drop = have[keep:]
        self.release_to_cache(drop)
        del have[keep:]
        self._charge(slot, -len(drop))
        self.block_tables[slot, keep:] = 0
        self._tables_dirty()

    def free_seq(self, slot: int) -> None:
        if self.seq_blocks[slot]:
            self.release_to_cache(self.seq_blocks[slot])
            self._charge(slot, -len(self.seq_blocks[slot]))
            self.seq_blocks[slot] = []
            self.block_tables[slot, :] = 0
            self._tables_dirty()
        self.seq_tenant[slot] = None

    def quarantine_seq(self, slot: int) -> None:
        """Free a slot's pages with NO cached-free second chance: used
        when the slot's pool content is suspect (numeric failure — a
        NaN/Inf reached its hidden, so its K/V pages may be poisoned).
        Solely-owned blocks lose their hash-index entry and return to
        the true free list (never resurrectable); blocks shared with
        other slots only drop this owner — a sharer's copy predates
        the corruption (shared pages are never written in place, so
        any poisoned append went to a COW-split private block)."""
        for b in self.seq_blocks[slot]:
            b = int(b)
            if self.allocator.refcount[b] == 1:
                self._on_reclaim(b)   # drop index entry + audit print
            self.allocator.free([b], to_cache=b in self._block_hash)
        self._charge(slot, -len(self.seq_blocks[slot]))
        self.seq_blocks[slot] = []
        self.block_tables[slot, :] = 0
        self._tables_dirty()
        self.seq_tenant[slot] = None

    def fork(self, src: int, dst: int, length: int) -> None:
        """Share src's first ``blocks_needed(length)`` blocks with dst
        (refcounted, including a partial last block — the first
        divergent append splits it copy-on-write)."""
        if self.seq_blocks[dst]:
            raise ValueError(f"dst slot {dst} already allocated")
        shared = self.seq_blocks[src][:self.blocks_needed(length)]
        self.allocator.ref(shared)
        for b in shared:   # fresh share epoch for the content audit
            self._audit_fp.pop(int(b), None)
        self.seq_blocks[dst] = list(shared)
        self._charge(dst, len(shared))
        self.block_tables[dst, :len(shared)] = shared
        self._tables_dirty()

    def share_report(self, slots) -> dict:
        """Fork-sharing introspection for a branch group (or any slot
        set): which pool blocks the given slots' tables reference, how
        many of the slots reference each (``multiplicity``), and the
        allocator's refcount per block. A pure read — the group audit
        (scheduler._audit_groups), the parallel-sampling tests and the
        ``serving_parallel`` bench all read the same numbers:

          shared_blocks   blocks referenced by >= 2 of the slots (the
                          COW-shared prompt pages)
          private_blocks  blocks referenced by exactly one slot (each
                          branch's divergent tail)
          multiplicity    {block: how many of the slots reference it}
          refcount        {block: allocator refcount} (>= multiplicity;
                          the prefix cache may hold more references)
          bytes_saved     whole-mesh pool bytes the sharing avoided
                          allocating: (multiplicity - 1) block copies
                          summed over shared blocks, priced at
                          kv_bytes_per_token() x block_size x mp
        """
        mult: dict = {}
        for slot in slots:
            for b in self.seq_blocks[int(slot)]:
                b = int(b)
                mult[b] = mult.get(b, 0) + 1
        shared = sorted(b for b, m in mult.items() if m >= 2)
        bpb = self.kv_bytes_per_token() * self.block_size * self.mp
        return {
            "shared_blocks": shared,
            "private_blocks": sorted(b for b, m in mult.items()
                                     if m == 1),
            "multiplicity": mult,
            "refcount": {b: int(self.allocator.refcount[b])
                         for b in mult},
            "bytes_saved": sum(mult[b] - 1 for b in shared) * bpb,
        }

    def _copy_block(self, slot: int, bpos: int, copy: bool = True) -> None:
        """Copy-on-write: give slot a private block at table position
        bpos. copy=False skips the pool copy for callers about to
        overwrite the whole block anyway (write_prefill)."""
        old = self.seq_blocks[slot][bpos]
        new = self.allocator.alloc(1)[0]
        if copy:
            src = Tensor(jnp.asarray([old], jnp.int32))
            dst = Tensor(jnp.asarray([new], jnp.int32))
            for i, pool in enumerate(self.pools):
                self.pools[i] = apply(_block_copy, (pool, src, dst),
                                      op_name="paged_block_copy")
            if self.quantized:
                # the page's scales are part of its content: a COW
                # split that copied only the int8 payload would
                # dequantize the private copy through stale scales
                for i, sc in enumerate(self.scales):
                    self.scales[i] = apply(
                        _block_copy, (sc, src, dst),
                        op_name="paged_block_copy_scales")
        self.release_to_cache([old])
        self.seq_blocks[slot][bpos] = new
        self.block_tables[slot, bpos] = new
        self._tables_dirty()

    # -- prefix caching -----------------------------------------------
    def _on_reclaim(self, block: int) -> None:
        """Allocator reclaimed a cached-free block: its content is
        about to be overwritten, drop the index entry."""
        h = self._block_hash.pop(block, None)
        if h is not None and self._hash_to_block.get(h) == block:
            del self._hash_to_block[h]
        # content legitimately changes from here: new audit epoch
        self._audit_fp.pop(block, None)

    def release_to_cache(self, blocks) -> None:
        """Drop ownership of ``blocks``; indexed blocks reaching
        refcount 0 park in the allocator's cached-free tier
        (resurrectable on a later ``match_prefix`` hit) instead of
        returning to the free list. Unindexed blocks (partial tails,
        decode pages, or any block when ``prefix_cache`` is off) free
        normally."""
        for b in blocks:
            self.allocator.free([b], to_cache=b in self._block_hash)

    def match_prefix(self, hashes) -> List[int]:
        """Longest indexed prefix of the hash chain -> pool block ids
        (a pure lookup: no refcounts move; use ``adopt_prefix`` to take
        ownership). A break in the chain ends the match — later links
        hash over the missing parent, so they cannot be present."""
        out: List[int] = []
        for h in hashes:
            b = self._hash_to_block.get(h)
            if b is None:
                break
            out.append(b)
        return out

    def adopt_prefix(self, slot, hashes) -> int:
        """Take shared ownership of the longest indexed prefix for an
        empty slot: active blocks gain an owner (``ref``), cached-free
        blocks are resurrected. Returns the number of blocks adopted —
        the caller prefills only tokens past ``n * block_size``."""
        if not self.prefix_cache:
            return 0
        if self.seq_blocks[slot]:
            raise ValueError(f"slot {slot} already allocated")
        matched = self.match_prefix(hashes)
        for b in matched:
            if self.allocator.refcount[b] > 0:
                self.allocator.ref([b])
                # new sharer: fresh epoch for the content audit
                self._audit_fp.pop(int(b), None)
            else:
                self.allocator.resurrect(b)
        if matched:
            self.seq_blocks[slot] = list(matched)
            self._charge(slot, len(matched))
            self.block_tables[slot, :len(matched)] = matched
            self._tables_dirty()
        return len(matched)

    def register_prefix(self, slot, hashes,
                        start: int = 0) -> None:
        """Index the slot's blocks ``[start, len(hashes))`` under their
        chain hashes (first writer wins: a hash already indexed keeps
        its original block — both hold identical content, and 1:1
        block<->hash bookkeeping is what reclaim relies on).
        ``start`` lets an incremental caller (per-chunk registration)
        skip the already-indexed prefix instead of re-probing it."""
        if not self.prefix_cache:
            return
        blocks = self.seq_blocks[slot]
        for i in range(start, min(len(hashes), len(blocks))):
            h, b = hashes[i], int(blocks[i])
            if h in self._hash_to_block or b in self._block_hash:
                continue
            self._hash_to_block[h] = b
            self._block_hash[b] = h

    # -- page migration (disaggregated serving) -----------------------
    def export_slice(self, slot: int, hashes) -> Optional[dict]:
        """Wire-format slice of ONE slot's finished prefix pages — the
        page-MIGRATION payload a disaggregated router ships from a
        prefill-heavy pool to a decode pool (inference/router.py).
        ``hashes`` is the slot's chain-hash identity (one per FULL
        block, ``PagedRequest.block_hashes``); the slice carries the
        first ``min(len(hashes), blocks held)`` blocks as
        content-addressed (hash, payload) pairs — exactly the
        snapshot()'s per-block format, sliced to one slot — plus the
        geometry the importer validates against. ONE fancy-index
        gather per layer, no allocator state: export is a pure read.
        Returns None when the slot holds no full indexed-identity
        block yet (nothing migratable)."""
        blocks = [int(b) for b in
                  self.seq_blocks[slot][:len(hashes)]]
        if not blocks:
            return None
        # gather ON DEVICE, transfer only the slice: pulling whole
        # pools to host per export would cost O(pool) per migrated
        # slot where the slice is a handful of blocks. Sharded pools
        # emit the CANONICAL full-head page (per-shard gathers
        # concatenated on the head axis) — the wire format is
        # mesh-width-independent, so any pool can adopt any slice
        ids = jnp.asarray(blocks, jnp.int32)
        if self.mp == 1:
            payload = np.stack([np.asarray(p.data[ids])
                                for p in self.pools],
                               axis=1)            # [n, L, 2, H, bs, D]
        else:
            payload = np.stack(
                [np.concatenate(
                    [np.asarray(
                        self.pools[self.pool_index(i, s)].data[ids])
                     for s in range(self.mp)], axis=2)
                 for i in range(self.num_layers)], axis=1)
        out = {
            "kind": "kv_slice",
            "geometry": {
                "num_layers": self.num_layers,
                "num_heads": self.num_heads,
                "head_dim": self.head_dim,
                "block_size": self.block_size,
                "dtype": self.dtype,
            },
            "hashes": list(hashes[:len(blocks)]),
            "payload": payload,
        }
        if self.quantized:
            if self.mp == 1:
                out["scale_payload"] = np.stack(
                    [np.asarray(s.data[ids]) for s in self.scales],
                    axis=1)                       # [n, L, 2, H, bs]
            else:
                out["scale_payload"] = np.stack(
                    [np.concatenate(
                        [np.asarray(self.scales[
                            self.pool_index(i, s)].data[ids])
                         for s in range(self.mp)], axis=2)
                     for i in range(self.num_layers)], axis=1)
        return out

    def import_slice(self, slc: dict) -> int:
        """Adopt a migrated ``export_slice`` into THIS pool: each
        (hash, page) lands as a CACHED-FREE hash-indexed block — the
        same second-chance tier a released prefix parks in — so the
        next ``adopt_prefix`` over the migrated request's chain
        resurrects them and the suffix prefill skips the work the
        source pool already did. Semantics:

          * a hash already indexed here is SKIPPED (a colliding live
            or cached prefix — by chain-hash identity the pool already
            holds bit-identical content, and 1:1 block<->hash
            bookkeeping must hold);
          * blocks import in PREFIX ORDER and a pool that cannot hold
            the next one stops early (an imported prefix is useful
            exactly up to its first gap — match_prefix ends there);
            allocation may LRU-reclaim older cached-free content,
            the live allocator's normal policy;
          * nothing is charged to any tenant (no table references) and
            no slot state moves: the import is invisible to admission
            until a request adopts it.

        Returns the number of NEW blocks written. Raises ValueError on
        a geometry/dtype mismatch (pages are raw pool rows — a wrong
        shape would corrupt attention silently) or when this pool has
        no prefix index to adopt into."""
        if slc.get("kind") != "kv_slice":
            raise ValueError(f"not a kv_slice: {slc.get('kind')!r}")
        if not self.prefix_cache:
            raise ValueError(
                "import_slice needs prefix_cache=True — migrated "
                "pages are adopted through the chain-hash index")
        g = slc["geometry"]
        mine = {"num_layers": self.num_layers,
                "num_heads": self.num_heads,
                "head_dim": self.head_dim,
                "block_size": self.block_size, "dtype": self.dtype}
        if {k: g.get(k) for k in mine} != mine:
            raise ValueError(
                f"kv_slice geometry {g} does not match pool {mine}")
        payload = np.asarray(slc["payload"])
        if self.quantized and "scale_payload" not in slc:
            raise ValueError(
                "kv_slice carries no scales but this pool is int8 — "
                "corrupt or hand-built slice")
        spay = (np.asarray(slc["scale_payload"])
                if self.quantized else None)
        # resolve the importable set FIRST (collisions skipped, stop
        # at the first allocation failure), then land it as ONE
        # scatter per layer — not one dispatch per (block, layer)
        landing: List[tuple] = []       # (pool block id, slice row)
        for i, h in enumerate(slc["hashes"]):
            if h in self._hash_to_block:
                continue            # colliding prefix: already here
            try:
                b = self.allocator.alloc(1)[0]
            except BlockOOM:
                break               # pool full: keep the clean prefix
            landing.append((b, i))
        if not landing:
            return 0
        ids = jnp.asarray([b for b, _ in landing], jnp.int32)
        rows = [i for _, i in landing]
        Hs = self.heads_per_shard
        for li in range(self.num_layers):
            # ONE fancy-index gather of the layer's canonical
            # full-head pages; each local shard lands a view-slice of
            # it (not mp re-gathers of the whole payload)
            seg_full = payload[rows, li]
            sfull = spay[rows, li] if self.quantized else None
            for s in range(self.mp):
                pi = self.pool_index(li, s)
                seg = jnp.asarray(
                    seg_full[:, :, s * Hs:(s + 1) * Hs])
                self.pools[pi] = Tensor(
                    self.pools[pi].data.at[ids].set(
                        seg.astype(self.pools[pi].data.dtype)))
                if self.quantized:
                    self.scales[pi] = Tensor(
                        self.scales[pi].data.at[ids].set(
                            jnp.asarray(
                                sfull[:, :, s * Hs:(s + 1) * Hs],
                                jnp.float32)))
        for (b, i) in landing:
            # fresh content: new audit epoch for the fingerprint
            # check, then park cached-free in prefix (oldest-first
            # LRU) order
            self._audit_fp.pop(b, None)
            self._hash_to_block[slc["hashes"][i]] = b
            self._block_hash[b] = slc["hashes"][i]
            self.allocator.free([b], to_cache=True)
        return len(landing)

    # -- mixed ragged step --------------------------------------------
    def ragged_views(self, segments, tile_q=None,
                     tile_kv=None) -> List["PagedRaggedView"]:
        """Per-layer views for ONE mixed ragged model call (the
        scheduler's token-budget step): ``segments`` is an ordered
        list of descriptors —

          ("prefill", slot, start, length, write_start)
              one prompt chunk: rows [start, start+length) of ``slot``
              append through its table (positions below write_start —
              an adopted shared prefix — route to trash) and attend
              causally at their absolute positions;
          ("decode", lens, 1)
              the fused decode rows: one query per batch slot at
              position lens[b], through the DECODE-MASKED batch table
              (mid-prefill/fresh slots write trash), exactly the plain
              fused step.

        The packed input x is [1, sum(rows), d] in segment order; on
        the kernel path each layer is ONE ``paged_attention_ragged``
        launch. Build AFTER ensure()ing coverage and setting the
        decode mask — the layout snapshots the current tables."""
        layout = _RaggedLayout(self, segments, tile_q=tile_q,
                               tile_kv=tile_kv)
        return [PagedRaggedView(self, i, layout)
                for i in range(self.num_layers)]

    # -- prefill ------------------------------------------------------
    def prefill_views(self, slot: int,
                      write_start: int = 0) -> List["PagedPrefillView"]:
        """Per-layer chunked-prefill views of one slot — the
        ``caches=`` list for a batch-1 chunk model call. A suffix-only
        (prefix-cache hit) prefill passes ``write_start`` = adopted
        tokens: recomputed rows below it attend over the adopted pages
        but never rewrite them (they may be shared), which is what
        replaced the old pages->scratch gather."""
        return [PagedPrefillView(self, i, slot, write_start=write_start)
                for i in range(self.num_layers)]

    def write_prefill_chunk(self, slot: int, layer: int, k, v,
                            start: int, write_start: int = 0) -> None:
        """Chunk-granular append: write k/v [1, C, H, D] Tensors into
        this slot's pages at positions [start, start + C) (skipping
        positions below ``write_start`` — an adopted shared prefix).
        ``ensure(slot, start + C, write_from=start)`` must have run.
        The model path goes through ``prefill_views`` (append + attend
        in one protocol call); this entry serves callers that already
        hold projected K/V — e.g. migrating a dense cache row into
        pages chunk by chunk."""
        C = int(k.shape[1])
        pi = self.pool_index(layer, 0)
        if self.mp > 1:
            raise ValueError(
                "write_prefill_chunk takes full-head K/V; a sharded "
                "pool's pages are written per shard through the "
                "prefill views (ShardedServingCore)")
        tt = Tensor(jnp.asarray([start], jnp.int32))
        ws = Tensor(jnp.asarray([write_start], jnp.int32))
        bt = self.bt_row_tensor(slot)
        if self.quantized:
            self.pools[pi], self.scales[pi] = apply(
                _make_append_chunk_q(self.block_size, C),
                (self.pools[pi], self.scales[pi], k, v, tt, bt,
                 ws),
                op_name="paged_prefill_chunk_kv_q")
        else:
            self.pools[pi] = apply(
                _make_append_chunk(self.block_size, C),
                (self.pools[pi], k, v, tt, bt, ws),
                op_name="paged_prefill_chunk_kv")

    def write_prefill(self, slot: int, row_caches, length: int,
                      start_block: int = 0) -> None:
        """Scatter a dense single-row scratch cache (the per-layer
        [2, 1, H, S, D] Tensors a batch-1 prefill produced) into this
        slot's pages from ``start_block`` on — an ``adopt_prefix`` hit
        passes the number of adopted blocks so the shared prefix pages
        are neither rewritten nor COW-split. ensure(slot, length) must
        have run first."""
        if self.mp > 1:
            raise ValueError(
                "write_prefill consumes dense full-head scratch rows; "
                "a sharded pool streams prompts through prefill_views"
                " / chunked_prefill (per-shard head slices)")
        n = self.blocks_needed(length)
        if n > len(self.seq_blocks[slot]):
            raise ValueError("ensure() the slot before write_prefill")
        # the scatter rewrites every covered block wholesale, so any
        # fork-shared block in range must be split first (no pool copy
        # needed — its contents are about to be replaced) or the peer
        # sequence would read this prefill through the shared page
        for bpos in range(start_block, n):
            if self.allocator.refcount[self.seq_blocks[slot][bpos]] > 1:
                self._copy_block(slot, bpos, copy=False)
        if start_block >= n:
            return  # fully cached prompt: every page already written
        blks = Tensor(jnp.asarray(self.seq_blocks[slot][start_block:n],
                                  jnp.int32))
        if self.quantized:
            impl_q = _make_prefill_scatter_q(start_block,
                                             n - start_block,
                                             self.block_size)
            for i, rc in enumerate(row_caches):
                self.pools[i], self.scales[i] = apply(
                    impl_q, (self.pools[i], self.scales[i], rc, blks),
                    op_name="paged_prefill_scatter_q")
            return
        impl = _make_prefill_scatter(start_block, n - start_block,
                                     self.block_size)
        for i, (pool, rc) in enumerate(zip(self.pools, row_caches)):
            self.pools[i] = apply(impl, (pool, rc, blks),
                                  op_name="paged_prefill_scatter")
