"""Serving telemetry: per-request lifecycle tracing, an engine
step-phase timeline, and a unified metrics registry with Chrome-trace
export.

The reference tree ships a whole profiler subsystem
(paddle/fluid/platform/profiler/ emits chrome://tracing timelines)
because an industrial serving stack is untunable blind. This module is
that subsystem for the paged serving stack:

* ``StatsBase`` — the one base behind the five serving stats siblings
  (``PrefixCacheStats`` / ``PrefillStats`` / ``ResilienceStats`` /
  ``TenantStats`` / ``SpecDecodeStats``, serving.py): subclasses
  declare ``FIELDS`` (zero-initialized counters/gauges), ``DERIVED``
  (property name -> rounding digits, exported next to the fields) and
  ``REPR`` (the headline subset), and ``as_dict``/``__repr__`` are
  generated — every stat a subclass declares is export-visible by
  construction, no copy-pasted dict/repr bodies to drift.

* ``MetricsRegistry`` — counters / gauges / histograms plus live
  ``attach``ed sources (a stats sibling, or any callable returning a
  dict — ``tenant_report`` rides this). ``as_dict()`` is a flat
  snapshot (nested sources dot-flattened), ``delta_since(prev)``
  turns two snapshots into interval deltas — the time-series sampling
  surface the ROADMAP's disaggregated router needs for its load
  signals (block pressure, shed rate, per-tenant charge).

* ``TraceCollector`` — the opt-in tracing hub the engines call into
  (``PagedServingEngine(collector=...)``). Three data planes:

    - per-REQUEST lifecycle: submitted -> admitted -> prefill-chunk xN
      -> first-token -> decode (counted, not per-event) ->
      preempted / rolled-back / oom-shed -> terminal outcome, with
      derived TTFT / TPOT / queue-wait / preemption-stall per request,
      rolled up into per-tenant percentiles by ``request_summary``;
    - per-STEP timeline: ``begin_step``/``phase``/``end_step`` bracket
      each engine step's phases (admission, prefill, model,
      bookkeeping), ``span_begin``/``span_end`` nest free-form spans
      around them (spec rounds, journal appends, snapshots), and
      ``end_step`` samples gauges (pool tiers, queue depth, per-tenant
      charge) from engine ground truth;
    - export: ``chrome_trace()`` emits the ``trace_events`` JSON
      format (loadable in Perfetto / chrome://tracing) with the
      request records and summaries riding ``metadata``;
      ``as_dict()`` is the flat metrics dump.

  CONTRACTS (tested in tests/test_telemetry.py):

    - DISABLED = ZERO OVERHEAD: with no collector installed the
      engines perform no clock reads and no telemetry allocations —
      every hook site is behind ``if self.collector is not None``,
      the same pattern as ``FaultInjector``.
    - PASSIVE: the collector only ever observes; token streams and
      terminal outcomes are bit-identical with tracing on vs off
      across plain / prefix-cached / speculative / recoverable
      serving (collector methods never raise into the engine and
      never touch engine state).
    - RECOVERY-SAFE: all wall-clock timestamps live HERE, never in
      engine-behavioral state — engine snapshots carry no collector
      state, a recovered engine gets the caller's collector installed
      fresh (``RecoverableServer.recover(collector=...)``). During
      journal replay the collector is flipped to replay mode
      (mirroring how ``CrashInjector`` is disarmed): timeline spans
      record flagged ``replay: True``, records observed live by the
      dead incarnation are FROZEN (no double counting), and requests
      first seen during replay are flagged ``replayed`` and excluded
      from latency percentiles (their replay-time stamps are not
      serving latencies).

The injectable ``clock`` (default ``time.perf_counter``) keeps tests
deterministic and is how the counting-clock test proves the
zero-overhead contract.
"""
from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["StatsBase", "MetricsRegistry", "NetStats",
           "TraceCollector", "percentiles"]


# ---------------------------------------------------------------------
# stats base (the five serving.py siblings subclass this)
# ---------------------------------------------------------------------

class StatsBase:
    """Declarative counter/gauge bundle: subclasses list ``FIELDS``
    (instance slots, zero-initialized), ``DERIVED`` ({property name:
    rounding digits or None}) and optionally ``REPR`` (the headline
    fields/properties; defaults to FIELDS). ``as_dict`` exports every
    field AND every derived property — a stat that exists is a stat
    that exports, by construction."""

    FIELDS: Tuple[str, ...] = ()
    DERIVED: Dict[str, Optional[int]] = {}
    REPR: Tuple[str, ...] = ()

    __slots__ = ()

    def __init__(self):
        for f in self.FIELDS:
            setattr(self, f, 0)

    def as_dict(self) -> dict:
        out = {f: getattr(self, f) for f in self.FIELDS}
        for name, nd in self.DERIVED.items():
            v = getattr(self, name)
            out[name] = round(v, nd) if nd is not None else v
        return out

    def __repr__(self):
        parts = []
        for name in (self.REPR or self.FIELDS):
            v = getattr(self, name)
            parts.append(f"{name}={v:.4g}" if isinstance(v, float)
                         else f"{name}={v}")
        return f"{type(self).__name__}({', '.join(parts)})"


class NetStats(StatsBase):
    """Session-transport accounting (inference/net.py), one instance
    per ``ResilientTransport``. A fleet supervisor sums these across
    its workers under the ``net.*`` registry namespace — the series
    the monitor's ``network-flapping`` detector watches. Every field
    is deterministic under a seeded ``NetworkFaultInjector`` storm:
    two identical runs report identical counters.

      sessions          session hellos answered (1 + reconnects,
                        counting the initial adoption)
      reconnects        successful reconnect+hello sequences after a
                        transient fault (EOF / torn frame / CRC /
                        op timeout)
      probes            liveness probe attempts (each reconnect try
                        IS a probe: connect + hello; a failed probe
                        escalates to WorkerDied)
      retried_ops       ops resent on a resumed session after a fault
      reply_cache_hits  retried ops the worker answered from its
                        bounded reply cache instead of re-executing
                        (the transport-level idempotency contract)
      frames_rejected   reply frames discarded as torn or
                        CRC-corrupt (never surfaced as data)
      stale_frames      late/duplicate frames for an already-resolved
                        op seq, discarded by the want-seq check
      blackholes        op deadlines that expired with the connection
                        open (a silent peer, recovered via probe)
    """

    __slots__ = FIELDS = (
        "sessions", "reconnects", "probes", "retried_ops",
        "reply_cache_hits", "frames_rejected", "stale_frames",
        "blackholes")
    REPR = ("sessions", "reconnects", "retried_ops",
            "reply_cache_hits", "frames_rejected")


# ---------------------------------------------------------------------
# unified metrics registry
# ---------------------------------------------------------------------

def percentiles(values, qs=(50, 90, 99)) -> dict:
    """{'count', 'mean', 'p50', 'p90', 'p99', 'max'} of a value list
    (empty input -> {'count': 0})."""
    vals = np.asarray([v for v in values if v is not None], np.float64)
    if vals.size == 0:
        return {"count": 0}
    out = {"count": int(vals.size), "mean": float(vals.mean()),
           "max": float(vals.max())}
    for q in qs:
        out[f"p{q}"] = float(np.percentile(vals, q))
    return out


def _flatten(prefix: str, value, out: dict) -> None:
    if isinstance(value, dict):
        for k, v in value.items():
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)
    else:
        out[prefix] = value


class MetricsRegistry:
    """One namespace for every serving metric: explicit counters /
    gauges / histograms plus live ``attach``ed sources read at
    snapshot time. ``as_dict()`` is flat ({'a.b.c': value}) so two
    snapshots diff into interval deltas with ``delta_since`` — the
    sampling loop a router or dashboard runs."""

    def __init__(self):
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self._hists: Dict[str, List[float]] = {}
        # observations trimmed off each series so far: the absolute
        # index of _hists[name][0] — what lets values_since address a
        # window by TOTAL observation count across trims
        self._hist_dropped: Dict[str, int] = {}
        self._sources: Dict[str, Any] = {}

    # -- writes -------------------------------------------------------
    def count(self, name: str, n: float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    # histogram observations are WINDOWED: a long-lived server must
    # not grow O(total requests) — when a series hits 2x the window
    # the older half is dropped, so percentiles reflect the most
    # recent <= 2*window samples (totals belong in counters)
    HIST_WINDOW = 4096

    def observe(self, name: str, value: float) -> None:
        lst = self._hists.setdefault(name, [])
        if len(lst) >= 2 * self.HIST_WINDOW:
            del lst[:self.HIST_WINDOW]
            self._hist_dropped[name] = \
                self._hist_dropped.get(name, 0) + self.HIST_WINDOW
        lst.append(float(value))

    def attach(self, prefix: str, source) -> None:
        """Register a live source exported under ``prefix``: an object
        with ``as_dict()`` (a stats sibling) or a zero-arg callable
        returning a dict (``tenant_report``, pool occupancy)."""
        self._sources[prefix] = source

    # -- reads --------------------------------------------------------
    def histogram(self, name: str) -> dict:
        return percentiles(self._hists.get(name, ()))

    # -- windowed histogram views -------------------------------------
    # ``as_dict``/``histogram`` report percentiles since boot (well,
    # since the retention window) — useless to an SLO tracker or a
    # router scrape that wants "the last interval". These views
    # address observations by their TOTAL count, the histogram
    # equivalent of ``delta_since``: mark now, serve, then ask for
    # everything after the mark.

    def hist_names(self) -> List[str]:
        return list(self._hists)

    def hist_total(self, name: str) -> int:
        """Observations EVER made on ``name`` (monotonic across the
        retention trim — the mark currency of values_since)."""
        return self._hist_dropped.get(name, 0) + \
            len(self._hists.get(name, ()))

    def hist_marks(self) -> Dict[str, int]:
        """{name: hist_total} for every histogram — snapshot before an
        interval, pass to ``percentiles_since`` after it."""
        return {name: self.hist_total(name) for name in self._hists}

    def last_value(self, name: str) -> Optional[float]:
        """Most recent observation on ``name`` (None when empty) —
        how the cost ledger pairs a step's analytic work with the
        step's just-closed ``span.model`` duration."""
        lst = self._hists.get(name)
        return lst[-1] if lst else None

    def values_since(self, name: str, start: int) -> List[float]:
        """Observations on ``name`` from absolute index ``start``
        (a previous ``hist_total``). Observations already trimmed by
        the retention window are gone — the view clamps to what is
        retained rather than failing."""
        lst = self._hists.get(name)
        if not lst:
            return []
        i = max(0, int(start) - self._hist_dropped.get(name, 0))
        return lst[i:]

    def percentiles_since(self, prev: Optional[Dict[str, int]] = None,
                          qs=(50, 90, 99)) -> Dict[str, dict]:
        """Windowed percentiles: for every histogram, the percentile
        dict over observations made AFTER the ``prev`` marks (a
        ``hist_marks()`` snapshot; names absent there count from 0).
        The interval view ``SloTracker`` and a router scrape consume —
        p50/p90/p99 over the last window, not since boot."""
        prev = prev or {}
        return {name: percentiles(
                    self.values_since(name, prev.get(name, 0)), qs)
                for name in self._hists}

    def as_dict(self) -> dict:
        out: Dict[str, Any] = {}
        for name, v in self.counters.items():
            _flatten(name, v, out)
        for name, v in self.gauges.items():
            _flatten(name, v, out)
        for name, vals in self._hists.items():
            _flatten(name, percentiles(vals), out)
        for prefix, src in self._sources.items():
            d = src() if callable(src) else src.as_dict()
            _flatten(prefix, d, out)
        return out

    def scrape(self, prefixes) -> dict:
        """``as_dict()`` filtered to keys under any of ``prefixes`` —
        the per-worker WIRE payload a fleet router samples
        (inference/router.py): the full flat dict drags along
        per-request latency histograms and tenant detail a placement
        decision has no use for, and scrape payloads cross a pipe
        every tick."""
        pref = tuple(str(p) for p in prefixes)
        return {k: v for k, v in self.as_dict().items()
                if k.startswith(pref)}

    def delta_since(self, prev: dict) -> dict:
        """Numeric differences between the current snapshot and a
        previous ``as_dict()`` (keys absent before count from 0);
        non-numeric entries are skipped."""
        cur = self.as_dict()
        out = {}
        for k, v in cur.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            p = prev.get(k, 0)
            if isinstance(p, bool) or not isinstance(p, (int, float)):
                p = 0
            out[k] = v - p
        return out


# ---------------------------------------------------------------------
# trace collector
# ---------------------------------------------------------------------

class _ReqTrace:
    """Lifecycle record of one request (collector-internal; exported
    via ``as_dict``). Timestamps are collector-relative seconds."""

    __slots__ = ("rid", "tenant", "gid", "submit_ts", "admit_ts",
                 "first_ts", "last_ts", "tokens", "chunks",
                 "preemptions", "stall_s", "_preempt_ts", "outcome",
                 "outcome_step", "events", "replayed")

    def __init__(self, rid: int, tenant, ts, replayed: bool = False,
                 gid=None):
        self.rid = rid
        self.tenant = tenant
        self.gid = gid             # fork-shared branch group, or None
        self.submit_ts = ts
        self.admit_ts = None
        self.first_ts = None
        self.last_ts = None
        self.tokens = 0            # decode tokens consumed (rollbacks
                                   # subtracted -> emitted tokens)
        self.chunks = 0
        self.preemptions = 0
        self.stall_s = 0.0         # preempted -> re-admitted wall time
        self._preempt_ts = None
        self.outcome = None
        self.outcome_step = None
        self.events: List[tuple] = []   # (ts, name, args or None)
        self.replayed = replayed

    # -- derived latencies (None until the defining events happened) --
    @property
    def queue_wait_s(self):
        if self.submit_ts is None or self.admit_ts is None:
            return None
        return self.admit_ts - self.submit_ts

    @property
    def ttft_s(self):
        if self.submit_ts is None or self.first_ts is None:
            return None
        return self.first_ts - self.submit_ts

    @property
    def tpot_s(self):
        if self.first_ts is None or self.last_ts is None or \
                self.tokens < 2:
            return None
        return (self.last_ts - self.first_ts) / (self.tokens - 1)

    def as_dict(self) -> dict:
        r = lambda v: None if v is None else round(v, 6)  # noqa: E731
        return {"rid": self.rid, "tenant": self.tenant,
                "gid": self.gid,
                "tokens": self.tokens, "chunks": self.chunks,
                "preemptions": self.preemptions,
                "outcome": self.outcome,
                "outcome_step": self.outcome_step,
                "queue_wait_s": r(self.queue_wait_s),
                "ttft_s": r(self.ttft_s),
                "tpot_s": r(self.tpot_s),
                "stall_s": r(self.stall_s),
                "replayed": self.replayed,
                "events": [(round(ts, 6), name, args)
                           for ts, name, args in self.events]}


class TraceCollector:
    """See the module docstring. Every method is a cheap append — the
    engines call them only when a collector is installed, and the
    collector never reaches back into the engine."""

    LATENCIES = ("ttft_s", "tpot_s", "queue_wait_s", "stall_s")

    # per-request event-log cap (a preemption storm must not grow one
    # record without bound; counters keep counting past it)
    MAX_REQ_EVENTS = 512

    def __init__(self, clock: Optional[Callable[[], float]] = None,
                 max_events: int = 500_000,
                 max_requests: int = 100_000):
        self._clock = time.perf_counter if clock is None else clock
        self._t0 = self._clock()
        self.max_events = int(max_events)
        self.max_requests = int(max_requests)
        self.dropped = 0
        self.evicted_requests = 0
        self.events: List[dict] = []       # timeline (chrome-ish dicts,
                                           # ts in relative seconds)
        self.requests: Dict[int, _ReqTrace] = {}
        self.registry = MetricsRegistry()
        self.steps = 0
        self.replayed_steps = 0
        self._replay = False
        self._step: Optional[tuple] = None     # (t, step_id, kind)
        self._phase: Optional[tuple] = None    # (t, name)
        self._spans: List[tuple] = []          # (t, name, args)

    def now(self) -> float:
        return self._clock() - self._t0

    # -- low-level emit -----------------------------------------------
    def _emit(self, ev: dict) -> None:
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        if self._replay and ev.get("ph") != "C":
            # counter events' args IS the {series: value} map — a
            # replay flag there would chart as a bogus series
            ev.setdefault("args", {})["replay"] = True
        self.events.append(ev)

    def _span_event(self, name: str, t0: float, t1: float,
                    args: Optional[dict] = None) -> None:
        ev = {"name": name, "ph": "X", "ts": t0, "dur": t1 - t0}
        if args:
            ev["args"] = dict(args)
        self._emit(ev)
        # every span duration also lands in a windowed registry
        # histogram (``span.<name>``): percentiles_since over these is
        # the windowed per-phase step-timing view the health monitor
        # samples (and kernel tile sizing reads). Replayed spans are
        # replay-time, not serving-time — timeline-flagged only.
        if not self._replay:
            self.registry.observe(f"span.{name}", t1 - t0)

    # -- step timeline ------------------------------------------------
    def begin_step(self, step: int, kind: str = "step") -> None:
        """Open the span for one engine step (auto-closing a step a
        crash left dangling) and its first phase."""
        t = self.now()
        if self._step is not None:
            self._close_step(t, aborted=True)
        self._step = (t, int(step), kind)
        self._phase = (t, "bookkeeping")

    def phase(self, name: str) -> None:
        """Close the current phase span, open the next. No-op outside
        a step (a crash may have torn one down)."""
        if self._step is None:
            return
        t = self.now()
        if self._phase is not None:
            self._span_event(self._phase[1], self._phase[0], t,
                             {"step": self._step[1]})
        self._phase = (t, name)

    def end_step(self, gauges: Optional[dict] = None,
                 aborted: bool = False) -> None:
        """Close the step span; ``gauges`` ({track: {series: value}})
        are emitted as Chrome counter events and mirrored into the
        registry. ``aborted`` closes a step a crash tore down: the
        span is flagged, counted separately (``steps.aborted``), and
        its gauges are NOT emitted — mid-crash state is not a
        step-boundary sample."""
        if self._step is None:
            return
        t = self.now()
        self._close_step(t, aborted=aborted)
        if aborted:
            return
        if gauges:
            for track, series in gauges.items():
                self._emit({"name": track, "ph": "C", "ts": t,
                            "args": dict(series)})
                for k, v in series.items():
                    self.registry.gauge(f"{track}.{k}", v)

    def _close_step(self, t: float, aborted: bool = False) -> None:
        t0, step, kind = self._step
        if self._phase is not None:
            self._span_event(self._phase[1], self._phase[0], t,
                             {"step": step})
            self._phase = None
        args = {"step": step}
        if aborted:
            args["aborted"] = True
        self._span_event(kind, t0, t, args)
        self._step = None
        if aborted:
            # a torn step is not a completed step: it either replays
            # after recovery (counted then) or the engine is abandoned
            self.registry.count("steps.aborted")
        elif self._replay:
            self.replayed_steps += 1
            self.registry.count("steps.replayed")
        else:
            self.steps += 1
            self.registry.count("steps.live")

    # -- free-form spans (spec rounds, journal, snapshots) ------------
    @property
    def span_depth(self) -> int:
        return len(self._spans)

    def span_begin(self, name: str, **args) -> None:
        self._spans.append((self.now(), name, args))

    def span_end(self, **extra) -> None:
        if not self._spans:
            return
        t0, name, args = self._spans.pop()
        if extra:
            args = dict(args, **extra)
        self._span_event(name, t0, self.now(), args or None)

    def span_unwind(self, depth: int, aborted: bool = False) -> None:
        """Close every span above ``depth``. ``aborted=True`` is for
        exception unwinding (an ``EngineCrash`` mid-round must not
        skew the stack, but the trace should say the span was torn
        down); the default closes normally, so a success path may
        unwind instead of matching every ``span_end`` by hand."""
        while len(self._spans) > depth:
            if aborted:
                self.span_end(aborted=True)
            else:
                self.span_end()

    def on_event(self, name: str, args: Optional[dict] = None) -> None:
        """Instant event on the engine track (OOM/shed occupancy
        dumps ride this)."""
        ev = {"name": name, "ph": "i", "ts": self.now(), "s": "t"}
        if args:
            ev["args"] = dict(args)
        self._emit(ev)
        if not self._replay:       # replayed instants are flagged in
            self.registry.count(f"events.{name}")   # the timeline only

    # -- request lifecycle --------------------------------------------
    def _rec_event(self, rec: _ReqTrace, ts: float, name: str,
                   args: Optional[dict] = None) -> None:
        """Bounded per-record event log (counters keep counting past
        the cap — only the log truncates)."""
        if len(rec.events) < self.MAX_REQ_EVENTS:
            rec.events.append((ts, name, args))

    def _req(self, rid: int) -> Optional[_ReqTrace]:
        """The record for ``rid``, or None when this collector never
        saw it submitted (wired onto a restored engine with in-flight
        requests): a request is traced from its submit or not at all —
        synthesizing a half-record here would put tenant-less entries
        (and, via rollback, NEGATIVE token tallies) in the summary."""
        rec = self.requests.get(rid)
        if rec is None or self._frozen(rec):
            return None
        return rec

    def _frozen(self, rec: _ReqTrace) -> bool:
        # during replay, records the dead incarnation observed live
        # hold the truth already — only replay-born records accumulate
        return self._replay and not rec.replayed

    def on_submit(self, rid: int, tenant: str,
                  prompt_tokens: int, gid=None) -> None:
        if rid in self.requests:        # replayed submit of a known
            return                      # rid: the live record stands
        if len(self.requests) >= self.max_requests:
            # long-lived servers: evict the OLDEST terminal record
            # (dict order == submission order) so memory stays
            # bounded; live records are never evicted
            victim = next((k for k, r in self.requests.items()
                           if r.outcome is not None), None)
            if victim is not None:
                del self.requests[victim]
                self.evicted_requests += 1
        ts = self.now()
        rec = _ReqTrace(rid, tenant, ts, replayed=self._replay,
                        gid=None if gid is None else int(gid))
        rec.events.append((ts, "submitted",
                           {"prompt_tokens": int(prompt_tokens)}))
        self.requests[rid] = rec
        self.registry.count("requests.submitted")

    def on_admitted(self, rid: int, slot: int, retry: bool) -> None:
        rec = self._req(rid)
        if rec is None:
            return
        ts = self.now()
        if rec.admit_ts is None:
            rec.admit_ts = ts
        if rec._preempt_ts is not None:
            rec.stall_s += ts - rec._preempt_ts
            rec._preempt_ts = None
        self._rec_event(rec, ts, "readmitted" if retry else "admitted",
                        {"slot": int(slot)})

    def on_prefill_chunk(self, rid: int, pos: int) -> None:
        rec = self._req(rid)
        if rec is None:
            return
        rec.chunks += 1
        self._rec_event(rec, self.now(), "prefill_chunk",
                        {"pos": int(pos)})

    def on_first_token(self, rid: int) -> None:
        rec = self._req(rid)
        if rec is None:
            return
        if rec.first_ts is None:
            rec.first_ts = self.now()
            self._rec_event(rec, rec.first_ts, "first_token")

    def on_decode(self, rids, n: int) -> None:
        """One fused step consumed ``n`` decode tokens for each rid —
        counted, not evented (the hot path of the hot path). Frozen
        (replayed) records count nowhere: neither their per-request
        tally nor the registry counter — replay must not inflate
        either."""
        ts = self.now()
        counted = 0
        for rid in rids:
            rec = self._req(rid)
            if rec is None:
                continue
            rec.tokens += n
            rec.last_ts = ts
            counted += 1
        if counted:
            self.registry.count("tokens.decoded", n * counted)

    def on_rollback(self, rid: int, rejected: int) -> None:
        rec = self._req(rid)
        if rec is None:
            return
        rec.tokens -= rejected      # consumed-but-rejected rows leave
        self._rec_event(rec, self.now(), "rolled_back",
                        {"rejected": int(rejected)})

    def on_preempted(self, rid: int) -> None:
        rec = self._req(rid)
        if rec is None:
            return
        rec.preemptions += 1
        rec._preempt_ts = self.now()
        self._rec_event(rec, rec._preempt_ts, "preempted")

    def on_outcome(self, rid: int, status: str, step: int,
                   reason: str = "") -> None:
        rec = self._req(rid)
        if rec is None or rec.outcome is not None:
            return                  # terminal exactly once per record
        ts = self.now()
        rec.outcome = status
        rec.outcome_step = int(step)
        # terminal event rides even past the cap: drop a middle entry
        # rather than lose the verdict from the log
        if len(rec.events) >= self.MAX_REQ_EVENTS:
            del rec.events[self.MAX_REQ_EVENTS // 2]
        rec.events.append((ts, status,
                           {"reason": reason[:120]} if reason else None))
        self.registry.count(f"outcomes.{status}")
        if not rec.replayed:
            for name in self.LATENCIES:
                v = getattr(rec, name)
                if v is not None:
                    self.registry.observe(f"latency.{name}", v)
                    # the per-tenant split the SLO tracker windows
                    # over (values_since / percentiles_since)
                    self.registry.observe(
                        f"latency.{name}.tenant.{rec.tenant}", v)

    # -- replay mode --------------------------------------------------
    def set_replay(self, on: bool) -> None:
        """Journal replay bracket (RecoverableServer.recover): spans
        record flagged, live-observed request records freeze — replay
        neither diverges the trace nor double-counts it."""
        self._replay = bool(on)

    # -- summaries / export -------------------------------------------
    def request_summary(self) -> dict:
        """Per-tenant (+ overall) percentiles of TTFT / TPOT /
        queue-wait / preemption-stall over TERMINAL, non-replayed
        requests (a replay-born record's stamps are replay times, not
        serving latencies — excluded)."""
        done = [r for r in self.requests.values()
                if r.outcome is not None and not r.replayed]
        by_tenant: Dict[str, list] = {}
        for r in done:
            by_tenant.setdefault(r.tenant, []).append(r)

        def roll(recs):
            out = {"requests": len(recs),
                   "tokens": sum(r.tokens for r in recs),
                   "preemptions": sum(r.preemptions for r in recs)}
            for name in self.LATENCIES:
                out[name] = percentiles(getattr(r, name)
                                        for r in recs)
            return out

        return {"overall": roll(done),
                "per_tenant": {t: roll(rs)
                               for t, rs in by_tenant.items()}}

    def group_summary(self) -> dict:
        """Per fork-shared branch group (scheduler ``submit(n>1)`` /
        ``fork_stream``): branch count, total tokens, and GROUP TTFT —
        the wall time from the group's earliest submit (the lead's;
        branches are forked later, at prefill completion) to the
        earliest first token emitted by ANY member. That is the
        latency the caller of one n-way request observes, which
        per-branch ``ttft_s`` (tiny for forked branches) does not
        measure. Non-replayed records only, keyed by str(gid) for
        JSON round-tripping."""
        by_gid: Dict[int, list] = {}
        for r in self.requests.values():
            if r.gid is not None and not r.replayed:
                by_gid.setdefault(r.gid, []).append(r)
        out = {}
        for gid, recs in by_gid.items():
            firsts = [r.first_ts for r in recs if r.first_ts is not None]
            submit = min(r.submit_ts for r in recs)
            out[str(gid)] = {
                "branches": len(recs),
                "tokens": sum(r.tokens for r in recs),
                "group_ttft_s": None if not firsts
                else round(min(firsts) - submit, 6),
                "outcomes": sorted(r.outcome for r in recs
                                   if r.outcome is not None)}
        return out

    def as_dict(self) -> dict:
        return {"steps": self.steps,
                "replayed_steps": self.replayed_steps,
                "timeline_events": len(self.events),
                "dropped_events": self.dropped,
                "requests": len(self.requests),
                "evicted_requests": self.evicted_requests,
                "registry": self.registry.as_dict(),
                "summary": self.request_summary(),
                "groups": self.group_summary()}

    def chrome_trace(self) -> dict:
        """The ``trace_events`` JSON object (Chrome/Perfetto): engine
        timeline on pid 1, request lifecycles as async events on
        pid 2, request/summary/registry dumps in ``metadata``."""
        evs: List[dict] = [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
             "args": {"name": "engine"}},
            {"name": "process_name", "ph": "M", "pid": 2, "tid": 0,
             "args": {"name": "requests"}},
        ]
        for ev in self.events:
            out = dict(ev)
            out["ts"] = round(out["ts"] * 1e6, 1)
            if "dur" in out:
                out["dur"] = round(out["dur"] * 1e6, 1)
            out.setdefault("pid", 1)
            out.setdefault("tid", 0)
            evs.append(out)
        for rec in self.requests.values():
            if not rec.events:
                continue
            rid = str(rec.rid)
            name = f"req {rec.rid}"
            args = {"tenant": rec.tenant, "replayed": rec.replayed}
            t_first = rec.events[0][0]
            evs.append({"name": name, "cat": "request", "ph": "b",
                        "id": rid, "ts": round(t_first * 1e6, 1),
                        "pid": 2, "tid": 0, "args": args})
            for ts, ev_name, ev_args in rec.events:
                e = {"name": ev_name, "cat": "request", "ph": "n",
                     "id": rid, "ts": round(ts * 1e6, 1),
                     "pid": 2, "tid": 0}
                if ev_args:
                    e["args"] = dict(ev_args)
                evs.append(e)
            t_last = rec.events[-1][0]
            evs.append({"name": name, "cat": "request", "ph": "e",
                        "id": rid, "ts": round(t_last * 1e6, 1),
                        "pid": 2, "tid": 0})
        return {"traceEvents": evs, "displayTimeUnit": "ms",
                "metadata": {
                    "requests": {r.rid: r.as_dict()
                                 for r in self.requests.values()},
                    "summary": self.request_summary(),
                    "registry": self.registry.as_dict(),
                    "steps": self.steps,
                    "replayed_steps": self.replayed_steps,
                    "dropped_events": self.dropped}}

    def save_chrome_trace(self, path: str) -> int:
        """Write ``chrome_trace()`` as JSON; returns bytes written."""
        blob = json.dumps(self.chrome_trace(), default=_json_default)
        with open(path, "w") as f:
            f.write(blob)
        return len(blob)


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o).__name__}")
