"""Continuous-batching serving engine over the fused decoder stack.

ref: /root/reference/paddle/fluid/operators/fused/
fused_multi_transformer_op.cu.h:835 — the reference decodes a FIXED
batch with per-batch valid lengths (masked mha over cache_kv). This
engine supplies the serving shape the reference leaves to external
stacks (and the PAPERS.md ragged-serving direction): a fixed pool of
cache SLOTS, each an independent sequence at its own position; one
fused decode step advances every active slot (ragged lengths ride the
per-row seq_lens of the flash-decode kernel / per-row mask), and
finished slots are freed and re-filled by new requests WITHOUT
stopping the batch — continuous batching.

The model contract is FusedMultiTransformer's decode protocol:
``model(x, caches=..., time_step=...) -> (hidden, new_caches)`` with
caches shaped [2, B, H, max_len, D] per layer and time_step a per-row
int32 vector. Prefill of a new request runs batch-1 against a fresh
single-row cache and is scattered into the slot, so in-flight slots
never stall.
"""
from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

from ..framework.tensor import Tensor
from .telemetry import StatsBase

__all__ = ["ContinuousBatchingEngine", "ParallelStats",
           "PrefillStats", "PrefixCacheStats", "ResilienceStats",
           "ShardedServingCore", "SpecDecodeStats", "TenantStats"]

# The five stats siblings below share ONE declarative base
# (telemetry.StatsBase): each lists its counter FIELDS, the DERIVED
# properties to export next to them (with rounding), and the REPR
# headline subset — as_dict()/__repr__ are generated, so every stat a
# class declares is export-visible by construction and the engines'
# MetricsRegistry can attach them wholesale.


class PrefixCacheStats(StatsBase):
    """Serving-surface accounting for the cross-request prefix cache
    (PagedServingEngine(prefix_cache=True)): block-level hit rate and
    the prefill work the cache saved. One instance per engine, read by
    benches/dashboards; counters only ever grow.

      lookups         admissions that probed the index
      lookup_blocks   full prompt blocks eligible to hit
      hit_blocks      blocks shared instead of allocated
      tokens_skipped  prompt tokens whose prefill was skipped
      tokens_computed prompt tokens actually prefilled
    """

    __slots__ = FIELDS = ("lookups", "lookup_blocks", "hit_blocks",
                          "tokens_skipped", "tokens_computed")
    DERIVED = {"blocks_saved": None, "hit_rate": 4}
    REPR = ("hit_rate", "blocks_saved", "tokens_skipped")

    @property
    def blocks_saved(self) -> int:
        """Pages neither allocated nor prefilled thanks to sharing."""
        return self.hit_blocks

    @property
    def hit_rate(self) -> float:
        if self.lookup_blocks == 0:
            return 0.0
        return self.hit_blocks / self.lookup_blocks


class PrefillStats(StatsBase):
    """Serving-surface accounting for CHUNKED PAGED PREFILL
    (scheduler.chunked_prefill / PagedServingEngine), sibling of
    PrefixCacheStats and SpecDecodeStats; counters only grow.

      chunks          chunk model calls run (each writes its K/V
                      straight into pages — no dense scratch)
      prefill_tokens  prompt tokens streamed through those chunks
      prefill_steps   engine steps that advanced at least one pending
                      prefill (token-budget mixed-step mode)
      decode_steps    engine steps that ran the fused decode call
      mixed_steps     steps that did BOTH — the Sarathi-style packing
                      signal (prefill riding along instead of
                      stalling the running batch)
      peak_blocks     high-water pool blocks in use (sampled after
                      every chunk AND every decode step's growth) —
                      with the dense scratch retired this IS the peak
                      KV footprint
    """

    __slots__ = FIELDS = ("chunks", "prefill_tokens", "prefill_steps",
                          "decode_steps", "mixed_steps", "peak_blocks")
    DERIVED = {"tokens_per_chunk": 2, "mixed_step_rate": 4,
               "prefill_tokens_per_step": 2}
    REPR = ("chunks", "prefill_tokens", "mixed_step_rate",
            "peak_blocks")

    @property
    def tokens_per_chunk(self) -> float:
        if self.chunks == 0:
            return 0.0
        return self.prefill_tokens / self.chunks

    @property
    def prefill_tokens_per_step(self) -> float:
        """Mean prompt tokens advanced per prefill-carrying step (the
        token-budget utilization signal)."""
        if self.prefill_steps == 0:
            return 0.0
        return self.prefill_tokens / self.prefill_steps

    @property
    def mixed_step_rate(self) -> float:
        """Fraction of steps that packed prefill chunks alongside
        decode rows."""
        total = self.decode_steps + self.prefill_steps \
            - self.mixed_steps
        if total == 0:
            return 0.0
        return self.mixed_steps / total


class ResilienceStats(StatsBase):
    """Serving-surface accounting for the resilience layer
    (inference/resilience.py + the per-request failure isolation in
    scheduler.py), sibling of PrefixCacheStats / PrefillStats /
    SpecDecodeStats; counters only grow.

      shed             requests FAILED_OOM: pool dry even after
                       preempting every other request, or the
                       re-prefill retry budget (max_preemptions)
                       exhausted — the request is failed and its
                       blocks freed, the step completes for everyone
                       else
      retried          re-admissions of previously preempted requests
                       (each one replays its history bit-identically)
      deadline_failed  requests FAILED_DEADLINE (per-request
                       deadline_steps / deadline_s blown, admitted or
                       still queued)
      nan_failed       requests FAILED_NUMERIC (non-finite hidden in
                       the slot's fused-step output row)
      rejected         requests REJECTED_ADMISSION (health-based
                       admission control refused them at submit:
                       quota- or pool-impossible, or the deadline
                       below the prefill-step lower bound)
      cancelled        requests CANCELLED — deliberate early stop
                       (best-of-n loser pruning, beam cuts, caller
                       cancel); NOT counted as a failure
      audits           check_invariants() passes run through the
                       engine surface
    """

    __slots__ = FIELDS = ("shed", "retried", "deadline_failed",
                          "nan_failed", "rejected", "cancelled",
                          "audits")
    DERIVED = {"failed": None}
    REPR = ("shed", "retried", "deadline_failed", "nan_failed",
            "rejected")

    @property
    def failed(self) -> int:
        """Total requests that ended in a failure outcome."""
        return (self.shed + self.deadline_failed + self.nan_failed
                + self.rejected)


class TenantStats(StatsBase):
    """Per-tenant serving accounting (multi-tenant isolation,
    scheduler.py): one instance per tenant in
    ``PagedServingEngine.tenant_stats``, the attribution surface that
    makes a noisy neighbor VISIBLE — which tenant sheds, which tenant
    gets rejected, which tenant holds the pool. Counters only grow
    except ``blocks_held``, a live gauge refreshed at every step top.

      admitted       requests of this tenant granted a slot (including
                     re-admissions after preemption)
      sheds          requests FAILED_OOM — pool or tenant quota dry
      rejections     requests REJECTED_ADMISSION at submit
      quota_hits     growth/admission attempts that ran into THIS
                     tenant's block quota (each may preempt or shed
                     within the tenant, never a neighbor)
      preemptions    evictions charged to this tenant's requests
      deadline_failed / nan_failed / cancelled   per-tenant split of
                     the engine ResilienceStats counters
      blocks_held    pool blocks currently charged to the tenant (one
                     charge per block-table reference its slots hold)
      tokens_served  decode tokens consumed by this tenant's slots
                     through fused steps
    """

    __slots__ = FIELDS = ("admitted", "sheds", "rejections",
                          "quota_hits", "preemptions",
                          "deadline_failed", "nan_failed", "cancelled",
                          "blocks_held", "tokens_served")
    DERIVED = {"failed": None}
    REPR = ("blocks_held", "tokens_served", "sheds", "rejections",
            "quota_hits")

    @property
    def failed(self) -> int:
        return (self.sheds + self.rejections + self.deadline_failed
                + self.nan_failed)


class ParallelStats(StatsBase):
    """Serving-surface accounting for fork-shared parallel decoding
    (branch groups, scheduler.py): one ``submit(n=k)`` prefills the
    prompt ONCE and COW-forks k branch slots over the same prompt
    pages. Sibling of the other stats classes; counters only grow.

      groups                branch groups admitted (submit(n>1) that
                            passed the health gate, plus on-demand
                            groups minted by ``fork_stream``)
      branches              branch slots forked (excludes the lead:
                            a group of n adds n-1 here; every
                            ``fork_stream`` clone adds 1)
      prefill_tokens_saved  prompt tokens whose prefill the fork
                            skipped (branch length at fork time,
                            summed over branches) — the work the
                            shared prefill amortized
      shared_blocks         block-table references the forks added to
                            already-resident pages (each one a page
                            NOT allocated; charged per reference
                            under the PR 7 quota policy)
    """

    __slots__ = FIELDS = ("groups", "branches",
                          "prefill_tokens_saved", "shared_blocks")
    DERIVED = {"branches_per_group": 2}
    REPR = ("groups", "branches", "prefill_tokens_saved")

    @property
    def branches_per_group(self) -> float:
        if self.groups == 0:
            return 0.0
        return self.branches / self.groups


class SpecDecodeStats(StatsBase):
    """Serving-surface accounting for speculative decoding
    (inference/speculative.py), the sibling of PrefixCacheStats. One
    counter bump per (slot, verification step); counters only grow.

      proposed          draft tokens offered to verification
      accepted          draft tokens the target model agreed with
      emitted           tokens actually emitted (accepted + the one
                        bonus/correction token per step)
      target_steps      per-slot target verification steps — the cost
                        unit speculation amortizes
      draft_steps       per-slot draft model forward steps
      rolled_back       rejected tokens rolled back via page-table
                        truncation
      draft_oom_rolls   draft rolls aborted by a draft-pool BlockOOM
                        (the partial roll is rolled back page-wise and
                        the round serves without speculation)
    """

    __slots__ = FIELDS = ("proposed", "accepted", "emitted",
                          "target_steps", "draft_steps", "rolled_back",
                          "draft_oom_rolls")
    DERIVED = {"acceptance_rate": 4, "tokens_per_target_step": 4}
    REPR = ("acceptance_rate", "tokens_per_target_step", "emitted")

    @property
    def acceptance_rate(self) -> float:
        if self.proposed == 0:
            return 0.0
        return self.accepted / self.proposed

    @property
    def tokens_per_target_step(self) -> float:
        """Mean tokens emitted per target-model step — the speculative
        speedup signal (1.0 == plain decode; K+1 == every proposal
        accepted)."""
        if self.target_steps == 0:
            return 0.0
        return self.emitted / self.target_steps


def _make_pad_heads(shard: int, heads_per_shard: int, num_heads: int):
    import jax.numpy as jnp

    def mp_pad_heads(a):
        # a [b, l, H/mp, D] -> [b, l, H, D], zeros outside this
        # shard's contiguous head slice: the shard's DISJOINT-support
        # contribution to the layer all-reduce. Summing the mp padded
        # contributions reconstructs the full-head attention output
        # BITWISE (x + 0.0 is exact in IEEE for every normal x) — the
        # property the sharded path's bit-identity proof rests on.
        out = jnp.zeros(a.shape[:2] + (num_heads,) + a.shape[3:],
                        a.dtype)
        lo = shard * heads_per_shard
        return out.at[:, :, lo:lo + heads_per_shard].set(a)
    return mp_pad_heads


def _uncommitted(arr):
    """Rebind a (possibly committed) jax array as UNCOMMITTED without
    leaving the device: downstream ops stay free to colocate with the
    next committed operand they meet instead of dragging everything to
    this array's device. The ArrayImpl rewrap is a zero-copy metadata
    op (same buffers, committed=False); if a jax upgrade moves the
    class, fall back to the legacy host round-trip rather than break
    serving."""
    try:
        from jax._src.array import ArrayImpl
        return ArrayImpl(arr.aval, arr.sharding,
                         [s.data for s in arr.addressable_shards],
                         committed=False)
    except Exception:  # pragma: no cover - jax-internal API drift
        import jax.numpy as jnp
        # survival fallback only — reached iff ArrayImpl moved
        return jnp.asarray(np.asarray(arr))  # lint: ok(compiled-step-purity)


class ShardedServingCore:
    """Tensor-parallel (head-sharded) serving twin of a
    FusedMultiTransformer core — the model half of sharded paged
    serving (the pool half is ``PagedKVCache(mp=N)``,
    inference/paged_cache.py). Megatron-style partition chosen so the
    CPU-mesh proof can be BIT-IDENTICAL to the single chip:

      * qkv projection: COLUMN-sharded by head — shard s owns
        ``[d, 3 * H/mp * hd]`` (its q/k/v column groups, bias sliced
        alike) — on the KERNEL (TPU) path. On the CPU proof path the
        column-sliced GEMM is NOT bitwise column-stable at serving
        widths (measured: XLA CPU matmul columns shift ~1 ulp with
        the output width at d=256 — the same executable-shape trap
        class as scheduler.MIN_PREFILL_SUFFIX_ROWS and PR 10's
        row-count finding), so there the REPLICATED projection runs
        once per layer — the exact single-chip executable — and each
        shard slices ITS HEADS out of the result (slicing is exact at
        every width). ``qkv_shard`` picks: "auto" (default — weights
        on TPU, activations on CPU), "weights", "activations".
      * attention: shard s appends to and attends over ITS pool slice
        only (heads are independent — per-shard outputs are bitwise
        the head slices of the full launch). One ragged kernel launch
        per layer per shard on TPU; the jnp fallbacks inherit.
      * the layer closes with ONE ALL-REDUCE: each shard contributes
        its attention output zero-padded to full heads (disjoint
        support), the sum reconstructs the full tensor exactly, and
        the out projection + FFN + LayerNorms run REPLICATED on it —
        the same executables, the same bytes, as the single chip.

    That is exactly ``num_layers`` collectives per model call
    (``allreduce_count`` is the acceptance counter), weights sharded
    where memory matters (qkv columns; the KV pool is the real win —
    per-request HBM headroom multiplies by the mesh width) and
    replicated where exactness matters (out/ffn/ln).

    Placement: ``devices`` (default
    ``parallel.mesh.serving_shard_devices(mp)``) commits shard s's
    qkv slices — and, through ``PagedKVCache.for_model``, its pool
    slice — to device s. On a single-device host the shards are
    LOGICAL (numerics and collective schedule identical, placement
    degenerate), which is how tier-1 proves bit-identity in-process;
    a real mesh (e.g. ``XLA_FLAGS=--xla_force_host_platform_device_
    count=N``) places them on N distinct devices and the all-reduce
    performs real cross-device transfers. This host-orchestrated
    collective is the CPU-mesh PROOF vehicle; the TPU deployment leg
    lowers the same schedule to jax.lax.psum under shard_map (ROADMAP
    hardware residual).

    The wrapper speaks the full FusedMultiTransformer serving
    protocol (``model(x, caches=..., time_step=...)``) for PAGED
    caches — decode, multi-token verify, chunked prefill and the
    packed ragged mixed step all ride the per-shard views'
    ``shard(s)`` accessor — so PagedServingEngine, SpeculativeEngine,
    RecoverableServer and the router compose unchanged. Dense
    (non-paged) caches are not served: sharding exists for the paged
    pool. Weights are SNAPSHOTTED at wrap time (like
    ``quantize_weights``): shard after the weights are final."""

    def __init__(self, base, mp: int, devices=None,
                 qkv_shard: str = "auto", compiled_step="auto",
                 out_shard: str = "auto"):
        import jax
        import jax.numpy as jnp
        if getattr(base, "_quantized", False):
            raise ValueError(
                "int8 cores drop their float weights at quantize "
                "time — shard the float core first (int8 core "
                "projections are a ROADMAP follow-up)")
        if hasattr(base, "moe_spec"):
            raise ValueError(
                "MoE cores shard over EXPERTS, not attention heads — "
                "use MoeServingCore.shard_experts(ep) "
                "(inference/moe_serving.py); composing ep x mp is a "
                "ROADMAP follow-up")
        self.base = base
        self.mp = int(mp)
        if self.mp < 1:
            raise ValueError(f"mp must be >= 1, got {mp}")
        if base.num_heads % self.mp:
            raise ValueError(
                f"num_heads {base.num_heads} must divide evenly over "
                f"mp={self.mp} shards")
        if devices is None:
            from ..parallel.mesh import serving_shard_devices
            devices = serving_shard_devices(self.mp)
        if len(devices) < self.mp:
            raise ValueError(f"need {self.mp} shard devices, got "
                             f"{len(devices)}")
        self.shard_devices = list(devices[:self.mp])
        self._distinct = len(set(self.shard_devices)) > 1
        try:
            on_tpu = jax.devices()[0].platform in ("tpu", "axon")
        except Exception:  # pragma: no cover
            on_tpu = False
        if qkv_shard == "auto":
            # the house rule (PR 10's ragged_step precedent): the
            # memory-sharded executable engages where it wins (TPU);
            # the CPU proof path keeps the decomposition that is
            # bitwise exact at every width (see class docstring)
            qkv_shard = "weights" if on_tpu else "activations"
        if qkv_shard not in ("weights", "activations"):
            raise ValueError(f"qkv_shard must be 'auto' | 'weights' |"
                             f" 'activations', got {qkv_shard!r}")
        self.qkv_shard = qkv_shard
        E = base.embed_dim
        Hs = self.heads_per_shard
        hd = base.head_dim
        # per-(layer, shard) qkv column slices, committed to the
        # shard's device on a real mesh. Column index set of shard s:
        # the q, k and v blocks' head-group columns — matches the
        # base's split(qkv, 3)-then-reshape head slicing exactly.
        # Built only on the weight-sharded path; the activation path
        # runs the base module's replicated projection.
        self._qkv_w: List[List[Tensor]] = []
        self._qkv_b: List[List[Optional[Tensor]]] = []
        if qkv_shard == "weights":
            cols = {}
            for s in range(self.mp):
                c = np.concatenate(
                    [np.arange(s * Hs * hd, (s + 1) * Hs * hd)
                     + j * E for j in range(3)])
                cols[s] = np.asarray(c, np.int32)
            for blk in base.layers:
                w = blk.qkv.weight.data
                bia = None if blk.qkv.bias is None \
                    else blk.qkv.bias.data
                ws, bs = [], []
                for s in range(self.mp):
                    wsl = jnp.take(w, jnp.asarray(cols[s]), axis=1)
                    bsl = None if bia is None else jnp.take(
                        bia, jnp.asarray(cols[s]), axis=0)
                    if self._distinct:
                        dev = self.shard_devices[s]
                        wsl = jax.device_put(wsl, dev)
                        if bsl is not None:
                            bsl = jax.device_put(bsl, dev)
                    ws.append(Tensor(wsl))
                    bs.append(None if bsl is None else Tensor(bsl))
                self._qkv_w.append(ws)
                self._qkv_b.append(bs)
        # acceptance counter: ONE all-reduce per layer per model call
        # on the sharded path (mp > 1); reset freely from tests
        self.allreduce_count = 0
        # -- compiled step (one jitted shard_map program per call) ----
        if out_shard == "auto":
            # rows = the true Megatron second GEMM (K-split partial
            # sums) — exact only where the GEMM is column-stable
            # (TPU); the CPU proof path psums the zero-padded head
            # sums and runs the out projection replicated, bitwise
            # the single-chip executable
            out_shard = "rows" if on_tpu else "replicated"
        if out_shard not in ("rows", "replicated"):
            raise ValueError(f"out_shard must be 'auto' | 'rows' | "
                             f"'replicated', got {out_shard!r}")
        self.out_shard = out_shard
        fully_distinct = len(set(self.shard_devices)) == self.mp
        if compiled_step == "auto":
            compiled_step = self.mp > 1 and fully_distinct
        if compiled_step not in (True, False):
            raise ValueError(f"compiled_step must be 'auto' | True | "
                             f"False, got {compiled_step!r}")
        if compiled_step and (self.mp < 2 or not fully_distinct):
            raise ValueError(
                "compiled_step=True needs mp >= 2 distinct shard "
                "devices (a real Mesh); logical same-device shards "
                "serve on the legacy host-staged path")
        self.compiled_step = compiled_step
        self._compiled = None
        if compiled_step:
            from .compiled_step import CompiledStepRunner
            self._compiled = CompiledStepRunner(self)

    # -- geometry delegation (the protocol surface engines read) ------
    @property
    def num_layers(self):
        return self.base.num_layers

    @property
    def num_heads(self):
        return self.base.num_heads

    @property
    def head_dim(self):
        return self.base.head_dim

    @property
    def embed_dim(self):
        return self.base.embed_dim

    @property
    def heads_per_shard(self) -> int:
        return self.base.num_heads // self.mp

    @property
    def layers(self):
        return self.base.layers

    @property
    def normalize_before(self):
        return self.base.normalize_before

    @property
    def _act_name(self):
        return self.base._act_name

    @property
    def activation(self):
        return self.base.activation

    def gen_paged_cache(self, block_size, num_blocks, max_seqs,
                        max_blocks_per_seq=None, dtype="float32",
                        prefix_cache=False):
        """Sharded pool matching this core's mesh layout (the engines
        call PagedKVCache.for_model, which reads the same fields)."""
        from .paged_cache import PagedKVCache
        return PagedKVCache.for_model(
            self, block_size, num_blocks, max_seqs,
            max_blocks_per_seq=max_blocks_per_seq, dtype=dtype,
            prefix_cache=prefix_cache)

    def reset_allreduce_count(self) -> None:
        self.allreduce_count = 0

    @property
    def prefers_packed_step(self) -> bool:
        """Scheduler hint: the compiled step amortizes best when the
        whole mixed batch rides ONE packed ragged program, so the
        scheduler should take the ragged plan whenever it's legal
        rather than only when per-slot staging would be slower."""
        return self._compiled is not None

    def sharded_metrics(self) -> dict:
        """MetricsRegistry source (attached as ``sharded.*`` by the
        scheduler): dispatch-count instrumentation for the compiled
        step next to the legacy all-reduce counter. A recompile storm
        shows up as ``retraces`` growing past the bucket count."""
        out = {"allreduce_count": self.allreduce_count,
               "mp": self.mp,
               "compiled": 1 if self._compiled is not None else 0}
        if self._compiled is not None:
            out.update(self._compiled.metrics())
        else:
            out.update({"jit_calls": 0, "retraces": 0,
                        "dispatches_per_step": 0, "psums_per_call": 0})
        return out

    def _allreduce(self, parts: List[Tensor]) -> Tensor:
        """THE one collective per layer: sum the shards' zero-padded
        head contributions (disjoint support -> exact reconstruction,
        see _make_pad_heads) in shard order. On a multi-device mesh
        every contribution transfers to shard 0's device and the
        reduced tensor is handed back replicated (host-staged here —
        the CPU-proof emulation of reduce+broadcast; the TPU leg is
        jax.lax.psum). Counted only when something actually crosses
        shards (mp > 1)."""
        if len(parts) == 1:
            return parts[0]
        self.allreduce_count += 1
        total = parts[0]
        if self._distinct:
            import jax
            d0 = self.shard_devices[0]
            for p in parts[1:]:
                # the legacy collective IS a transfer: host-staged
                # reduce-to-shard-0 — the compiled path replaces it
                # with an in-program psum
                total = total + Tensor(
                    jax.device_put(p.data, d0))  # lint: ok(compiled-step-purity)
            # uncommitted replicated result: the out/ffn/ln ops that
            # consume it stay free to colocate with the NEXT
            # committed operand they meet (each shard's qkv slice).
            # The rebind stays ON DEVICE — the old np.asarray round-
            # trip was the per-layer host pull the compiled step
            # exists to kill; the legacy path shouldn't pay it either
            return Tensor(_uncommitted(total.data))
        for p in parts[1:]:
            total = total + p
        return total

    def __call__(self, src, attn_mask=None, caches=None,
                 time_step=None, **kwargs):
        return self.forward(src, attn_mask=attn_mask, caches=caches,
                            time_step=time_step, **kwargs)

    def forward(self, src, attn_mask=None, caches=None,
                time_step=None, **kwargs):
        import jax
        import jax.numpy as jnp
        from ..framework.op import apply
        from ..incubate.nn.fused_transformer import _use_decode_kernel
        from ..nn import functional as F
        from ..ops.manipulation import reshape, split
        from ..ops.pallas.paged_attention import head_slice
        if caches is None or time_step is None or \
                not getattr(caches[0], "is_paged", False):
            raise NotImplementedError(
                "ShardedServingCore serves the PAGED cache protocol "
                "only (caches=PagedKVCache views + time_step) — "
                "dense caches have no sharded pool to win")
        cache_mp = getattr(caches[0], "_cache", None)
        if cache_mp is None or cache_mp.mp != self.mp:
            raise ValueError(
                f"cache mesh width "
                f"{getattr(cache_mp, 'mp', '?')} != model mp "
                f"{self.mp} — build the pool via "
                f"PagedKVCache.for_model(sharded_core, ...)")
        if self._compiled is not None:
            res = self._compiled.forward(src, caches, time_step)
            if res is not None:
                return res
        x = src
        b, l = x.shape[0], x.shape[1]
        E, Hs, hd = self.embed_dim, self.heads_per_shard, self.head_dim
        t = time_step.data if isinstance(time_step, Tensor) \
            else jnp.asarray(time_step, jnp.int32)
        t = jnp.broadcast_to(t.reshape(-1).astype(jnp.int32), (b,))
        use_k = _use_decode_kernel()
        new_caches = []
        for i, blk in enumerate(self.base.layers):
            residual = x
            h = blk.ln(x) if self.normalize_before else x
            qf = kf = vf = None
            if self.qkv_shard == "activations":
                # the replicated projection — the EXACT single-chip
                # executable, run once per layer; shards slice their
                # heads out of the result (exact at every width)
                y = blk.qkv(h)
                qf, kf, vf = split(y, 3, axis=-1)
                qf = reshape(qf, [b, l, self.num_heads, hd])
                kf = reshape(kf, [b, l, self.num_heads, hd])
                vf = reshape(vf, [b, l, self.num_heads, hd])
            parts = []
            for s in range(self.mp):
                if self.qkv_shard == "weights":
                    y = F.linear(h, self._qkv_w[i][s],
                                 self._qkv_b[i][s])
                    q, k, v = split(y, 3, axis=-1)
                    q = reshape(q, [b, l, Hs, hd])
                    k = reshape(k, [b, l, Hs, hd])
                    v = reshape(v, [b, l, Hs, hd])
                else:
                    q = Tensor(head_slice(qf.data, s, self.mp))
                    k = Tensor(head_slice(kf.data, s, self.mp))
                    v = Tensor(head_slice(vf.data, s, self.mp))
                view = caches[i] if self.mp == 1 \
                    else caches[i].shard(s)
                attn_s = view.decode(q, k, v, t, use_kernel=use_k)
                if self.mp == 1:
                    parts.append(attn_s)
                else:
                    parts.append(apply(
                        _make_pad_heads(s, Hs, self.num_heads),
                        (attn_s,), op_name="mp_pad_heads"))
            attn = self._allreduce(parts)
            attn = blk.out_proj(reshape(attn, [b, l, E]))
            x = residual + attn
            if not self.normalize_before:
                x = blk.ln(x)
            residual = x
            hh = blk.ffn_ln(x) if self.normalize_before else x
            hh = blk.ffn2(self.activation(blk.ffn1(hh)))
            x = residual + hh
            if not self.normalize_before:
                x = blk.ffn_ln(x)
            new_caches.append(caches[i])
        return x, new_caches


class ContinuousBatchingEngine:
    def __init__(self, model, max_batch: int, max_len: int,
                 dtype: str = "float32"):
        self.model = model
        self.max_batch = int(max_batch)
        self.max_len = int(max_len)
        self.dtype = dtype
        self.caches: List[Tensor] = model.gen_cache(self.max_batch,
                                                    self.max_len,
                                                    dtype=dtype)
        self.lens = np.zeros(self.max_batch, np.int32)
        self.active = np.zeros(self.max_batch, bool)
        # persistent single-row prefill scratch, reused across
        # admissions (stale tail positions are masked by time_step, so
        # re-zeroing between prompts is unnecessary)
        self._scratch: Optional[List[Tensor]] = None
        # slots auto-released by step() on reaching max_len
        self.finished: List[int] = []

    # -- slot management ----------------------------------------------------
    @property
    def free_slots(self) -> int:
        return int((~self.active).sum())

    def add_request(self, prompt: Tensor) -> Tuple[int, Tensor]:
        """Admit a prompt ([T, d_model] embeddings). Prefills a fresh
        single-row cache and scatters it into a free slot. Returns
        (slot, last_hidden [1, d_model])."""
        import jax.numpy as jnp
        free = np.flatnonzero(~self.active)
        if free.size == 0:
            raise RuntimeError(
                "ContinuousBatchingEngine: no free slots "
                f"(max_batch={self.max_batch}); release() one first")
        slot = int(free[0])
        T = prompt.shape[0]
        if T > self.max_len:
            raise ValueError(f"prompt length {T} > max_len "
                             f"{self.max_len}")
        from ..framework.autograd import no_grad
        if self._scratch is None:
            self._scratch = self.model.gen_cache(1, self.max_len,
                                                 dtype=self.dtype)
        # serving never backprops: without no_grad the tape would pin
        # every superseded cache version across the decode loop.
        # time_step rides as a TENSOR scalar so prefill attends over
        # the scratch's FULL extent with a validity mask (not the
        # int-t [:T] slice): reductions then have one extent for every
        # prompt length, keeping prefill numerics length-independent —
        # the property cross-request prefix reuse is bit-exact under
        with no_grad():
            out, row_caches = self.model(
                prompt.unsqueeze(0), caches=self._scratch,
                time_step=Tensor(np.int32(0)))
        self._scratch = row_caches  # reuse the buffers next admission
        for c, row in zip(self.caches, row_caches):
            c._data = c.data.at[:, slot].set(row.data[:, 0])
        self.lens[slot] = T
        self.active[slot] = True
        return slot, out[:, -1]

    def release(self, slot: int):
        self.active[slot] = False
        self.lens[slot] = 0

    # -- decode -------------------------------------------------------------
    def step(self, x: Tensor) -> Optional[Tensor]:
        """One fused decode step for ALL slots. x: [max_batch, 1,
        d_model] next-token embeddings (inactive rows: any values —
        their cache rows are fully overwritten on reuse). Returns
        hidden [max_batch, 1, d_model]; only active rows are
        meaningful. Advances every active slot's length.

        Slots that already reached max_len are auto-released and
        recorded in ``finished`` — one full sequence no longer stalls
        the rest of the batch. If that empties the batch, returns None
        (drain ``finished`` and admit new requests)."""
        if int(self.active.sum()) == 0:
            raise RuntimeError("step() with no active slots")
        for slot in np.flatnonzero(self.active &
                                   (self.lens >= self.max_len)):
            self.finished.append(int(slot))
            self.release(int(slot))
        if int(self.active.sum()) == 0:
            return None
        from ..framework.autograd import no_grad
        t = Tensor(np.asarray(self.lens, np.int32))
        with no_grad():
            out, self.caches = self.model(x, caches=self.caches,
                                          time_step=t)
        self.lens[self.active] += 1
        return out
