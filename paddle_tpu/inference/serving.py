"""Continuous-batching serving engine over the fused decoder stack.

ref: /root/reference/paddle/fluid/operators/fused/
fused_multi_transformer_op.cu.h:835 — the reference decodes a FIXED
batch with per-batch valid lengths (masked mha over cache_kv). This
engine supplies the serving shape the reference leaves to external
stacks (and the PAPERS.md ragged-serving direction): a fixed pool of
cache SLOTS, each an independent sequence at its own position; one
fused decode step advances every active slot (ragged lengths ride the
per-row seq_lens of the flash-decode kernel / per-row mask), and
finished slots are freed and re-filled by new requests WITHOUT
stopping the batch — continuous batching.

The model contract is FusedMultiTransformer's decode protocol:
``model(x, caches=..., time_step=...) -> (hidden, new_caches)`` with
caches shaped [2, B, H, max_len, D] per layer and time_step a per-row
int32 vector. Prefill of a new request runs batch-1 against a fresh
single-row cache and is scattered into the slot, so in-flight slots
never stall.
"""
from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

from ..framework.tensor import Tensor
from .telemetry import StatsBase

__all__ = ["ContinuousBatchingEngine", "PrefillStats",
           "PrefixCacheStats", "ResilienceStats", "SpecDecodeStats",
           "TenantStats"]

# The five stats siblings below share ONE declarative base
# (telemetry.StatsBase): each lists its counter FIELDS, the DERIVED
# properties to export next to them (with rounding), and the REPR
# headline subset — as_dict()/__repr__ are generated, so every stat a
# class declares is export-visible by construction and the engines'
# MetricsRegistry can attach them wholesale.


class PrefixCacheStats(StatsBase):
    """Serving-surface accounting for the cross-request prefix cache
    (PagedServingEngine(prefix_cache=True)): block-level hit rate and
    the prefill work the cache saved. One instance per engine, read by
    benches/dashboards; counters only ever grow.

      lookups         admissions that probed the index
      lookup_blocks   full prompt blocks eligible to hit
      hit_blocks      blocks shared instead of allocated
      tokens_skipped  prompt tokens whose prefill was skipped
      tokens_computed prompt tokens actually prefilled
    """

    __slots__ = FIELDS = ("lookups", "lookup_blocks", "hit_blocks",
                          "tokens_skipped", "tokens_computed")
    DERIVED = {"blocks_saved": None, "hit_rate": 4}
    REPR = ("hit_rate", "blocks_saved", "tokens_skipped")

    @property
    def blocks_saved(self) -> int:
        """Pages neither allocated nor prefilled thanks to sharing."""
        return self.hit_blocks

    @property
    def hit_rate(self) -> float:
        if self.lookup_blocks == 0:
            return 0.0
        return self.hit_blocks / self.lookup_blocks


class PrefillStats(StatsBase):
    """Serving-surface accounting for CHUNKED PAGED PREFILL
    (scheduler.chunked_prefill / PagedServingEngine), sibling of
    PrefixCacheStats and SpecDecodeStats; counters only grow.

      chunks          chunk model calls run (each writes its K/V
                      straight into pages — no dense scratch)
      prefill_tokens  prompt tokens streamed through those chunks
      prefill_steps   engine steps that advanced at least one pending
                      prefill (token-budget mixed-step mode)
      decode_steps    engine steps that ran the fused decode call
      mixed_steps     steps that did BOTH — the Sarathi-style packing
                      signal (prefill riding along instead of
                      stalling the running batch)
      peak_blocks     high-water pool blocks in use (sampled after
                      every chunk AND every decode step's growth) —
                      with the dense scratch retired this IS the peak
                      KV footprint
    """

    __slots__ = FIELDS = ("chunks", "prefill_tokens", "prefill_steps",
                          "decode_steps", "mixed_steps", "peak_blocks")
    DERIVED = {"tokens_per_chunk": 2, "mixed_step_rate": 4,
               "prefill_tokens_per_step": 2}
    REPR = ("chunks", "prefill_tokens", "mixed_step_rate",
            "peak_blocks")

    @property
    def tokens_per_chunk(self) -> float:
        if self.chunks == 0:
            return 0.0
        return self.prefill_tokens / self.chunks

    @property
    def prefill_tokens_per_step(self) -> float:
        """Mean prompt tokens advanced per prefill-carrying step (the
        token-budget utilization signal)."""
        if self.prefill_steps == 0:
            return 0.0
        return self.prefill_tokens / self.prefill_steps

    @property
    def mixed_step_rate(self) -> float:
        """Fraction of steps that packed prefill chunks alongside
        decode rows."""
        total = self.decode_steps + self.prefill_steps \
            - self.mixed_steps
        if total == 0:
            return 0.0
        return self.mixed_steps / total


class ResilienceStats(StatsBase):
    """Serving-surface accounting for the resilience layer
    (inference/resilience.py + the per-request failure isolation in
    scheduler.py), sibling of PrefixCacheStats / PrefillStats /
    SpecDecodeStats; counters only grow.

      shed             requests FAILED_OOM: pool dry even after
                       preempting every other request, or the
                       re-prefill retry budget (max_preemptions)
                       exhausted — the request is failed and its
                       blocks freed, the step completes for everyone
                       else
      retried          re-admissions of previously preempted requests
                       (each one replays its history bit-identically)
      deadline_failed  requests FAILED_DEADLINE (per-request
                       deadline_steps / deadline_s blown, admitted or
                       still queued)
      nan_failed       requests FAILED_NUMERIC (non-finite hidden in
                       the slot's fused-step output row)
      rejected         requests REJECTED_ADMISSION (health-based
                       admission control refused them at submit:
                       quota- or pool-impossible, or the deadline
                       below the prefill-step lower bound)
      audits           check_invariants() passes run through the
                       engine surface
    """

    __slots__ = FIELDS = ("shed", "retried", "deadline_failed",
                          "nan_failed", "rejected", "audits")
    DERIVED = {"failed": None}
    REPR = ("shed", "retried", "deadline_failed", "nan_failed",
            "rejected")

    @property
    def failed(self) -> int:
        """Total requests that ended in a failure outcome."""
        return (self.shed + self.deadline_failed + self.nan_failed
                + self.rejected)


class TenantStats(StatsBase):
    """Per-tenant serving accounting (multi-tenant isolation,
    scheduler.py): one instance per tenant in
    ``PagedServingEngine.tenant_stats``, the attribution surface that
    makes a noisy neighbor VISIBLE — which tenant sheds, which tenant
    gets rejected, which tenant holds the pool. Counters only grow
    except ``blocks_held``, a live gauge refreshed at every step top.

      admitted       requests of this tenant granted a slot (including
                     re-admissions after preemption)
      sheds          requests FAILED_OOM — pool or tenant quota dry
      rejections     requests REJECTED_ADMISSION at submit
      quota_hits     growth/admission attempts that ran into THIS
                     tenant's block quota (each may preempt or shed
                     within the tenant, never a neighbor)
      preemptions    evictions charged to this tenant's requests
      deadline_failed / nan_failed   per-tenant split of the engine
                     ResilienceStats counters
      blocks_held    pool blocks currently charged to the tenant (one
                     charge per block-table reference its slots hold)
      tokens_served  decode tokens consumed by this tenant's slots
                     through fused steps
    """

    __slots__ = FIELDS = ("admitted", "sheds", "rejections",
                          "quota_hits", "preemptions",
                          "deadline_failed", "nan_failed",
                          "blocks_held", "tokens_served")
    DERIVED = {"failed": None}
    REPR = ("blocks_held", "tokens_served", "sheds", "rejections",
            "quota_hits")

    @property
    def failed(self) -> int:
        return (self.sheds + self.rejections + self.deadline_failed
                + self.nan_failed)


class SpecDecodeStats(StatsBase):
    """Serving-surface accounting for speculative decoding
    (inference/speculative.py), the sibling of PrefixCacheStats. One
    counter bump per (slot, verification step); counters only grow.

      proposed          draft tokens offered to verification
      accepted          draft tokens the target model agreed with
      emitted           tokens actually emitted (accepted + the one
                        bonus/correction token per step)
      target_steps      per-slot target verification steps — the cost
                        unit speculation amortizes
      draft_steps       per-slot draft model forward steps
      rolled_back       rejected tokens rolled back via page-table
                        truncation
      draft_oom_rolls   draft rolls aborted by a draft-pool BlockOOM
                        (the partial roll is rolled back page-wise and
                        the round serves without speculation)
    """

    __slots__ = FIELDS = ("proposed", "accepted", "emitted",
                          "target_steps", "draft_steps", "rolled_back",
                          "draft_oom_rolls")
    DERIVED = {"acceptance_rate": 4, "tokens_per_target_step": 4}
    REPR = ("acceptance_rate", "tokens_per_target_step", "emitted")

    @property
    def acceptance_rate(self) -> float:
        if self.proposed == 0:
            return 0.0
        return self.accepted / self.proposed

    @property
    def tokens_per_target_step(self) -> float:
        """Mean tokens emitted per target-model step — the speculative
        speedup signal (1.0 == plain decode; K+1 == every proposal
        accepted)."""
        if self.target_steps == 0:
            return 0.0
        return self.emitted / self.target_steps


class ContinuousBatchingEngine:
    def __init__(self, model, max_batch: int, max_len: int,
                 dtype: str = "float32"):
        self.model = model
        self.max_batch = int(max_batch)
        self.max_len = int(max_len)
        self.dtype = dtype
        self.caches: List[Tensor] = model.gen_cache(self.max_batch,
                                                    self.max_len,
                                                    dtype=dtype)
        self.lens = np.zeros(self.max_batch, np.int32)
        self.active = np.zeros(self.max_batch, bool)
        # persistent single-row prefill scratch, reused across
        # admissions (stale tail positions are masked by time_step, so
        # re-zeroing between prompts is unnecessary)
        self._scratch: Optional[List[Tensor]] = None
        # slots auto-released by step() on reaching max_len
        self.finished: List[int] = []

    # -- slot management ----------------------------------------------------
    @property
    def free_slots(self) -> int:
        return int((~self.active).sum())

    def add_request(self, prompt: Tensor) -> Tuple[int, Tensor]:
        """Admit a prompt ([T, d_model] embeddings). Prefills a fresh
        single-row cache and scatters it into a free slot. Returns
        (slot, last_hidden [1, d_model])."""
        import jax.numpy as jnp
        free = np.flatnonzero(~self.active)
        if free.size == 0:
            raise RuntimeError(
                "ContinuousBatchingEngine: no free slots "
                f"(max_batch={self.max_batch}); release() one first")
        slot = int(free[0])
        T = prompt.shape[0]
        if T > self.max_len:
            raise ValueError(f"prompt length {T} > max_len "
                             f"{self.max_len}")
        from ..framework.autograd import no_grad
        if self._scratch is None:
            self._scratch = self.model.gen_cache(1, self.max_len,
                                                 dtype=self.dtype)
        # serving never backprops: without no_grad the tape would pin
        # every superseded cache version across the decode loop.
        # time_step rides as a TENSOR scalar so prefill attends over
        # the scratch's FULL extent with a validity mask (not the
        # int-t [:T] slice): reductions then have one extent for every
        # prompt length, keeping prefill numerics length-independent —
        # the property cross-request prefix reuse is bit-exact under
        with no_grad():
            out, row_caches = self.model(
                prompt.unsqueeze(0), caches=self._scratch,
                time_step=Tensor(np.int32(0)))
        self._scratch = row_caches  # reuse the buffers next admission
        for c, row in zip(self.caches, row_caches):
            c._data = c.data.at[:, slot].set(row.data[:, 0])
        self.lens[slot] = T
        self.active[slot] = True
        return slot, out[:, -1]

    def release(self, slot: int):
        self.active[slot] = False
        self.lens[slot] = 0

    # -- decode -------------------------------------------------------------
    def step(self, x: Tensor) -> Optional[Tensor]:
        """One fused decode step for ALL slots. x: [max_batch, 1,
        d_model] next-token embeddings (inactive rows: any values —
        their cache rows are fully overwritten on reuse). Returns
        hidden [max_batch, 1, d_model]; only active rows are
        meaningful. Advances every active slot's length.

        Slots that already reached max_len are auto-released and
        recorded in ``finished`` — one full sequence no longer stalls
        the rest of the batch. If that empties the batch, returns None
        (drain ``finished`` and admit new requests)."""
        if int(self.active.sum()) == 0:
            raise RuntimeError("step() with no active slots")
        for slot in np.flatnonzero(self.active &
                                   (self.lens >= self.max_len)):
            self.finished.append(int(slot))
            self.release(int(slot))
        if int(self.active.sum()) == 0:
            return None
        from ..framework.autograd import no_grad
        t = Tensor(np.asarray(self.lens, np.int32))
        with no_grad():
            out, self.caches = self.model(x, caches=self.caches,
                                          time_step=t)
        self.lens[self.active] += 1
        return out
