"""Crash recovery for the paged serving stack: atomic snapshot
persistence, a write-ahead request journal, and a recoverable token-ID
serving host with exactly-once outcome delivery.

PR 5 made failures INSIDE a live engine survivable (per-request
outcomes, shed/quarantine); this module makes the DEATH OF THE PROCESS
survivable too. The design is snapshot + journal + deterministic
replay, the classic WAL recipe:

* **Snapshot** (``save_snapshot``/``load_snapshot``): the engine's
  ``snapshot()`` dict persisted atomically — write temp, fsync, rename
  — behind a magic + version + length + CRC header, so a truncated or
  foreign file fails with a clear ``SnapshotVersionError`` instead of
  a pickle traceback. Pool pages ride content-addressed
  (PagedKVCache.snapshot), which is also the wire format page
  MIGRATION between pools needs (the disaggregated prefill/decode
  direction in the ROADMAP).

* **Journal** (``RequestJournal``): an append-only log of everything
  that crosses the serving boundary — submissions (token ids +
  resilience/tenancy knobs, written BEFORE the engine sees them),
  per-round emitted tokens, releases, tenant reconfigurations
  (``set_tenant``), and drained outcomes. Records are
  length + CRC framed; a record torn by a crash mid-append is dropped
  on read (the round it described simply replays).

* **Replay** (``RecoverableServer.recover``): restore the last
  snapshot, then replay the journal suffix — re-submit, re-step,
  re-release in the recorded order. Every engine layer is
  deterministic given its inputs (the bit-identity property PRs 1-5
  proved for preemption/prefix/speculation), so the replayed rounds
  regenerate EXACTLY the journaled emissions — checked record by
  record (``RecoveryError`` on divergence, which would mean journal
  corruption or lost determinism). Tokens of an interrupted,
  unjournaled round were never delivered and simply regenerate live.

* **Exactly-once outcomes**: terminal ``RequestOutcome``s are
  delivered only through ``drain_outcomes()``, which journals the
  drained rids in the same breath. Replay regenerates every outcome;
  the journaled drain records suppress the already-delivered ones, so
  across any crash each request's verdict reaches the caller exactly
  once — never lost (an undrained outcome survives in the snapshot or
  regenerates in replay), never duplicated.

Crash scheduling for tests lives in ``resilience.CrashInjector``;
the headline guarantee — under a seeded crash storm over plain,
prefix-cached and speculative serving, every surviving stream is
bit-identical to an uninterrupted run and deep invariants hold after
every restore — is proven in tests/test_recovery.py.
"""
from __future__ import annotations

import os
import pickle
import struct
import zlib
from typing import Dict, List, Optional

import numpy as np

from .resilience import RequestOutcome  # noqa: F401  (re-export surface)
from .speculative import SpeculativeEngine

__all__ = ["SNAPSHOT_VERSION", "SnapshotVersionError", "RecoveryError",
           "save_snapshot", "load_snapshot", "RequestJournal",
           "read_journal", "RecoverableServer", "FRAME_HEADER_SIZE",
           "frame_message", "frame_body_size", "unframe_message"]

SNAPSHOT_MAGIC = b"PTSNAP"
SNAPSHOT_VERSION = 1
_SNAP_HDR = struct.Struct("<IQI")      # version, body length, body crc


class SnapshotVersionError(RuntimeError):
    """The snapshot file is not readable by this build: wrong magic,
    wrong format version, or truncated/corrupt body. Raised INSTEAD of
    a pickle traceback so operators see the actual problem."""


class RecoveryError(RuntimeError):
    """Journal replay diverged from the recorded run (or the journal
    references state the snapshot cannot produce). Indicates journal
    corruption or broken engine determinism — recovery must stop
    rather than serve wrong tokens."""


# -- restricted unpickling ---------------------------------------------
#
# Snapshots and journals are plain data (numpy + containers + ints +
# bytes), so loading them never needs arbitrary globals. pickle.loads
# would execute whatever a malicious file references — and the offline
# doctor (tools/recovery_check.py) is explicitly pointed at files of
# unknown provenance — so every load goes through an allowlist instead:
# a snapshot referencing anything else fails with SnapshotVersionError,
# not code execution.

_ALLOWED_GLOBALS = {
    ("collections", "OrderedDict"),
    ("numpy", "ndarray"), ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _ALLOWED_GLOBALS:
            return super().find_class(module, name)
        raise SnapshotVersionError(
            f"snapshot/journal references disallowed global "
            f"{module}.{name} — refusing to unpickle (the format is "
            f"plain numpy + containers; anything else means a foreign "
            f"or malicious file)")


def _restricted_loads(blob: bytes):
    import io
    return _RestrictedUnpickler(io.BytesIO(blob)).load()


# -- atomic snapshot persistence --------------------------------------

def save_snapshot(path: str, payload: dict) -> int:
    """Persist ``payload`` (any picklable dict) atomically: the bytes
    land in a temp file, are fsync'd, and REPLACE ``path`` in one
    rename — a crash mid-write leaves either the old snapshot or the
    new one, never a torn file. Returns the byte size written."""
    blob = pickle.dumps(payload, protocol=4)
    head = SNAPSHOT_MAGIC + _SNAP_HDR.pack(
        SNAPSHOT_VERSION, len(blob), zlib.crc32(blob) & 0xFFFFFFFF)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(head)
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return len(head) + len(blob)


def load_snapshot(path: str) -> dict:
    """Read a ``save_snapshot`` file, verifying magic, version, length
    and CRC before unpickling; every failure mode is a
    ``SnapshotVersionError`` naming what is wrong."""
    with open(path, "rb") as f:
        data = f.read()
    head_len = len(SNAPSHOT_MAGIC) + _SNAP_HDR.size
    if len(data) < head_len:
        raise SnapshotVersionError(
            f"truncated snapshot {path!r}: {len(data)} bytes, header "
            f"alone is {head_len}")
    if data[:len(SNAPSHOT_MAGIC)] != SNAPSHOT_MAGIC:
        raise SnapshotVersionError(
            f"{path!r} is not a serving snapshot (bad magic "
            f"{data[:len(SNAPSHOT_MAGIC)]!r})")
    ver, n, crc = _SNAP_HDR.unpack_from(data, len(SNAPSHOT_MAGIC))
    if ver != SNAPSHOT_VERSION:
        raise SnapshotVersionError(
            f"snapshot {path!r} is format v{ver}; this build reads "
            f"v{SNAPSHOT_VERSION} — re-snapshot from a matching build")
    body = data[head_len:]
    if len(body) < n:
        raise SnapshotVersionError(
            f"truncated snapshot {path!r}: body {len(body)} of {n} "
            f"bytes")
    body = body[:n]
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise SnapshotVersionError(
            f"corrupt snapshot {path!r}: body CRC mismatch")
    return _restricted_loads(body)


# -- write-ahead request journal --------------------------------------

class RequestJournal:
    """Append-only WAL of serving-boundary events. Each record is
    ``(seq, kind, payload)`` pickled behind a (length, CRC) frame;
    ``read_journal`` drops a torn trailing record (crash mid-append)
    instead of failing. ``fresh=True`` truncates (a brand-new serving
    lineage); the default appends (recovery continues the lineage,
    seq numbering picked up where the journal left off).

    Durability scope: by default ``append`` flushes to the OS but does
    NOT fsync, so records survive death of the serving PROCESS (the
    crash model this subsystem defends) but a host/power loss may drop
    a flushed-yet-unsynced tail — pass ``sync=True`` to fsync every
    append when the journal must survive the machine too (snapshots
    always fsync)."""

    _HDR = struct.Struct("<II")

    def __init__(self, path: str, fresh: bool = False,
                 sync: bool = False, _scanned=None):
        self.path = path
        self.sync = sync
        self.seq = 0
        # intact journal bytes on disk (header + body per record) —
        # the ``journal.bytes`` durability gauge's ground truth
        self.bytes_written = 0
        # intact records found on open (append mode) — recovery reads
        # them from here instead of re-scanning the file
        self.startup_records: List[tuple] = []
        if not fresh:
            # _scanned lets recover() validate the journal READ-ONLY
            # (lineage check) before this open mutates it (torn-tail
            # truncate) — and skips a second full scan
            recs, valid = (_scan_journal(path) if _scanned is None
                           else _scanned)
            if valid is not None and valid < os.path.getsize(path):
                # a torn tail record must be CUT before appending, or
                # everything written after it would sit behind the
                # break and never be read back. Only when there IS a
                # torn tail: an intact journal reopens untouched, so
                # repeated open/recover cycles never re-truncate (or
                # even re-write) a clean file.
                with open(path, "r+b") as f:
                    f.truncate(valid)
            if recs:
                self.seq = recs[-1][0]
            self.startup_records = recs
            self.bytes_written = 0 if valid is None else int(valid)
        self._f = open(path, "wb" if fresh else "ab")

    def append(self, kind: str, payload: dict) -> int:
        self.seq += 1
        data = self._frame((self.seq, kind, payload))
        self._f.write(data)
        self.bytes_written += len(data)
        self._f.flush()
        if self.sync:
            os.fsync(self._f.fileno())
        return self.seq

    @staticmethod
    def _frame(record: tuple) -> bytes:
        blob = pickle.dumps(record, protocol=4)
        return RequestJournal._HDR.pack(
            len(blob), zlib.crc32(blob) & 0xFFFFFFFF) + blob

    def compact(self, upto_seq: int) -> int:
        """Drop every record at or below ``upto_seq`` — a snapshot now
        covers them, so replaying them is impossible (recovery skips
        seq <= the snapshot's journal_seq) and keeping them only grows
        the file without bound on a long-running server. The
        survivors are rewritten behind a COMPACT MARKER record that
        REUSES seq == upto_seq: the marker keeps the file's last seq
        at/past the snapshot's journal_seq, so the recovery lineage
        check ("this journal belongs to this snapshot") still holds on
        an otherwise-empty journal, seq numbering continues unchanged,
        and replay skips it like any other covered record. Atomic
        (write temp + fsync + rename, same recipe as save_snapshot);
        the append handle reopens on the new file. No-op when there is
        nothing to drop. Returns the bytes reclaimed."""
        upto_seq = int(upto_seq)
        recs, _ = _scan_journal(self.path)
        old = [r for r in recs if r[0] <= upto_seq]
        if not old or (len(old) == 1 and old[0][1] == "compact"
                       and old[0][0] == upto_seq):
            return 0
        before = self.bytes_written
        frames = [self._frame((upto_seq, "compact",
                               {"upto": upto_seq}))]
        frames += [self._frame(r) for r in recs if r[0] > upto_seq]
        data = b"".join(frames)
        tmp = f"{self.path}.compact.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")
        self.bytes_written = len(data)
        # the marker frame can outweigh a single tiny dropped record:
        # "reclaimed" never reports negative
        return max(0, before - len(data))

    def close(self) -> None:
        """Idempotent: closing a closed journal is a no-op, and the
        append handle is released exactly once (no fd leak when a
        host retires the same server twice)."""
        if not self._f.closed:
            self._f.close()

    @property
    def closed(self) -> bool:
        return self._f.closed


def _scan_journal(path: str):
    """([(seq, kind, payload)], valid_byte_length) — valid_byte_length
    is None when the file does not exist, else the offset right after
    the last INTACT record (a torn tail starts there). A break is only
    treated as a torn tail when the file ENDS inside the broken record
    — the only shape a crash mid-append can produce. A record whose
    bytes are all present but whose CRC fails, with more data behind
    it, is MID-FILE damage (reordered writeback on power loss, disk
    corruption): truncating there would silently destroy the intact
    records after the hole, so the scan raises ``RecoveryError``
    instead."""
    out: List[tuple] = []
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return out, None
    hdr = RequestJournal._HDR
    off = 0
    while off + hdr.size <= len(data):
        n, crc = hdr.unpack_from(data, off)
        end = off + hdr.size + n
        body = data[off + hdr.size:end]
        if len(body) < n:
            break                              # torn tail (file ends)
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            if end < len(data):
                raise RecoveryError(
                    f"journal {path!r} is damaged MID-FILE: record at "
                    f"byte {off} fails its CRC but {len(data) - end} "
                    f"byte(s) follow — refusing to drop intact "
                    f"records behind the hole")
            break                              # torn tail (last record)
        out.append(_restricted_loads(body))
        off = end
    return out, off


def read_journal(path: str) -> List[tuple]:
    """All intact records of a journal as [(seq, kind, payload)]. A
    torn or CRC-failing TAIL record is silently dropped — that is the
    crash-mid-append case and the event it described never completed.
    Mid-file damage (a broken record with intact data behind it)
    raises ``RecoveryError`` rather than silently losing the rest."""
    return _scan_journal(path)[0]


# -- wire framing ------------------------------------------------------
#
# The journal's (length, CRC32) frame doubles as the fleet's SOCKET
# wire format (inference/fleet.py): one framing discipline everywhere a
# torn byte stream must be DETECTED rather than guessed at. A frame
# that fails its CRC over TCP means the peer died mid-write — exactly
# the torn-tail case on disk — and maps to the same abandonment
# semantics (dead socket == dead pipe).

FRAME_HEADER_SIZE = RequestJournal._HDR.size


def frame_message(obj) -> bytes:
    """One framed message: 8-byte (length, CRC32) header + pickled
    body — byte-compatible with a journal record frame."""
    blob = pickle.dumps(obj, protocol=4)
    return RequestJournal._HDR.pack(
        len(blob), zlib.crc32(blob) & 0xFFFFFFFF) + blob


def frame_body_size(head: bytes) -> int:
    """Body length announced by an 8-byte frame header."""
    return RequestJournal._HDR.unpack(head)[0]


def unframe_message(head: bytes, body: bytes):
    """Decode one framed message from its header + body. Raises
    ``ValueError`` on a CRC mismatch (torn frame) and refuses
    non-allowlisted globals like every other journal load — a socket
    peer gets no more unpickling power than a journal file does."""
    _n, crc = RequestJournal._HDR.unpack(head)
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise ValueError("framed message CRC mismatch (torn frame)")
    return _restricted_loads(body)


# -- recoverable serving host -----------------------------------------

class RecoverableServer:
    """Crash-recoverable host around a ``SpeculativeEngine`` (the
    token-ID surface: ``k=0`` is plain paged serving, ``k=0,
    prefix_cache=True`` adds the prefix cache, ``k>0`` speculates —
    so ONE host covers every serving mode). All traffic flows through
    this object so the journal sees everything:

      submit()          WAL first, then the engine — a crash inside
                        admission replays the submission
      step()            one engine round; emissions journaled after
                        the round, snapshots taken every
                        ``snapshot_every`` rounds
      drain_outcomes()  exactly-once terminal outcomes (see module
                        docstring)
      release()         journaled caller-side finish

    Construction writes snapshot 0 (the empty engine) so a crash
    before the first periodic snapshot still recovers;
    ``RecoverableServer.recover`` rebuilds from the files after an
    ``EngineCrash`` (or a real process restart)."""

    def __init__(self, engine: SpeculativeEngine, *, journal_path: str,
                 snapshot_path: str, snapshot_every: int = 0,
                 sync: bool = False, compact_journal: bool = True,
                 _fresh: bool = True):
        self.engine = engine
        self.injector = engine.injector
        self.journal_path = journal_path
        self.snapshot_path = snapshot_path
        self.snapshot_every = int(snapshot_every)
        self.sync = bool(sync)      # fsync journal appends (host-death
                                    # durability; see RequestJournal)
        # drop journal records a successful snapshot covers (they can
        # never replay again — recovery skips seq <= the snapshot's):
        # bounds the journal on a long-running server. False keeps the
        # full history on disk (debugging/forensics).
        self.compact_journal = bool(compact_journal)
        self.rounds = 0                 # rounds served, live + replayed
        self.replayed_rounds = 0
        self.replayed_tokens = 0
        self.snapshots_taken = 0
        self.snapshot_bytes = 0
        self._delivered: set = set()    # rids whose outcome was drained
        # outcomes handed to the caller but not yet journaled: the
        # drain record is written at the START of the next server call
        # (before any crash point), so a death BETWEEN calls leaves
        # them unjournaled and recovery RE-DELIVERS them — the caller
        # that would have held them died with the process, so
        # re-delivery is what exactly-once means post-recovery
        self._pending_drain: List[list] = []
        # durability ground truth in the ALWAYS-ON registry (the
        # journal-lag health alert's source — previously these existed
        # only as trace spans): records appended since the last
        # snapshot, intact journal bytes, and engine steps since the
        # last snapshot. Live sources — read at scrape time, zero
        # hot-path cost.
        self._snap_seq = 0          # journal.seq at the last snapshot
        self._snap_step = 0         # engine step at the last snapshot
        self._closed = False
        engine.registry.attach("journal", self._journal_gauges)
        engine.registry.attach("snapshot", self._snapshot_gauges)
        if _fresh:
            self.journal = RequestJournal(journal_path, fresh=True,
                                          sync=self.sync)
            self.save_snapshot()

    def _engine_step(self) -> int:
        return self.engine.engine._step_count

    def _journal_gauges(self) -> dict:
        j = getattr(self, "journal", None)   # recover() wires it late
        if j is None:
            return {"lag_records": 0, "bytes": 0}
        return {"lag_records": j.seq - self._snap_seq,
                "bytes": j.bytes_written}

    def _snapshot_gauges(self) -> dict:
        return {"age_steps": self._engine_step() - self._snap_step}

    # -- persistence --------------------------------------------------
    def _flush_drains(self) -> None:
        if self._pending_drain:
            self.journal.append("outcomes",
                                {"rids": self._pending_drain})
            self._pending_drain = []

    def save_snapshot(self) -> None:
        # the snapshot's delivered set must never run ahead of the
        # journal: flush first so a crash right after the rename can
        # still account for every delivery it suppresses
        self._flush_drains()
        self.snapshot_bytes = save_snapshot(self.snapshot_path, {
            "kind": "recoverable_server",
            "engine": self.engine.snapshot(),
            "journal_seq": self.journal.seq,
            "rounds": self.rounds,
            "snapshot_every": self.snapshot_every,
            "delivered": sorted(self._delivered),
        })
        self.snapshots_taken += 1
        self._snap_seq = self.journal.seq
        self._snap_step = self._engine_step()
        if self.compact_journal:
            # the snapshot is durable (atomic rename happened): every
            # record at/below its journal_seq is dead weight now. The
            # lag gauge is already 0 (seq == _snap_seq) and the bytes
            # gauge shrinks to the surviving suffix. A crash between
            # the rename and this rewrite only leaves extra covered
            # records, which replay skips.
            self.journal.compact(self._snap_seq)

    # -- serving surface ----------------------------------------------
    def submit(self, token_ids, **kw) -> int:
        if kw.get("deadline_s") is not None:
            # wall-clock deadlines cannot replay deterministically (a
            # replayed round's wall time is not the live round's), so
            # a journaled server refuses them up front instead of
            # failing recovery with a RecoveryError later
            raise ValueError(
                "deadline_s is wall-clock and breaks deterministic "
                "journal replay; use deadline_steps on a "
                "RecoverableServer (bare engines still accept "
                "deadline_s)")
        self._flush_drains()
        toks = [int(t) for t in np.asarray(token_ids).reshape(-1)]
        self.journal.append("submit", {"tokens": toks,
                                       "kw": dict(kw)})
        return self.engine.submit(toks, **kw)

    def step(self) -> Dict[int, List[int]]:
        self._flush_drains()
        inj = self.injector
        col = self.engine.collector
        if inj is not None:
            inj.begin_round()           # live-round crash clock
        emitted = self.engine.step()
        if inj is not None:
            inj.crash_point("pre_journal")
        # the durability phases ride the engine timeline as spans —
        # journal-append and snapshot cost is visible next to the
        # model/prefill phases it competes with. try/finally, not a
        # bare bracket: injected crashes cannot fire between the
        # crash points, but a REAL append/snapshot failure (disk
        # full) could — and an unclosed span would skew the stack
        # for every later step on this collector
        if col is not None:
            col.span_begin("journal")
        try:
            self.journal.append("round", {
                "emitted": {int(r): [int(t) for t in toks]
                            for r, toks in emitted.items()}})
        finally:
            if col is not None:
                col.span_end()
        if inj is not None:
            inj.crash_point("post_journal")
        self.rounds += 1
        if self.snapshot_every and \
                self.rounds % self.snapshot_every == 0:
            if col is not None:
                col.span_begin("snapshot")
            try:
                self.save_snapshot()
            finally:
                if col is not None:
                    col.span_end(bytes=self.snapshot_bytes)
        return emitted

    def drain_outcomes(self) -> List[RequestOutcome]:
        """Terminal outcomes not yet delivered — the exactly-once edge
        of the recovery contract. The drain record reaches the journal
        at the start of the NEXT server call (before any crash point
        can fire), so an injected crash can never re-deliver, while a
        raw process kill between calls leaves the record unwritten and
        recovery re-delivers to the rebuilt caller — delivered exactly
        once from every observer that survives."""
        self._flush_drains()
        fresh = [oc for oc in self.engine.outcomes
                 if oc.rid not in self._delivered]
        self.engine.outcomes.clear()
        if fresh:
            self._pending_drain.extend(
                [oc.rid, oc.status] for oc in fresh)
            self._delivered.update(oc.rid for oc in fresh)
        return fresh

    def release(self, rid: int) -> None:
        self._flush_drains()
        self.journal.append("release", {"rid": int(rid)})
        self.engine.release(rid)

    def cancel(self, rid: int) -> bool:
        """Journaled early stop (best-of loser pruning, beam cuts,
        caller cancel): the record lands BEFORE the engine mutates,
        like a submit, so a crash after the append replays the
        cancellation and the replayed rounds serve the same surviving
        streams. Unknown/terminal rids return False live AND on
        replay (the engine's cancel is a no-op for them) — nothing to
        special-case."""
        self._flush_drains()
        self.journal.append("cancel", {"rid": int(rid)})
        return self.engine.cancel(rid)

    def export_slice(self, rid: int):
        """Migration export (inference/router.py): ``rid``'s finished
        prefix pages as a content-addressed kv_slice. A pure read —
        nothing to journal; the SOURCE of a migration keeps serving
        (or releasing) the request exactly as before."""
        return self.engine.export_slice(rid)

    def import_slice(self, slc: dict) -> int:
        """Adopt a migrated slice into this server's target pool. The
        slice is JOURNALED BEFORE the pool mutates, like a submit: a
        crash after the append replays the import, so the pages a
        replayed admission adopted are present again and the replayed
        rounds re-emit identically. (The slice also becomes durable
        here — a migration target that outlives its source still
        holds the pages in its own lineage.)"""
        self._flush_drains()
        self.journal.append("import_slice", {"slice": slc})
        return self.engine.import_slice(slc)

    def export_slices(self, rids) -> dict:
        """BATCHED migration export — the router's one-export-per-
        worker-per-tick call (N finished-prefill slots ride one round
        trip instead of N). {rid: slice-or-None}, each entry exactly
        ``export_slice(rid)``; a pure read like its singular twin."""
        return {int(r): self.engine.export_slice(int(r))
                for r in rids}

    def import_slices(self, slices) -> int:
        """BATCHED migration import: every slice journals and lands
        exactly as one ``import_slice`` — the journal record stream
        (and therefore crash replay) is IDENTICAL to N singleton
        imports, so batching changes round trips, never durability
        semantics. Returns total new blocks written."""
        return sum(self.import_slice(s) for s in slices)

    def set_tenant(self, tenant_id: str, **cfg):
        """Journaled tenant registration/reconfiguration: the record
        replays after a crash, so quotas/weights/floors changed
        between snapshots survive recovery (construction-time
        ``tenants=`` config rides snapshot 0 instead)."""
        self._flush_drains()
        self.journal.append("set_tenant", {"tenant_id": str(tenant_id),
                                           "cfg": dict(cfg)})
        return self.engine.set_tenant(tenant_id, **cfg)

    def tenant_stats(self):
        return self.engine.tenant_stats

    def tenant_report(self):
        return self.engine.tenant_report()

    def tokens(self, rid: int) -> List[int]:
        return self.engine.tokens(rid)

    def generated(self, rid: int) -> List[int]:
        return self.engine.generated(rid)

    def check_invariants(self) -> bool:
        return self.engine.check_invariants()

    def close(self) -> None:
        """Clean shutdown: flush pending drain records and close the
        journal fd. IDEMPOTENT — a second close is a no-op (the flush
        ran once, the fd was released once), so teardown paths that
        cannot know whether the server was already retired (a router's
        worker harness, test fixtures) may call it unconditionally.
        An incarnation abandoned after an ``EngineCrash`` does not
        need this — its handle is released when the object is
        collected — but a host that cycles through many servers in
        one process should close each one it retires."""
        if self._closed:
            return
        self._closed = True
        self._flush_drains()
        self.journal.close()

    # -- recovery -----------------------------------------------------
    @classmethod
    def recover(cls, target, draft=None, *, journal_path: str,
                snapshot_path: str, injector=None, collector=None,
                monitor=None, ledger=None, sync: bool = False,
                compact_journal: bool = True,
                num_blocks: Optional[int] = None) -> "RecoverableServer":
        """Rebuild a server after a crash: restore the last snapshot,
        then deterministically replay the journal suffix. Crash points
        are disarmed for the whole replay (the recorded rounds already
        happened; re-dying inside them would loop forever) while fault
        schedules stay live on the restored step clock, so a replayed
        step re-injects exactly the faults the live step saw. Each
        replayed round's emissions are checked against the journal
        record — divergence is a hard ``RecoveryError``. ``num_blocks``
        rehomes the pool during recovery (restore-into-a-different-
        pool); it only composes with ``k=0`` engines, whose draft side
        is absent.

        ``collector`` (TraceCollector) is wired onto the restored
        engine and flipped to REPLAY mode for the journal replay, the
        exact mirror of the injector's ``arm(False)``: replayed rounds'
        timeline spans record flagged ``replay: True`` and request
        records the dead incarnation already observed stay frozen —
        tracing a recovery neither diverges the replay nor
        double-counts a span or a latency. Snapshots carry no
        collector state (telemetry is observational; its wall-clock
        stamps must never enter engine-behavioral state).

        ``monitor`` (HealthMonitor) rides the same bracket: monitor
        state is DERIVED, never snapshotted — a fresh monitor rebuilds
        its series by resampling the replayed steps (alerts re-derived
        there are flagged ``replayed`` and kept out of the live
        counts), while a monitor that lived through the crash keeps
        its live samples frozen and nothing double-counts."""
        snap = load_snapshot(snapshot_path)
        if snap.get("kind") != "recoverable_server":
            raise SnapshotVersionError(
                f"{snapshot_path!r} holds a {snap.get('kind')!r} "
                f"snapshot, not a recoverable_server one")
        eng_snap = snap["engine"]
        if num_blocks is not None:
            eng = SpeculativeEngine.restore(
                target, draft, _resize_engine_snap(eng_snap,
                                                   num_blocks),
                injector=injector, collector=collector,
                monitor=monitor, ledger=ledger)
        else:
            eng = SpeculativeEngine.restore(target, draft, eng_snap,
                                            injector=injector,
                                            collector=collector,
                                            monitor=monitor,
                                            ledger=ledger)
        srv = cls(eng, journal_path=journal_path,
                  snapshot_path=snapshot_path, sync=sync,
                  compact_journal=compact_journal,
                  snapshot_every=snap["snapshot_every"], _fresh=False)
        # scan READ-ONLY first: the lineage check must reject a
        # foreign journal before RequestJournal's open truncates its
        # (possibly live) torn tail
        records, valid = _scan_journal(journal_path)
        last_seq = records[-1][0] if records else 0
        if last_seq < snap["journal_seq"]:
            # the snapshot was taken AFTER journal seq N; a journal
            # ending short of N is not this snapshot's journal (wrong
            # path, lost file, stale backup). Proceeding would hand
            # out seqs <= N that the NEXT recovery silently skips —
            # every post-recovery request would vanish
            raise RecoveryError(
                f"journal {journal_path!r} ends at seq {last_seq} "
                f"but the snapshot covers seq {snap['journal_seq']} — "
                f"the journal does not belong to this snapshot "
                f"lineage")
        journal = RequestJournal(journal_path, fresh=False, sync=sync,
                                 _scanned=(records, valid))
        srv.journal = journal
        journal.startup_records = []        # `records` is held here
        srv.rounds = snap["rounds"]
        srv._delivered = set(snap["delivered"])
        # the durability gauges resume from the RECOVERED lineage: lag
        # counts from the snapshot being restored, age from the
        # restored step clock — exactly what a live server that just
        # snapshotted would report
        srv._snap_seq = snap["journal_seq"]
        srv._snap_step = srv._engine_step()
        if injector is not None:
            injector.arm(False)
        if collector is not None:
            collector.set_replay(True)
        if monitor is not None:
            monitor.set_replay(True)
        if ledger is not None:
            # same bracket as the collector/monitor: records the dead
            # incarnation observed live freeze; replay-born records
            # (and replayed steps a fresh ledger never saw) accumulate
            ledger.set_replay(True)
        ok = False
        try:
            for seq, kind, payload in records:
                if kind == "outcomes":
                    # delivered-ness is global, not suffix-local: an
                    # outcome drained after the snapshot must not
                    # re-deliver either
                    srv._delivered.update(
                        rid for rid, _ in payload["rids"])
                if seq <= snap["journal_seq"]:
                    continue
                if kind == "submit":
                    try:
                        eng.submit(payload["tokens"], **payload["kw"])
                    except (ValueError, TypeError, KeyError):
                        # the live call raised this SAME error (all
                        # submit validation fires before any engine
                        # mutation, deterministically), the caller saw
                        # it, and the engine was left untouched — so
                        # the record is a no-op on replay too. A good
                        # submit wrongly skipped here cannot slip
                        # through: the next round record's emission
                        # check would diverge.
                        pass
                elif kind == "round":
                    got = {int(r): [int(t) for t in toks]
                           for r, toks in eng.step().items()}
                    if got != payload["emitted"]:
                        raise RecoveryError(
                            f"replay of journal record {seq} "
                            f"diverged: engine emitted {got}, journal "
                            f"recorded {payload['emitted']}")
                    srv.rounds += 1
                    srv.replayed_rounds += 1
                    srv.replayed_tokens += sum(
                        len(t) for t in got.values())
                elif kind == "release":
                    try:
                        eng.release(payload["rid"])
                    except KeyError:
                        # unknown rid: raised live before any
                        # mutation, same determinism argument as the
                        # submit case above
                        pass
                elif kind == "cancel":
                    # deterministic bool return, never raises: an
                    # unknown/terminal rid was a no-op live and is a
                    # no-op here
                    eng.cancel(payload["rid"])
                elif kind == "set_tenant":
                    try:
                        eng.set_tenant(payload["tenant_id"],
                                       **payload["cfg"])
                    except ValueError:
                        # refused live (quota below charge, floors
                        # over pool) before any mutation: no-op on
                        # replay too
                        pass
                elif kind == "import_slice":
                    # re-adopt the migrated pages the live call
                    # imported: replayed admissions then adopt the
                    # same prefix the live ones did. A ValueError
                    # (geometry mismatch) was raised live before any
                    # mutation — same no-op argument as submit.
                    try:
                        eng.import_slice(payload["slice"])
                    except ValueError:
                        pass
                elif kind == "compact":
                    # a compaction marker reuses the covered seq, so
                    # the seq-gate above already skips it; belt and
                    # braces for a marker that somehow outran its
                    # snapshot
                    pass
            ok = True
        finally:
            if injector is not None:
                injector.arm(True)
            if collector is not None:
                collector.set_replay(False)
            if monitor is not None:
                monitor.set_replay(False)
            if ledger is not None:
                ledger.set_replay(False)
            if not ok:
                # a failed replay (RecoveryError divergence) abandons
                # this half-built server: release its journal append
                # handle so the caller can retry recovery — or point a
                # doctor at the files — without a leaked fd holding
                # the journal open
                journal.close()
        # outcomes regenerated by the replay that were already drained
        # pre-crash: drop them here, exactly-once stands
        eng.outcomes[:] = [oc for oc in eng.outcomes
                           if oc.rid not in srv._delivered]
        eng.check_invariants()
        return srv


def _resize_engine_snap(spec_snap: dict, num_blocks: int) -> dict:
    """Clone a SpeculativeEngine snapshot with the TARGET pool resized
    (restore-into-a-different-pool): the engine config's num_blocks is
    rewritten so the rebuilt engine owns the new budget, and the cache
    snapshot rehoming happens inside PagedKVCache.restore."""
    import copy
    out = copy.copy(spec_snap)
    out["engine"] = copy.copy(spec_snap["engine"])
    out["engine"]["config"] = dict(spec_snap["engine"]["config"],
                                   num_blocks=int(num_blocks))
    return out
