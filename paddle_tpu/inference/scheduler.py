"""Paged serving engine: block-budget admission, preemption by block
eviction, continuous slot refill.

Sits where ContinuousBatchingEngine sits (same model contract:
``model(x, caches=..., time_step=...)`` with per-row int32 positions),
but the cache is a PagedKVCache — sequences reserve pages as they
grow instead of a dense max_len row, so the concurrency limit is the
BLOCK BUDGET, not slots*max_len. Scheduling policy (vLLM-style):

  * admission: a queued request is admitted only when a slot is free
    AND the allocator can cover its prompt's pages plus a watermark;
    prefill runs batch-1 against a persistent dense scratch cache and
    is scattered into freshly allocated pages.
  * growth: before each fused step, every active row crossing a block
    boundary allocates its next page (allocate-on-write).
  * preemption: when the pool is exhausted, the YOUNGEST active
    request is evicted — all its pages are freed at once and the
    request goes back to the FRONT of the queue for re-prefill from
    its recorded history (prompt + every decode input), so a later
    re-admission reproduces its cache exactly.
  * refill: releases/preemptions re-run admission, so the batch stays
    full without stopping in-flight rows.

Events are surfaced in ``admitted`` / ``finished`` / ``preempted``
lists the caller drains between steps (prefill outputs ride along so
the caller can seed the next input row).
"""
from __future__ import annotations

from collections import deque
from typing import Deque, List, Optional, Tuple

import numpy as np

from ..framework.autograd import no_grad
from ..framework.tensor import Tensor
from .paged_cache import BlockOOM, PagedKVCache

__all__ = ["PagedRequest", "PagedServingEngine"]


class PagedRequest:
    """One sequence. ``history`` is every embedding row the model has
    consumed for it (prompt rows + each decode-step input row): exactly
    what a re-prefill needs to rebuild the evicted cache."""

    def __init__(self, rid: int, history: np.ndarray):
        self.rid = rid
        self.history = [np.asarray(r, np.float32) for r in history]
        self.slot: Optional[int] = None
        self.admit_seq = -1
        self.preemptions = 0

    def __len__(self):
        return len(self.history)


class PagedServingEngine:
    def __init__(self, model, max_batch: int, block_size: int,
                 num_blocks: int, max_blocks_per_seq: Optional[int] = None,
                 dtype: str = "float32", watermark_blocks: int = 0):
        self.model = model
        self.max_batch = int(max_batch)
        self.dtype = dtype
        self.watermark_blocks = int(watermark_blocks)
        self.cache = PagedKVCache.for_model(
            model, block_size, num_blocks, max_seqs=max_batch,
            max_blocks_per_seq=max_blocks_per_seq, dtype=dtype)
        self.max_len = self.cache.capacity_per_seq
        self.lens = np.zeros(self.max_batch, np.int32)
        self.active = np.zeros(self.max_batch, bool)
        self._requests: List[Optional[PagedRequest]] = \
            [None] * self.max_batch
        self.queue: Deque[PagedRequest] = deque()
        # decode inputs not yet attributed to request histories:
        # (x, active-mask) per step, materialized to host lazily so the
        # hot decode loop never pays a device->host sync for the
        # (rare) preemption path
        self._pending_history: List[Tuple[Tensor, np.ndarray]] = []
        self._scratch = None          # persistent single-row prefill cache
        self._next_rid = 0
        self._next_admit_seq = 0
        # event queues the caller drains
        self.admitted: List[Tuple[int, int, Tensor]] = []
        self.finished: List[Tuple[int, int, int]] = []
        self.preempted: List[int] = []

    # -- introspection ------------------------------------------------
    @property
    def num_active(self) -> int:
        return int(self.active.sum())

    @property
    def free_slots(self) -> int:
        return int((~self.active).sum())

    @property
    def free_blocks(self) -> int:
        return self.cache.allocator.num_free

    # -- admission ----------------------------------------------------
    def submit(self, prompt) -> int:
        """Queue a prompt ([T, d_model] embeddings) and try to admit.
        Returns the request id; if admission succeeded an
        ``(rid, slot, last_hidden)`` event is in ``admitted``."""
        arr = np.asarray(prompt.numpy() if hasattr(prompt, "numpy")
                         else prompt, np.float32)
        if arr.shape[0] == 0:
            raise ValueError("empty prompt")
        if arr.shape[0] > self.max_len:
            raise ValueError(
                f"prompt length {arr.shape[0]} > per-seq page capacity "
                f"{self.max_len}")
        req = PagedRequest(self._next_rid, arr)
        self._next_rid += 1
        self.queue.append(req)
        self._try_admit()
        return req.rid

    def _try_admit(self) -> None:
        """Admit from the queue head while a slot is free and the
        block budget covers the prompt plus the watermark."""
        while self.queue and self.free_slots > 0:
            req = self.queue[0]
            # cover the prompt AND the first decode token's page —
            # admitting with zero headroom would re-preempt a request
            # sitting on a block boundary every step (prefill/evict
            # livelock)
            need = self.cache.blocks_needed(
                min(len(req) + 1, self.max_len))
            if need + self.watermark_blocks > self.free_blocks:
                return  # head-of-line blocks; keep FIFO fairness
            self.queue.popleft()
            self._prefill(req)

    def _prefill(self, req: PagedRequest) -> None:
        import paddle_tpu as paddle
        slot = int(np.flatnonzero(~self.active)[0])
        T = len(req)
        if self._scratch is None:
            self._scratch = self.model.gen_cache(1, self.max_len,
                                                 dtype=self.dtype)
        x = paddle.to_tensor(np.stack(req.history)[None]
                             .astype(np.float32))
        # serving never backprops: without no_grad the tape would pin
        # every superseded scratch/pool version across the loop
        with no_grad():
            out, row_caches = self.model(x, caches=self._scratch,
                                         time_step=0)
        self._scratch = row_caches  # persistent: reused next admission
        self.cache.ensure(slot, T)
        self.cache.write_prefill(slot, row_caches, T)
        self.lens[slot] = T
        self.active[slot] = True
        self._requests[slot] = req
        req.slot = slot
        req.admit_seq = self._next_admit_seq
        self._next_admit_seq += 1
        self.admitted.append((req.rid, slot, out[:, -1]))

    # -- release / preemption -----------------------------------------
    def release(self, slot: int) -> None:
        """Caller-side finish (e.g. EOS): free the pages, refill."""
        self._drop(slot)
        self._try_admit()

    def _flush_history(self) -> None:
        """Attribute buffered decode inputs to their requests'
        histories. Must run before any slot->request mapping change
        (drop/preempt), which is the only time histories are read."""
        if not self._pending_history:
            return
        pending, self._pending_history = self._pending_history, []
        for xt, mask in pending:
            xv = np.asarray(xt.numpy(), np.float32)
            for slot in np.flatnonzero(mask):
                req = self._requests[int(slot)]
                if req is not None:
                    req.history.append(xv[int(slot), 0].copy())

    def _drop(self, slot: int) -> None:
        self._flush_history()
        self.cache.free_seq(slot)
        self.active[slot] = False
        self.lens[slot] = 0
        self._requests[slot] = None

    def preempt(self, slot: int) -> None:
        """Evict a running request: free ALL its pages and requeue it
        at the front for re-prefill from its history."""
        req = self._requests[slot]
        if req is None:
            raise ValueError(f"slot {slot} not active")
        self._drop(slot)
        req.slot = None
        req.preemptions += 1
        self.queue.appendleft(req)
        self.preempted.append(req.rid)

    def _preempt_youngest(self) -> int:
        cands = [int(s) for s in np.flatnonzero(self.active)]
        victim = max(cands, key=lambda s: self._requests[s].admit_seq)
        self.preempt(victim)
        return victim

    # -- decode -------------------------------------------------------
    def step(self, x: Tensor):
        """One fused decode step for every active slot. x: [max_batch,
        1, d_model] next-token embeddings (inactive rows: any values —
        they scatter into the trash block). Slots at page capacity are
        auto-released first (reported in ``finished``) so one full
        sequence never stalls the batch; rows crossing a block boundary
        allocate their next page, preempting the youngest request if
        the pool is dry. Returns hidden [max_batch, 1, d_model] (only
        rows active during this step are meaningful), or None if every
        slot finished before the step could run."""
        if self.num_active == 0:
            raise RuntimeError("step() with no active slots")
        # 1. capacity-finished slots: report + release, keep the rest
        for slot in np.flatnonzero(self.active & (self.lens >=
                                                  self.max_len)):
            req = self._requests[int(slot)]
            self.finished.append((req.rid, int(slot),
                                  int(self.lens[slot])))
            self._drop(int(slot))
        if self.num_active == 0:
            self._try_admit()
            return None
        # 2. grow pages (allocate-on-write), preempting on OOM.
        #    Oldest first: under pressure the young yield to the old.
        order = sorted(np.flatnonzero(self.active),
                       key=lambda s: self._requests[s].admit_seq)
        for slot in order:
            slot = int(slot)
            while self.active[slot]:
                try:
                    self.cache.ensure(slot, int(self.lens[slot]) + 1)
                    break
                except BlockOOM:
                    # victim = youngest active request — possibly this
                    # row itself (then the while condition ends its
                    # growth attempt and it re-queues for re-prefill)
                    if self.num_active == 1:
                        raise RuntimeError(
                            "pool too small: one sequence cannot grow "
                            "even with every other request evicted")
                    self._preempt_youngest()
        # 3. record the inputs being consumed (re-prefill history) —
        #    a Tensor ref + mask snapshot only; the device->host read
        #    is deferred to _flush_history (next drop/preempt, or the
        #    periodic bound below so long-lived batches don't pin an
        #    unbounded window of input buffers)
        if len(self._pending_history) >= 32:
            self._flush_history()
        self._pending_history.append((x, self.active.copy()))
        # 4. fused ragged step over the paged views
        t = Tensor(np.asarray(self.lens, np.int32))
        with no_grad():
            out, _ = self.model(x, caches=self.cache.views, time_step=t)
        self.lens[self.active] += 1
        # 5. continuous refill
        self._try_admit()
        return out
