"""Paged serving engine: block-budget admission, preemption by block
eviction, continuous slot refill.

Sits where ContinuousBatchingEngine sits (same model contract:
``model(x, caches=..., time_step=...)`` with per-row int32 positions),
but the cache is a PagedKVCache — sequences reserve pages as they
grow instead of a dense max_len row, so the concurrency limit is the
BLOCK BUDGET, not slots*max_len. Scheduling policy (vLLM-style):

  * admission: a queued request is admitted only when a slot is free
    AND the allocator can cover its prompt's pages plus a watermark;
    prefill STREAMS the prompt straight into the slot's pages in
    fixed-size causal chunks (``chunked_prefill`` below — batch-1
    chunk calls through PagedKVCache.prefill_views), so there is no
    dense ``[2, 1, H, max_len, D]`` scratch allocation and no
    pages<->scratch scatter/gather pass: peak KV memory IS the pool.
  * growth: before each fused step, every active row crossing a block
    boundary allocates its next page (allocate-on-write).
  * preemption: when the pool is exhausted, the YOUNGEST active
    request is evicted — all its pages are freed at once and the
    request goes back to the FRONT of the queue for re-prefill from
    its recorded history (prompt + every decode input), so a later
    re-admission reproduces its cache exactly.
  * refill: releases/preemptions re-run admission, so the batch stays
    full without stopping in-flight rows.
  * prefix caching (``prefix_cache=True``): admission matches the
    prompt's chained block hashes against previously computed pages
    (paged_cache.match_prefix), ``ref``s the hits into the new slot's
    table, and prefills ONLY the uncached suffix — cached prefix
    tokens cost zero prefill FLOPs and zero new blocks. The suffix
    chunk simply ATTENDS over the adopted pages through the chunk
    protocol (no pages->scratch gather). Released pages park
    cached-free (resurrectable) until LRU reclaim; hit accounting
    rides in ``prefix_stats``.
  * mixed prefill/decode steps (``prefill_token_budget=N``,
    Sarathi-style): admission only grants the slot; each ``step``
    first spends up to N prompt tokens advancing pending prefills
    chunk by chunk (oldest first, growing pages under the same
    preemption rules — no max_len block reservation up front), then
    runs the fused decode call for the active rows, so one long
    prompt never stalls the running batch. The admitted event fires
    when the last chunk lands. Without a budget (the default),
    admission runs every chunk synchronously — same external
    behavior as the old scratch path, still scratchless inside.
    Chunk accounting rides in ``prefill_stats`` (PrefillStats).

  * quantized serving (``dtype="int8"``): the pool stores int8 K/V
    pages with per-row scales (paged_cache.py "QUANTIZED SERVING") —
    ~1.88x the blocks at equal HBM, so the block-budget admission
    above admits ~1.88x the concurrent requests. Scheduling is
    completely dtype-blind: admission, growth, preemption, prefix
    adoption, quotas and snapshots all operate on block counts and
    quantized payloads unchanged. Off by default (bit-identity
    suites run on fp pools).

  * failure isolation (inference/resilience.py): requests end in a
    terminal ``RequestOutcome`` — FINISHED, or FAILED_OOM /
    FAILED_NUMERIC / FAILED_DEADLINE / REJECTED_ADMISSION — surfaced
    in ``outcomes``;
    a BlockOOM that survives preemption sheds ONE request instead of
    raising, ``max_preemptions`` bounds the re-prefill retry budget,
    per-request deadlines (steps or wall clock) are enforced each
    step, and an optional numeric guard fails a slot whose hidden
    goes non-finite (its pages are quarantined). A ``FaultInjector``
    can drive all of it deterministically; ``check_invariants``
    audits the pool bookkeeping. Counters ride in
    ``resilience_stats`` (ResilienceStats).

  * multi-tenant isolation (``tenants=`` / ``set_tenant`` /
    ``submit(..., tenant_id=...)``): every request belongs to a
    tenant (the implicit unlimited ``default`` tenant when no id is
    given — bit-identical to the single-tenant engine). Tenants carry
    a block QUOTA (hard cap on the blocks their slots' tables may
    reference — one charge per reference, so a tenant's bill is a
    pure function of its own tables; see PagedKVCache.__init__), a
    RESERVED floor (pool headroom other tenants may never dip into
    while this tenant is below it), and a WEIGHT for admission.
    Admission is weighted fair queuing over one physical queue:
    the tenant with the lowest virtual time admits next (vtime
    advances by 1/weight per admission; start-time bumped to the
    virtual clock on enqueue-from-idle), age-fair within a tenant and
    still preempted-ahead-of-new. A tenant whose head request is
    blocked by its OWN quota (or by others' reserved floors) is
    skipped — its cap is its problem, never its neighbors' — while
    true pool pressure stops the pass head-of-line as before.
    Preemption and shedding are tenant-aware: a quota or floor hit
    evicts the over-budget tenant's OWN youngest (or sheds its
    grower), and a physical pool OOM takes victims from the grower's
    own tenant — a neighbor is only ever preempted when the grower
    is still under its reserved floor and that neighbor is borrowing
    above its own. Health-based admission control REJECTS provably
    unservable requests at submit (quota- or pool-impossible prompt,
    deadline below the prefill lower bound) with a terminal
    ``REJECTED_ADMISSION`` outcome — never an exception. Per-tenant
    accounting (sheds, rejections, quota hits, blocks held, tokens
    served) rides in ``tenant_stats`` (TenantStats).

  * telemetry (``collector=`` — inference/telemetry.py): an opt-in
    ``TraceCollector`` records every request's lifecycle (submitted /
    admitted / prefill chunks / first token / preemptions / rollbacks
    / terminal outcome -> TTFT, TPOT, queue-wait, preemption-stall
    percentiles per tenant) and brackets each step's phases
    (admission / prefill / model / bookkeeping) with per-step pool /
    queue / per-tenant gauges, exportable as Chrome-trace JSON. With
    no collector (default) every hook site is dark — zero clock
    reads, zero allocations, bit-identical streams. The always-on
    ``registry`` (MetricsRegistry) unifies the five stats siblings,
    ``tenant_report`` and the pool/queue gauges behind one flat
    ``as_dict()`` with interval deltas.

  * health monitoring (``monitor=`` — inference/monitor.py): an
    opt-in ``HealthMonitor`` sampled at the end of every completed
    step — windowed time-series over the registry (tokens/step, shed
    rate, pool tiers, queue depth, per-tenant charge, spec
    acceptance), per-tenant SLO tracking off the collector's latency
    histograms, and deterministic threshold-crossing ``Alert`` events
    (pool-pressure-high, shed-spike, queue-growth, ...) keyed to the
    step clock. Same contracts as the collector: zero overhead off,
    passive, derived-not-snapshotted.

Events are surfaced in ``admitted`` / ``finished`` / ``preempted`` /
``outcomes`` lists the caller drains between steps (prefill outputs
ride along so the caller can seed the next input row).
"""
from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from ..framework.autograd import no_grad
from ..framework.tensor import Tensor
from .paged_cache import BlockOOM, PagedKVCache, chain_block_hashes
from .resilience import RequestOutcome
from .serving import (ParallelStats, PrefillStats, PrefixCacheStats,
                      ResilienceStats, TenantStats)
from .telemetry import MetricsRegistry

__all__ = ["PagedRequest", "PagedServingEngine", "Tenant",
           "chunked_prefill", "DEFAULT_TENANT",
           "MIN_PREFILL_SUFFIX_ROWS"]

# the implicit tenant every request without a tenant_id belongs to:
# unlimited quota, no reserved floor, weight 1 — a single-tenant
# engine therefore schedules bit-identically to the pre-tenant one
# (weighted fair queuing over one tenant IS FIFO, and every victim
# policy degenerates to "youngest first")
DEFAULT_TENANT = "default"


class Tenant:
    """One tenant's isolation contract + accounting.

      quota_blocks     hard cap on pool blocks charged to the tenant
                       (one charge per block-table reference its slots
                       hold); None = unlimited. Growth into the cap
                       evicts/sheds WITHIN the tenant, admission past
                       it skips the tenant — neighbors never pay.
      reserved_blocks  guaranteed floor: while this tenant's charge is
                       below it, other tenants' admissions and growth
                       may not dip the free pool below the unmet
                       remainder, and a pool OOM suffered while below
                       it may evict an over-floor borrower.
      weight           weighted-fair-queuing admission share: a
                       tenant's virtual time advances by 1/weight per
                       admission, so a weight-2 tenant admits twice as
                       often under contention.
      vtime            the WFQ virtual-time tag (scheduler state —
                       snapshots round-trip it).
      fifo             this tenant's FIFO SUB-QUEUE (the physical
                       queue is sharded per tenant, so WFQ head
                       selection reads one deque head per tenant —
                       O(tenants) — instead of scanning one global
                       queue per admission, O(queue)). Within a
                       tenant the order is the same global contract
                       as before: preempted requests (by rid) ahead
                       of never-admitted ones (by enqueue order).
                       ``PagedServingEngine.queue`` materializes the
                       merged global view for snapshots/diagnostics.
      queued           live count of this tenant's queued requests
                       (== len(fifo); gauge maintained at every queue
                       mutation and audited by check_invariants;
                       derived state, so restore recomputes it from
                       the queue instead of round-tripping it).
      stats            TenantStats (serving.py).
    """

    __slots__ = ("tid", "quota_blocks", "reserved_blocks", "weight",
                 "vtime", "fifo", "queued", "stats")

    def __init__(self, tid: str, quota_blocks: Optional[int] = None,
                 reserved_blocks: int = 0, weight: float = 1.0):
        self.tid = str(tid)
        if weight <= 0:
            raise ValueError(f"tenant {tid!r}: weight must be > 0")
        if reserved_blocks < 0:
            raise ValueError(
                f"tenant {tid!r}: reserved_blocks must be >= 0")
        if quota_blocks is not None and quota_blocks < reserved_blocks:
            raise ValueError(
                f"tenant {tid!r}: quota_blocks ({quota_blocks}) < "
                f"reserved_blocks ({reserved_blocks})")
        self.quota_blocks = (None if quota_blocks is None
                             else int(quota_blocks))
        self.reserved_blocks = int(reserved_blocks)
        self.weight = float(weight)
        self.vtime = 0.0
        self.fifo: Deque[PagedRequest] = deque()
        self.queued = 0
        self.stats = TenantStats()

# A partial (suffix-only) prefill must recompute at least this many
# trailing prompt rows, even when the prefix cache covers more: a
# 1-row attention lowers to a GEMV whose accumulation order differs
# from the same row computed inside a multi-row prefill, so a 1-row
# suffix would break bit-identity with the cold path (and a fully
# cached prompt still needs its last hidden for the admission event).
# The same floor governs CHUNK boundaries: every prefill chunk keeps
# >= MIN_PREFILL_SUFFIX_ROWS rows (chunking is bit-transparent for
# multi-row calls — per-row sdpa results are invariant to both chunk
# length and masked key extent — but a 1-row tail chunk would take
# the GEMV lowering).
# See tests/test_prefix_cache.py::test_one_row_suffix_regression.
MIN_PREFILL_SUFFIX_ROWS = 2


def _chunk_len(total: int, pos: int, chunk_tokens: int,
               budget: Optional[int] = None) -> int:
    """Next chunk length for a prefill at ``pos`` of ``total`` rows:
    ``chunk_tokens`` capped by the remaining prompt (and the remaining
    step budget, floored at the 2-row minimum), then adjusted so the
    REMAINING tail is never a single row — a 1-row chunk would break
    bit-identity (MIN_PREFILL_SUFFIX_ROWS)."""
    c = min(chunk_tokens, total - pos)
    if budget is not None:
        c = min(c, max(MIN_PREFILL_SUFFIX_ROWS, budget))
    if total - (pos + c) == 1:
        c = c - 1 if c > MIN_PREFILL_SUFFIX_ROWS else c + 1
    return c


def chunked_prefill(model, cache: PagedKVCache, slot: int, rows,
                    *, pos: int = 0, target: Optional[int] = None,
                    chunk_tokens: int = 64, start_block: int = 0,
                    write_start: int = 0, stats: Optional[PrefillStats]
                    = None, on_chunk=None):
    """Stream ``rows[pos:target]`` ([T, d_model] ndarray) into
    ``slot``'s pages in causal chunks: each chunk is one batch-1 model
    call through ``cache.prefill_views`` — K/V append straight into
    the pages, attention runs over them at ``time_step = chunk
    start`` with full-extent masking, so the resulting pages AND the
    final hidden are bit-identical to a dense scratch prefill of the
    whole prompt (asserted in tests/test_paged_cache.py). The ONE
    prefill implementation shared by PagedServingEngine (admission +
    re-prefill + mixed steps) and SpeculativeEngine (draft prefill).

    ``start_block``/``write_start``: adopted prefix-cache pages — the
    chunks attend over them but never rewrite (or COW-split) them.
    The caller must ``ensure`` page coverage only when running under
    its own OOM policy; this helper ensures per chunk and lets
    BlockOOM propagate. ``on_chunk(new_pos)`` fires after every chunk
    lands — the engine uses it to register completed prefix blocks as
    the prompt streams, so a preemption (or crash restore) mid-prefill
    resumes warm instead of recomputing finished pages. Returns
    ``(new_pos, last_hidden)`` — last_hidden is the final chunk's
    trailing row ([1, d_model]), or None when no chunk ran."""
    import paddle_tpu as paddle
    T = rows.shape[0] if target is None else int(target)
    out = None
    views = cache.prefill_views(slot, write_start=write_start)
    while pos < T:
        c = _chunk_len(T, pos, chunk_tokens)
        cache.ensure(slot, pos + c, start_block=start_block,
                     write_from=pos)
        x = paddle.to_tensor(
            np.ascontiguousarray(rows[pos:pos + c], np.float32)[None])
        # serving never backprops (no_grad keeps the tape from pinning
        # pool versions); time_step as a TENSOR scalar routes to the
        # full-extent masked attention — the length-independence that
        # makes chunking and prefix adoption bit-transparent
        with no_grad():
            out, _ = model(x, caches=views,
                           time_step=Tensor(np.int32(pos)))
        pos += c
        if stats is not None:
            stats.chunks += 1
            stats.prefill_tokens += c
            stats.peak_blocks = max(stats.peak_blocks,
                                    cache.blocks_in_use)
        if on_chunk is not None:
            on_chunk(pos)
    return pos, (out[:, -1] if out is not None else None)


class PagedRequest:
    """One sequence. ``history`` is every embedding row the model has
    consumed for it (prompt rows + each decode-step input row): exactly
    what a re-prefill needs to rebuild the evicted cache. It is ONE
    growable [T, d_model] ndarray (amortized append), not a list of
    rows — re-admission previously paid an O(T) np.stack on every
    prefill and a per-row list append on every history flush."""

    def __init__(self, rid: int, history: np.ndarray):
        self.rid = rid
        arr = np.array(history, np.float32, copy=True)
        if arr.ndim != 2:
            raise ValueError("history must be [T, d_model] rows")
        self._hist = arr
        self._len = arr.shape[0]
        # chain hashes are append-only like the history: memoized and
        # extended in place, never recomputed across re-admissions
        self._hashes: List[bytes] = []
        self.slot: Optional[int] = None
        self.admit_seq = -1
        # global FIFO position among never-admitted requests (the
        # per-tenant sub-queues merge by it — see _queue_key)
        self.enqueue_seq = -1
        self.preemptions = 0
        # multi-tenant isolation: which tenant's quota/weight/floor
        # govern this request (set by submit; DEFAULT_TENANT when the
        # caller gave no tenant_id)
        self.tenant: str = DEFAULT_TENANT
        # resilience knobs (set by the engine at submit): re-prefill
        # retry budget and per-request deadlines — None = unbounded
        self.max_preemptions: Optional[int] = None
        self.deadline_steps: Optional[int] = None
        self.deadline_time: Optional[float] = None   # monotonic clock
        self.submit_step = 0
        # fork-shared parallel decoding (branch groups): ``gid`` is the
        # group id (the LEAD request's rid) for every member, ``branch``
        # the lane index within it. ``group_n`` > 1 marks a lead whose
        # branches have NOT forked yet (submit sets it; the fork clears
        # it, so a post-fork preemption re-prefills a normal request).
        self.gid: Optional[int] = None
        self.branch = 0
        self.group_n = 1

    @property
    def history(self) -> np.ndarray:
        """[T, d_model] view of every consumed row (no copy)."""
        return self._hist[:self._len]

    def append_history(self, row) -> None:
        if self._len == self._hist.shape[0]:
            grown = np.empty((max(8, 2 * self._hist.shape[0]),
                              self._hist.shape[1]), np.float32)
            grown[:self._len] = self._hist[:self._len]
            self._hist = grown
        self._hist[self._len] = row
        self._len += 1

    def block_hashes(self, block_size: int) -> List[bytes]:
        """Chained hashes of every FULL block of the history (the
        prompt-hash identity the prefix cache indexes by)."""
        n_full = self._len // block_size
        have = len(self._hashes)
        if have < n_full:
            self._hashes.extend(chain_block_hashes(
                self._hist[have * block_size:n_full * block_size],
                block_size,
                parent=self._hashes[-1] if self._hashes else b""))
        return self._hashes[:n_full]

    def truncate_history(self, length: int, block_size: int) -> None:
        """Roll the recorded history back to ``length`` rows
        (speculative rejection): rows past it were consumed
        speculatively and rejected, so a re-prefill must not replay
        them. Memoized chain hashes past the new last full block are
        dropped with them."""
        if length < 0 or length > self._len:
            raise ValueError(
                f"truncate to {length} outside [0, {self._len}]")
        self._len = length
        del self._hashes[length // block_size:]

    def __len__(self):
        return self._len


class _GroupTable:
    """Engine-side registry of fork-shared branch groups (parallel
    sampling: ``submit(..., n=4)``). One record per live group:

      n         branch count the group was admitted for
      rids      member rids in branch order (rids[0] == gid == the
                lead's rid; branch rids land here AT FORK TIME — they
                are minted from the engine's rid counter then, so a
                journal replay reproduces them exactly)
      live      member rids without a terminal outcome yet (the group
                outcome-aggregation unit: the group is done when this
                empties)
      reserved  slot indices held for the pending branches while the
                lead's prompt streams (token-budget mode only): marked
                ``prefilling`` with no request/prefill state so the
                admission pass cannot hand them out; emptied by the
                fork (or by a lead drop)
      forked    whether the COW fork has run

    The table is ENGINE-BEHAVIORAL state (admission gating, fork
    targets, outcome aggregation), so it snapshots/restores with the
    engine — tools/check_static.py's snapshot-completeness pass audits
    it like any other state holder."""

    def __init__(self):
        self.groups: Dict[int, dict] = {}
        self._by_rid: Dict[int, int] = {}

    def create(self, gid: int, n: int) -> dict:
        g = {"n": int(n), "rids": [gid], "live": [gid],
             "reserved": [], "forked": False}
        self.groups[gid] = g
        self._by_rid[gid] = gid
        return g

    def add_branch(self, gid: int, rid: int) -> None:
        g = self.groups[gid]
        g["rids"].append(rid)
        g["live"].append(rid)
        self._by_rid[rid] = gid

    def gid_of(self, rid: int) -> Optional[int]:
        return self._by_rid.get(rid)

    def group_of(self, rid: int) -> Optional[dict]:
        gid = self._by_rid.get(rid)
        return None if gid is None else self.groups.get(gid)

    def reserved_slots(self) -> set:
        return {s for g in self.groups.values() for s in g["reserved"]}

    def on_terminal(self, rid: int) -> Optional[dict]:
        """Mark a member terminal; drop the record once every member
        is. Returns the (now possibly dead) group record, or None for
        a non-member rid."""
        gid = self._by_rid.get(rid)
        if gid is None:
            return None
        g = self.groups[gid]
        if rid in g["live"]:
            g["live"].remove(rid)
        if not g["live"]:
            for r in g["rids"]:
                self._by_rid.pop(r, None)
            del self.groups[gid]
        return g

    def snapshot(self) -> dict:
        return {"groups": [dict(g, gid=gid, rids=list(g["rids"]),
                                live=list(g["live"]),
                                reserved=list(g["reserved"]))
                           for gid, g in self.groups.items()],
                "by_rid": dict(self._by_rid)}

    def restore(self, rec: dict) -> None:
        self.groups = {}
        for g in rec.get("groups", []):
            self.groups[int(g["gid"])] = {
                "n": int(g["n"]), "rids": list(g["rids"]),
                "live": list(g["live"]),
                "reserved": [int(s) for s in g["reserved"]],
                "forked": bool(g["forked"])}
        self._by_rid = {int(r): int(gid)
                        for r, gid in rec.get("by_rid", {}).items()}


class PagedServingEngine:
    def __init__(self, model, max_batch: int, block_size: int,
                 num_blocks: int, max_blocks_per_seq: Optional[int] = None,
                 dtype: str = "float32", watermark_blocks: int = 0,
                 prefix_cache: bool = False,
                 chunk_tokens: Optional[int] = None,
                 prefill_token_budget: Optional[int] = None,
                 injector=None, max_preemptions: Optional[int] = None,
                 numeric_guard: Optional[bool] = None,
                 tenants: Optional[Dict[str, dict]] = None,
                 collector=None, monitor=None, ledger=None,
                 ragged_step: bool = True,
                 tile_q: Optional[int] = None,
                 tile_kv: Optional[int] = None):
        self.model = model
        # ragged mixed step (token-budget mode): plan the step's
        # prefill chunks, then launch them PACKED with the decode rows
        # as one model call — ONE paged-attention dispatch per layer —
        # instead of one launch per chunk plus one for the decode.
        # Packing engages ON THE KERNEL PATH (TPU / forced kernels),
        # where dispatch count is the cost being collapsed; the CPU
        # jnp fallback keeps the per-phase calls because CPU
        # bit-identity is strict and XLA CPU matmul row results are
        # only row-count-invariant at small shapes (a packed
        # [R, d] projection can differ from the [B, 1, d] call by a
        # ulp at serving widths). ragged_step="force" packs on the
        # CPU fallback too (tests/benches of the packing machinery —
        # bit-identical at test dims, token-identical at bench dims);
        # False keeps the legacy per-chunk launches everywhere.
        # tile_q/tile_kv pass through to paged_attention_ragged
        # (kernel tuning knobs; None = the kernel's default table).
        self.ragged_step = ragged_step
        self.tile_q = tile_q
        self.tile_kv = tile_kv
        self._ragged_plan: Optional[List[dict]] = None
        self.max_batch = int(max_batch)
        self.dtype = dtype
        self.watermark_blocks = int(watermark_blocks)
        self.prefix_cache = bool(prefix_cache)
        self.prefix_stats = PrefixCacheStats()
        self.prefill_stats = PrefillStats()
        # multi-tenant isolation: registration order is the WFQ
        # tie-break, so the dict's insertion order is load-bearing
        # (snapshots preserve it). The implicit default tenant always
        # exists; ``tenants={"a": {"quota_blocks": 8, "weight": 2}}``
        # pre-registers more, set_tenant adds/updates at runtime, and
        # an unknown tenant_id at submit auto-registers with the
        # unlimited defaults.
        self.tenants: Dict[str, Tenant] = {
            DEFAULT_TENANT: Tenant(DEFAULT_TENANT)}
        self._vclock = 0.0
        # resilience layer (inference/resilience.py): per-request
        # terminal outcomes instead of engine crashes, bounded retry,
        # optional deterministic fault injection + numeric guard. The
        # guard (one [B]-bool device->host read per step) defaults ON
        # only when an injector is present; pass numeric_guard=True to
        # run it in production serving too.
        self.injector = injector
        self.max_preemptions = max_preemptions
        self.numeric_guard = (injector is not None
                              if numeric_guard is None
                              else bool(numeric_guard))
        self.resilience_stats = ResilienceStats()
        # fork-shared parallel decoding (branch groups): group/branch
        # counters next to the resilience siblings
        self.parallel_stats = ParallelStats()
        self.outcomes: List[RequestOutcome] = []
        self._step_count = 0
        self._has_deadlines = False
        # telemetry (inference/telemetry.py). collector: the opt-in
        # TraceCollector — per-request lifecycle + step-phase timeline
        # + Chrome-trace export; None (default) keeps every hook site
        # dark (zero clock reads, zero allocations — the FaultInjector
        # pattern). The collector is PASSIVE (never consulted for
        # control flow) and deliberately NOT part of snapshot():
        # wall-clock timestamps stay out of engine-behavioral state;
        # a restored engine gets the caller's collector wired fresh.
        self.collector = collector
        # ledger (inference/accounting.py): the opt-in CostLedger —
        # classifies every token-row of model work as goodput, waste
        # (per-cause: speculative rejection, re-prefill replay, failed
        # requests) or pending, prices it through the analytic
        # WorkModel, and integrates per-tenant block-step billing.
        # Same contracts as the collector: None (default) keeps every
        # hook site dark, the ledger is PASSIVE (counters only, never
        # consulted for control flow, never reads a clock) and never
        # part of snapshot() — ledger state is derived.
        self.ledger = ledger
        # monitor (inference/monitor.py): the opt-in HealthMonitor —
        # windowed time-series over the registry, per-tenant SLO
        # tracking, deterministic threshold alerting. Sampled at the
        # end of every COMPLETED step (_end_step_telemetry); None
        # (default) keeps the hook dark, and like the collector it is
        # PASSIVE (reads only) and never part of snapshot() — monitor
        # state is derived, rebuilt by resampling after a restore.
        self.monitor = monitor
        # registry: the always-on unified metric surface — the five
        # stats siblings, tenant_report and the pool/queue gauges
        # behind ONE as_dict() (flat keys, interval-deltable). Sources
        # are LIVE (read at snapshot time), so attaching here costs
        # the hot path nothing.
        self.registry = MetricsRegistry()
        self.registry.attach("prefix_cache", self.prefix_stats)
        self.registry.attach("prefill", self.prefill_stats)
        self.registry.attach("resilience", self.resilience_stats)
        self.registry.attach("parallel", self.parallel_stats)
        self.registry.attach("tenants", self.tenant_report)
        # tiers_only: the registry's pool namespace is the per-step /
        # per-sample scrape surface (router, HealthMonitor) and must
        # stay O(1) — the per-slot / per-tenant occupancy HISTOGRAMS
        # are an explicit-diagnosis surface (cache.pool_occupancy(),
        # BlockOOM.details, the oom_shed event), not a gauge
        self.registry.attach(
            "pool",
            lambda: dict(self.cache.pool_occupancy(tiers_only=True),
                         peak=self.cache.peak_blocks_used))
        self.registry.attach("queue", self._queue_gauges)
        # sharded cores export their dispatch instrumentation (jit
        # calls, retraces, psums per call) next to allreduce_count —
        # the monitor's recompile-storm alert surface
        if hasattr(model, "sharded_metrics"):
            self.registry.attach("sharded", model.sharded_metrics)
        # MoE cores export per-expert load / overflow / routing totals
        # (moe_serving.MoeServingCore.moe_metrics) — the expert-collapse
        # detector's sampling surface; dense models leave the namespace
        # absent and the detector dark
        if hasattr(model, "moe_metrics"):
            self.registry.attach("moe", model.moe_metrics)
        self.cache = PagedKVCache.for_model(
            model, block_size, num_blocks, max_seqs=max_batch,
            max_blocks_per_seq=max_blocks_per_seq, dtype=dtype,
            prefix_cache=prefix_cache)
        if injector is not None:
            self.cache.allocator.fault_hook = \
                lambda n: injector.on_alloc("target", n)
        self.max_len = self.cache.capacity_per_seq
        for tid, cfg in (tenants or {}).items():
            self.set_tenant(tid, **cfg)
        # prompt chunk size (chunked_prefill): a multiple of the block
        # size by default so most chunk boundaries land on page edges;
        # any value >= MIN_PREFILL_SUFFIX_ROWS is bit-transparent
        if chunk_tokens is None:
            chunk_tokens = 4 * self.cache.block_size
        if chunk_tokens < MIN_PREFILL_SUFFIX_ROWS:
            raise ValueError(
                f"chunk_tokens must be >= {MIN_PREFILL_SUFFIX_ROWS}")
        self.chunk_tokens = int(chunk_tokens)
        # Sarathi-style mixed steps: with a budget, each step() spends
        # ~this many prompt tokens advancing pending prefills before
        # the fused decode call (a chunk may run ONE token past the
        # cap rather than leave a 1-row tail — the GEMV bit-identity
        # floor); admission only grants a slot. None (default):
        # admission prefills synchronously.
        if prefill_token_budget is not None and \
                prefill_token_budget < MIN_PREFILL_SUFFIX_ROWS:
            raise ValueError(
                f"prefill_token_budget must be >= "
                f"{MIN_PREFILL_SUFFIX_ROWS}")
        self.prefill_token_budget = prefill_token_budget
        self.lens = np.zeros(self.max_batch, np.int32)
        self.active = np.zeros(self.max_batch, bool)
        # slots granted but still streaming their prompt (mixed-step
        # mode): they own pages but must not ride the decode call
        self.prefilling = np.zeros(self.max_batch, bool)
        self._prefills: Dict[int, dict] = {}
        self._requests: List[Optional[PagedRequest]] = \
            [None] * self.max_batch
        # the physical queue lives SHARDED in the tenants' FIFO
        # sub-queues (Tenant.fifo) so the WFQ admission pass touches
        # one deque head per tenant; ``queue`` (property below) merges
        # them back into the legacy global order for snapshots,
        # deadline scans and diagnostics. _queue_len is the O(1)
        # depth gauge the hot paths read.
        self._queue_len = 0
        self._next_enqueue_seq = 0
        # decode inputs not yet attributed to request histories:
        # (x, active-mask) per step, materialized to host lazily so the
        # hot decode loop never pays a device->host sync for the
        # (rare) preemption path
        self._pending_history: List[Tuple[Tensor, np.ndarray]] = []
        self._next_rid = 0
        self._next_admit_seq = 0
        # fork-shared parallel decoding: branch-group registry (the
        # group is the unit of admission, fork and outcome
        # aggregation; a forked branch is a NORMAL slot everywhere
        # else — growth, preemption, shed)
        self.groups = _GroupTable()
        # event queues the caller drains
        self.admitted: List[Tuple[int, int, Tensor]] = []
        self.finished: List[Tuple[int, int, int]] = []
        self.preempted: List[int] = []
        # the ledger binds before the monitor so the monitor's
        # baseline registry snapshot already carries the work.* keys
        if ledger is not None:
            ledger.bind(self.registry, model=model,
                        kv_token_bytes=self.cache.kv_bytes_per_token())
        # wire the monitor LAST (its baseline snapshot reads the live
        # registry sources, which need the engine fully built); the
        # rebase pins the interval-delta baseline at the current step
        # so the first sampled step computes a one-interval delta —
        # the same contract PagedServingEngine.restore re-establishes
        if monitor is not None:
            monitor.bind(self.registry, collector=collector)
            monitor.rebase(self._step_count)

    # -- introspection ------------------------------------------------
    @property
    def num_active(self) -> int:
        return int(self.active.sum())

    @property
    def num_prefilling(self) -> int:
        return int(self.prefilling.sum())

    @property
    def free_slots(self) -> int:
        return int((~self.active & ~self.prefilling).sum())

    @property
    def free_blocks(self) -> int:
        """Allocatable blocks: the true free list PLUS the cached-free
        second-chance tier (reclaimable on demand)."""
        return self.cache.allocator.num_free

    @property
    def prefix_hit_rate(self) -> float:
        return self.prefix_stats.hit_rate

    # -- tenants ------------------------------------------------------
    @property
    def tenant_stats(self) -> Dict[str, TenantStats]:
        """{tenant_id: TenantStats} — the noisy-neighbor attribution
        surface (blocks_held gauges refresh at every step top)."""
        return {tid: t.stats for tid, t in self.tenants.items()}

    def set_tenant(self, tenant_id: str, *,
                   quota_blocks: Optional[int] = None,
                   reserved_blocks: int = 0,
                   weight: float = 1.0) -> Tenant:
        """Register or reconfigure a tenant. Refused (ValueError) when
        the quota would fall below the tenant's CURRENT charge (the
        audit asserts charge <= quota, and enforcement only gates new
        growth — a silently over-quota tenant would be a lie) or when
        the reserved floors together exceed the usable pool (an
        unkeepable promise). Stats and the WFQ virtual time survive
        reconfiguration."""
        held = self.cache.tenant_charge(tenant_id)
        if quota_blocks is not None and quota_blocks < held:
            raise ValueError(
                f"tenant {tenant_id!r} already holds {held} block(s); "
                f"a quota of {quota_blocks} would be violated on "
                f"arrival — drain the tenant first")
        existing = self.tenants.get(tenant_id)
        ten = Tenant(tenant_id, quota_blocks=quota_blocks,
                     reserved_blocks=reserved_blocks, weight=weight)
        if existing is not None:
            ten.vtime = existing.vtime
            ten.fifo = existing.fifo
            ten.queued = existing.queued
            ten.stats = existing.stats
        total_reserved = ten.reserved_blocks + sum(
            t.reserved_blocks for tid, t in self.tenants.items()
            if tid != tenant_id)
        usable = self.cache.num_blocks - 1 - self.watermark_blocks
        if total_reserved > usable:
            raise ValueError(
                f"reserved floors total {total_reserved} block(s) but "
                f"only {usable} are usable (pool {self.cache.num_blocks}"
                f" minus trash and watermark) — the guarantee would be "
                f"unkeepable")
        self.tenants[tenant_id] = ten
        return ten

    def _tenant_of(self, req: PagedRequest) -> Tenant:
        return self.tenants[req.tenant]

    @staticmethod
    def _queue_key(req: PagedRequest):
        """Global queue-order key the per-tenant sub-queues merge by:
        preempted requests (sunk compute) ride ahead of never-admitted
        ones, ordered by original submission age among themselves —
        exactly the order the old single physical deque maintained."""
        if req.preemptions > 0:
            return (0, req.rid)
        return (1, req.enqueue_seq)

    @property
    def queue(self) -> List[PagedRequest]:
        """The merged global queue view, in admission-contract order
        (see _queue_key). Built on demand — snapshot, deadline scans,
        audits and external callers read it; the admission hot path
        never does (it reads the per-tenant sub-queue heads)."""
        out: List[PagedRequest] = []
        for ten in self.tenants.values():
            out.extend(ten.fifo)
        out.sort(key=self._queue_key)
        return out

    def _enqueue(self, req: PagedRequest) -> None:
        """Queue a never-admitted request at its tenant's tail."""
        req.enqueue_seq = self._next_enqueue_seq
        self._next_enqueue_seq += 1
        ten = self.tenants[req.tenant]
        ten.fifo.append(req)
        ten.queued += 1
        self._queue_len += 1

    def _dequeue(self, req: PagedRequest) -> None:
        """The one way OFF the queue (the tenant's queued gauge moves
        with the request) — raises ValueError if not queued."""
        ten = self.tenants[req.tenant]
        if ten.fifo and ten.fifo[0] is req:
            ten.fifo.popleft()      # the admission path: O(1)
        else:
            ten.fifo.remove(req)    # rare (failure/release paths)
        ten.queued -= 1
        self._queue_len -= 1

    def _resolve_tenant(self, tenant_id: Optional[str]) -> Tenant:
        tid = DEFAULT_TENANT if tenant_id is None else str(tenant_id)
        ten = self.tenants.get(tid)
        if ten is None:
            ten = self.set_tenant(tid)   # unlimited defaults
        return ten

    def _unmet_floors(self, exclude: str) -> int:
        """Free-pool headroom reserved for OTHER tenants still below
        their floors — blocks the ``exclude`` tenant may not touch."""
        return sum(
            max(0, t.reserved_blocks - self.cache.tenant_charge(tid))
            for tid, t in self.tenants.items()
            if tid != exclude and t.reserved_blocks)

    def _bump_vtime(self, tid: str) -> None:
        """Start-time fairness: a tenant enqueueing from IDLE (nothing
        of it queued) starts at the virtual clock instead of replaying
        service credit it accrued by sitting out."""
        ten = self.tenants[tid]
        if ten.queued == 0 and ten.vtime < self._vclock:
            ten.vtime = self._vclock

    def tenant_report(self) -> Dict[str, dict]:
        """Operator view: per-tenant config + live occupancy/queue +
        stats (the doctor and the bench print this)."""
        active: Dict[str, int] = {}
        for s in np.flatnonzero(self.active | self.prefilling):
            req = self._requests[int(s)]
            if req is not None:
                active[req.tenant] = active.get(req.tenant, 0) + 1
        cost = (self.ledger.tenant_cost()
                if self.ledger is not None else None)
        return {tid: dict({
            "quota_blocks": t.quota_blocks,
            "reserved_blocks": t.reserved_blocks,
            "weight": t.weight,
            "vtime": round(t.vtime, 6),
            "blocks_held": self.cache.tenant_charge(tid),
            "active": active.get(tid, 0),
            "queued": t.queued,
            "stats": t.stats.as_dict(),
        }, **({"cost": cost[tid]} if cost and tid in cost else {}))
            for tid, t in self.tenants.items()}

    # -- admission ----------------------------------------------------
    def submit(self, prompt, *, max_preemptions: Optional[int] = None,
               deadline_steps: Optional[int] = None,
               deadline_s: Optional[float] = None,
               tenant_id: Optional[str] = None,
               n: int = 1) -> int:
        """Queue a prompt ([T, d_model] embeddings) and try to admit.
        Returns the request id; if admission succeeded an
        ``(rid, slot, last_hidden)`` event is in ``admitted``. With
        ``prefill_token_budget`` set, admission only grants a slot —
        the prompt streams during subsequent ``step`` calls and the
        admitted event fires when the last chunk lands.

        ``tenant_id`` attributes the request to a tenant (quota /
        reserved floor / admission weight — see the class docstring);
        None maps to the implicit unlimited ``default`` tenant, and an
        unknown id auto-registers one with unlimited defaults.

        HEALTH-BASED ADMISSION CONTROL: a request that provably can
        never be served — its prompt needs more blocks than its
        tenant's quota, or than the pool minus other tenants' reserved
        floors, or (token-budget mode) its ``deadline_steps`` is below
        the prefill-step lower bound ceil(T / (budget + 1)) — is
        REJECTED at submit with a terminal ``REJECTED_ADMISSION``
        outcome in ``outcomes`` instead of being queued to fail later.
        Rejection is an outcome, never an exception, and depends only
        on deterministic scheduler state, so a journaled replay
        re-rejects identically. (Malformed submissions — empty prompt,
        prompt past the per-seq page capacity — still raise ValueError
        before any engine mutation, as before.)

        Resilience knobs (all optional, None = unbounded):
        ``max_preemptions`` caps the re-prefill retry budget for THIS
        request (overriding the engine default) — exceeding it fails
        the request with FAILED_OOM instead of requeueing, so two long
        prompts can never livelock each other through eviction.
        ``deadline_steps`` / ``deadline_s`` fail the request
        (FAILED_DEADLINE) once that many engine steps / seconds have
        passed since submission, whether it is running, mid-prefill or
        still queued. Terminal outcomes surface in ``outcomes``.

        FORK-SHARED PARALLEL DECODING (``n`` > 1): ONE request is
        queued whose prompt prefills ONCE; when the last chunk lands
        the engine COW-forks n-1 branch slots whose block tables
        reference the same prompt pages (each branch charged per
        reference — the PR 7 policy), every branch gets its own fresh
        rid and its own ``(rid, slot, last_hidden)`` admitted event
        sharing the lead's prefill hidden, and from then on each
        branch is a normal slot (growth COW-splits the written block;
        preemption degrades a branch to an independent re-prefill).
        Admission requires n free slots; the group is the admission
        unit. The return value is the LEAD's rid == the group id."""
        arr = np.asarray(prompt.numpy() if hasattr(prompt, "numpy")
                         else prompt, np.float32)
        if arr.shape[0] == 0:
            raise ValueError("empty prompt")
        if arr.shape[0] > self.max_len:
            raise ValueError(
                f"prompt length {arr.shape[0]} > per-seq page capacity "
                f"{self.max_len}")
        n = int(n)
        if n < 1:
            raise ValueError(f"n must be >= 1, got {n}")
        if n > self.max_batch:
            raise ValueError(
                f"n={n} branches exceed max_batch={self.max_batch}")
        ten = self._resolve_tenant(tenant_id)
        req = PagedRequest(self._next_rid, arr)
        self._next_rid += 1
        req.tenant = ten.tid
        if n > 1:
            req.gid = req.rid
            req.group_n = n
        req.max_preemptions = (self.max_preemptions
                               if max_preemptions is None
                               else int(max_preemptions))
        req.submit_step = self._step_count
        if deadline_steps is not None:
            req.deadline_steps = int(deadline_steps)
        if deadline_s is not None:
            req.deadline_time = time.monotonic() + float(deadline_s)
        if self.collector is not None:
            self.collector.on_submit(req.rid, ten.tid, arr.shape[0],
                                     gid=req.gid)
        if self.ledger is not None:
            self.ledger.on_submit(req.rid, ten.tid, arr.shape[0])
        reject = self._admission_health(req, ten)
        if reject:
            self._record(req, RequestOutcome.REJECTED_ADMISSION,
                         reject)
            return req.rid
        if deadline_steps is not None or deadline_s is not None:
            self._has_deadlines = True
        if n > 1:
            self.groups.create(req.rid, n)
            self.parallel_stats.groups += 1
        self._bump_vtime(ten.tid)
        self._enqueue(req)
        self._try_admit()
        return req.rid

    def _admission_health(self, req: PagedRequest,
                          ten: Tenant) -> str:
        """Reason string when the request provably cannot be served
        from the current configuration (it would only ever burn pool
        and queue time before failing), else ''. Every check is a
        PERMANENT impossibility under the current tenant/pool
        contracts — transient pressure never rejects, it queues."""
        # the horizon every serving path must eventually cover: the
        # prompt PLUS the first decode token's page (the same +1 the
        # synchronous admission gate uses — a prompt ending on a block
        # boundary needs one block more than blocks_needed(T), and a
        # health check one block looser would queue it to stall at the
        # admission gate forever)
        need = self.cache.blocks_needed(min(len(req) + 1, self.max_len))
        # a branch group charges its tenant per REFERENCE (every
        # branch table references the shared prompt blocks), while the
        # PHYSICAL pool holds the prompt once plus each extra branch's
        # COW-split write page — both horizons must be coverable
        charge_need = need * req.group_n
        phys_need = need + max(0, req.group_n - 1)
        if ten.quota_blocks is not None and \
                charge_need > ten.quota_blocks:
            return (f"prompt needs {charge_need} charged block(s) "
                    f"through its first decode token "
                    f"(x{req.group_n} branch references) but tenant "
                    f"{ten.tid!r} quota is {ten.quota_blocks} — can "
                    f"never be admitted")
        # the permanent bound subtracts other tenants' FULL reserved
        # floors, not the currently-unmet remainder: free minus unmet
        # can never exceed usable minus reserved (free <= usable -
        # charge, unmet = max(0, reserved - charge)), so a check built
        # on the momentary unmet would queue a request every admission
        # pass then floor-skips forever once the floor tenant's charge
        # drops back
        reserved_others = sum(
            t.reserved_blocks for tid, t in self.tenants.items()
            if tid != ten.tid)
        room = self.cache.num_blocks - 1 - self.watermark_blocks \
            - reserved_others
        if phys_need > room:
            return (f"prompt needs {phys_need} block(s) through its "
                    f"first decode token but only {room} can ever be "
                    f"available past other tenants' reserved floors "
                    f"and the watermark")
        if req.deadline_steps is not None and \
                self.prefill_token_budget is not None:
            # each mixed step advances at most budget + 1 prompt
            # tokens (the soft cap), so this lower bound is exact
            floor_steps = -(-len(req) // (self.prefill_token_budget
                                          + 1))
            if req.deadline_steps < floor_steps:
                return (f"prefill alone needs >= {floor_steps} "
                        f"step(s) at prefill_token_budget="
                        f"{self.prefill_token_budget} but the "
                        f"deadline is {req.deadline_steps} — cannot "
                        f"be met at any pool pressure")
        return ""

    def _try_admit(self) -> None:
        """One admission pass, then the ``post_admission`` crash
        point (CrashInjector — a no-op without an injector)."""
        self._admit_pass()
        self._crash("post_admission")

    def _admit_pass(self) -> None:
        """Weighted fair admission: while a slot is free, the queued
        tenant with the LOWEST virtual time (ties broken by
        registration order) offers its oldest queued request —
        age-fair within a tenant, and preempted requests still ride
        ahead of never-admitted ones (the physical queue keeps the
        PR 5 ordering; tenancy only picks WHICH tenant's head goes
        next). The block budget must cover the admission horizon plus
        the watermark: the whole prompt (plus the first decode
        token's page) in synchronous mode, only the FIRST chunk in
        token-budget mode — chunked prefill grows the rest page by
        page under the normal preemption rules.

        Isolation semantics of a blocked head: a tenant blocked by
        its OWN quota, or by OTHER tenants' unmet reserved floors, is
        SKIPPED for this pass (its cap must never become its
        neighbors' head-of-line blocker) and its virtual time does
        not advance; true pool pressure — the head does not fit the
        raw free pool — stops the whole pass, the same no-starvation
        head-of-line rule as before (the blocked tenant keeps the
        lowest vtime, so it admits first once space frees)."""
        skipped: set = set()
        order = {tid: i for i, tid in enumerate(self.tenants)}
        while self._queue_len and self.free_slots > 0:
            # head selection is O(tenants): each tenant's oldest
            # queued request IS its sub-queue head (no global scan)
            cands = [tid for tid, t in self.tenants.items()
                     if t.fifo and tid not in skipped]
            if not cands:
                return
            tid = min(cands, key=lambda t: (self.tenants[t].vtime,
                                            order.get(t, len(order))))
            ten = self.tenants[tid]
            req = ten.fifo[0]
            # a branch group admits as ONE unit: the lead's prompt
            # plus a slot per branch — without all n slots the fork at
            # prefill completion could not land, so the group waits
            # head-of-line (same no-starvation rule as pool pressure)
            if req.group_n > self.free_slots:
                return
            if self.prefill_token_budget is None:
                # cover the prompt AND the first decode token's page —
                # admitting with zero headroom would re-preempt a
                # request sitting on a block boundary every step
                # (prefill/evict livelock)
                horizon = min(len(req) + 1, self.max_len)
            else:
                horizon = min(len(req), self.chunk_tokens)
            need = self.cache.blocks_needed(horizon)
            # tenant quota gates the FULL reference count (shared
            # prefix hits are charged per reference — the policy note
            # in PagedKVCache.__init__), unlike the pool draw below;
            # a branch group's fork multiplies every prompt-block
            # reference by n, so the quota gate scales with it
            quota_need = need * req.group_n
            if ten.quota_blocks is not None and \
                    self.cache.tenant_charge(tid) + quota_need \
                    > ten.quota_blocks:
                ten.stats.quota_hits += 1
                skipped.add(tid)
                continue
            if self.prefix_cache:
                # actively shared prefix hits cost no pool draw at all;
                # cached-free hits come out of free_blocks (a resurrect
                # consumes one free unit, same as an alloc) so only the
                # active ones discount `need`
                matched = self.cache.match_prefix(
                    req.block_hashes(self.cache.block_size))
                rc = self.cache.allocator.refcount
                need -= sum(1 for b in matched if rc[b] > 0)
            # physical pool draw: the prompt pages land ONCE however
            # many branches will reference them; each extra branch
            # only needs headroom for its first COW-split write page
            draw = max(need, 0) + max(0, req.group_n - 1) \
                + self.watermark_blocks
            if draw > self.free_blocks:
                return  # head-of-line pool pressure blocks the pass
            if draw > self.free_blocks - self._unmet_floors(tid):
                # only other tenants' reservations stand in the way:
                # their entitlement, this tenant's wait
                skipped.add(tid)
                continue
            self._dequeue(req)
            self._vclock = ten.vtime
            ten.vtime += 1.0 / ten.weight
            if self.prefill_token_budget is None:
                try:
                    self._prefill(req)
                except BlockOOM as e:
                    if self.collector is not None:
                        self.collector.on_event("block_oom", dict(
                            e.details, rid=req.rid, tenant=req.tenant,
                            step=self._step_count))
                    # the budget check above said the prompt fits, so
                    # this is an injected fault (or a raced reclaim):
                    # un-admit — drop the partial pages and retry on a
                    # later admission pass, against the retry budget
                    if req.slot is not None:
                        self._drop(req.slot)
                        req.slot = None
                    if self._over_retry_budget(req):
                        self._fail(req, RequestOutcome.FAILED_OOM,
                                   f"admission prefill OOM and retry "
                                   f"budget exhausted: {e}")
                    else:
                        req.preemptions += 1
                        self._tenant_of(req).stats.preemptions += 1
                        self._requeue_preempted(req)
                        self.preempted.append(req.rid)
                    return
            else:
                # grant the slot only; step() streams the chunks
                self._start_prefill(req)

    def _start_prefill(self, req: PagedRequest) -> int:
        """Grant a slot and set up chunked-prefill state: adopt any
        cached prefix pages and compute the recompute start P (the
        suffix keeps at least MIN_PREFILL_SUFFIX_ROWS rows — see the
        constant's comment: 1-row GEMV accumulation breaks
        bit-identity, and the admission event needs a last hidden)."""
        slot = int(np.flatnonzero(~self.active & ~self.prefilling)[0])
        # attribute the slot BEFORE any page lands in it, so adopted
        # prefix blocks and the first chunk's pages charge the right
        # tenant from the first reference
        self.cache.set_seq_tenant(slot, req.tenant)
        T = len(req)
        bs = self.cache.block_size
        hashes: List[bytes] = []
        n_cached = 0
        if self.prefix_cache:
            hashes = req.block_hashes(bs)
            n_cached = self.cache.adopt_prefix(slot, hashes)
            self.prefix_stats.lookups += 1
            self.prefix_stats.lookup_blocks += len(hashes)
            self.prefix_stats.hit_blocks += n_cached
        P = max(0, min(n_cached * bs, T - MIN_PREFILL_SUFFIX_ROWS)) \
            if n_cached else 0
        if self.ledger is not None and P:
            # rows [0, P) adopted, never computed: prefix-cache
            # savings (warm-resume savings on a re-prefill)
            self.ledger.on_prefill_skip(req.rid, P)
        self._prefills[slot] = {"pos": P, "start": P,
                                "n_cached": n_cached, "hashes": hashes}
        self.prefilling[slot] = True
        self._requests[slot] = req
        req.slot = slot
        req.admit_seq = self._next_admit_seq
        self._next_admit_seq += 1
        self._tenant_of(req).stats.admitted += 1
        if req.preemptions > 0:
            self.resilience_stats.retried += 1
        if self.collector is not None:
            self.collector.on_admitted(req.rid, slot,
                                       retry=req.preemptions > 0)
        if req.group_n > 1 and self.prefill_token_budget is not None:
            # token-budget mode: the lead's prompt streams over many
            # steps while admission keeps running — hold the branch
            # slots NOW (prefilling, no request/prefill state) so the
            # fork at prefill completion still has its n-1 targets.
            # The admission gate guaranteed free_slots >= group_n.
            g = self.groups.groups[req.gid]
            for _ in range(req.group_n - 1):
                rs = int(np.flatnonzero(~self.active
                                        & ~self.prefilling)[0])
                self.prefilling[rs] = True
                g["reserved"].append(rs)
        return slot

    def _complete_prefill(self, slot: int, last_hidden) -> None:
        """Last chunk landed: the slot turns decodable and the
        admission event fires."""
        st = self._prefills.pop(slot)
        req = self._requests[slot]
        T = len(req)
        if self.prefix_cache:
            self.cache.register_prefix(slot, st["hashes"])
            self.prefix_stats.tokens_computed += T - st["start"]
            self.prefix_stats.tokens_skipped += st["start"]
        self.prefilling[slot] = False
        self.lens[slot] = T
        self.active[slot] = True
        self.admitted.append((req.rid, slot, last_hidden))
        if self.collector is not None:
            # the admitted event's last hidden is what the caller
            # samples the FIRST TOKEN from — TTFT's defining moment
            self.collector.on_first_token(req.rid)
        self._fork_group(slot, last_hidden)
        self._crash("post_prefill")

    def _chunk_registrar(self, slot: int, st: dict):
        """``on_chunk`` hook for chunked_prefill: index every COMPLETED
        prompt block under its chain hash as the stream advances (not
        only at prefill completion), so a preemption or crash-restore
        mid-prefill re-adopts its own finished pages on re-admission
        instead of recomputing them — the pages park cached-free when
        the victim's slot is dropped and resurrect via adopt_prefix.
        Only full blocks below the write frontier are registered;
        their content is final (later chunks write strictly past
        them), so the immutability audit holds."""
        if not self.prefix_cache:
            return None
        last = [0]      # blocks registered so far by THIS registrar —
                        # keeps a C-chunk prefill at O(blocks), not
                        # O(blocks x chunks) re-probes of the prefix

        def register(pos: int) -> None:
            done = pos // self.cache.block_size
            if done > last[0]:
                self.cache.register_prefix(slot, st["hashes"][:done],
                                           start=last[0])
                last[0] = done
        return register

    def _chunk_hook(self, slot: int, st: dict, req: PagedRequest):
        """``on_chunk`` for engine prefills: the prefix registrar
        (above) composed with the telemetry chunk event and the cost
        ledger's chunk accounting — one callback, built only when a
        consumer exists. The ledger sees every computed chunk as a
        [prev, pos) row span (the replay-vs-fresh split happens
        inside the ledger off its per-request high-water mark)."""
        reg = self._chunk_registrar(slot, st)
        col = self.collector
        led = self.ledger
        if col is None and led is None:
            return reg
        rid = req.rid
        prev = [st["pos"]]

        def hook(pos: int) -> None:
            if reg is not None:
                reg(pos)
            if led is not None:
                led.on_prefill(rid, prev[0], pos)
                prev[0] = pos
            if col is not None:
                col.on_prefill_chunk(rid, pos)
        return hook

    def _prefill(self, req: PagedRequest) -> None:
        """Synchronous admission: stream every chunk now (block budget
        for the whole prompt was checked by _try_admit, so the chunk
        ensures cannot OOM). Runs outside the step-phase timeline
        (submit-time admission), so it records its own ``prefill``
        span — admission prefill cost stays visible either way."""
        slot = self._start_prefill(req)
        st = self._prefills[slot]
        col = self.collector
        depth = col.span_depth if col is not None else 0
        if col is not None:
            col.span_begin("prefill", rid=req.rid,
                           tokens=len(req) - st["pos"])
        try:
            _, h = chunked_prefill(
                self.model, self.cache, slot, req.history,
                pos=st["pos"], target=len(req),
                chunk_tokens=self.chunk_tokens,
                start_block=st["n_cached"],
                write_start=st["n_cached"] * self.cache.block_size,
                stats=self.prefill_stats,
                on_chunk=self._chunk_hook(slot, st, req))
            self._complete_prefill(slot, h)
        except BaseException:
            # an injected BlockOOM or EngineCrash mid-prefill unwinds
            # through here (the admission pass un-admits): close the
            # span flagged so the trace shows the tear-down
            if col is not None:
                col.span_unwind(depth, aborted=True)
            raise
        if col is not None:
            col.span_unwind(depth)

    def _advance_prefills(self) -> Tuple[bool, List[int]]:
        """Token-budget mode: spend ``prefill_token_budget`` prompt
        tokens on pending prefills, oldest first (finish what was
        started before newer grants). The cap is soft by one token:
        a chunk never splits below MIN_PREFILL_SUFFIX_ROWS and never
        leaves a 1-row tail, so when the remaining budget and prompt
        collide with that floor the chunk runs one token long rather
        than deferring (a deferral could never clear — the budget is
        identical next step). Page growth preempts the
        youngest request on OOM — possibly a prefilling one, possibly
        the slot being advanced itself (it then re-queues whole).
        Returns (ran, fresh): whether any chunk ran, and the slots
        whose prefill COMPLETED just now — the caller hasn't drained
        their admitted events yet, so they must sit this step's
        decode out."""
        if self.prefill_token_budget is None or \
                self.num_prefilling == 0:
            return False, []
        budget = self.prefill_token_budget
        ran = False
        fresh: List[int] = []
        while budget >= MIN_PREFILL_SUFFIX_ROWS:
            # reserved branch slots (prefilling, no prefill state)
            # hold no prompt to advance — only real prefills qualify
            slots = [int(s) for s in np.flatnonzero(self.prefilling)
                     if int(s) in self._prefills]
            if not slots:
                break
            slot = min(slots,
                       key=lambda s: self._requests[s].admit_seq)
            req = self._requests[slot]
            st = self._prefills[slot]
            T = len(req)
            c = _chunk_len(T, st["pos"], self.chunk_tokens,
                           budget=budget)
            if not self._grow_or_shed(slot, req, st["pos"] + c,
                                      start_block=st["n_cached"],
                                      write_from=st["pos"]):
                continue  # the slot was evicted (or shed) growing
            pos, h = chunked_prefill(
                self.model, self.cache, slot, req.history,
                pos=st["pos"], target=st["pos"] + c,
                chunk_tokens=self.chunk_tokens,
                start_block=st["n_cached"],
                write_start=st["n_cached"] * self.cache.block_size,
                stats=self.prefill_stats,
                on_chunk=self._chunk_hook(slot, st, req))
            st["pos"] = pos
            budget -= c
            ran = True
            if pos >= T:
                self._complete_prefill(slot, h)
                fresh.append(slot)
        if ran:
            self.prefill_stats.prefill_steps += 1
        return ran, fresh

    def _plan_prefills(self) -> Tuple[bool, List[int]]:
        """RAGGED token-budget mode: spend the prefill budget exactly
        like ``_advance_prefills`` — identical chunk lengths, growth/
        preemption sequence and stats — but RECORD the chunks in
        ``self._ragged_plan`` instead of launching each as its own
        model call; the step's single packed launch
        (``_flush_ragged_plan``) runs them with the decode rows.
        Completed prefills transition slot state HERE (so the step's
        masks and capacity checks match the eager path exactly); the
        admitted event and prefix registration fire post-launch, when
        the pages exist. A drop of a planned slot flushes the pending
        segments first (``_drop``) — in the eager path those chunks
        had already run before any later preemption could fire, so
        registration/warm-resume semantics are unchanged."""
        if self.prefill_token_budget is None or \
                self.num_prefilling == 0:
            return False, []
        plan = self._ragged_plan
        budget = self.prefill_token_budget
        ran = False
        fresh: List[int] = []
        while budget >= MIN_PREFILL_SUFFIX_ROWS:
            # reserved branch slots (prefilling, no prefill state)
            # hold no prompt to advance — only real prefills qualify
            slots = [int(s) for s in np.flatnonzero(self.prefilling)
                     if int(s) in self._prefills]
            if not slots:
                break
            slot = min(slots,
                       key=lambda s: self._requests[s].admit_seq)
            req = self._requests[slot]
            st = self._prefills[slot]
            T = len(req)
            c = _chunk_len(T, st["pos"], self.chunk_tokens,
                           budget=budget)
            if not self._grow_or_shed(slot, req, st["pos"] + c,
                                      start_block=st["n_cached"],
                                      write_from=st["pos"]):
                continue  # the slot was evicted (or shed) growing
            seg = plan[-1] if plan and plan[-1]["slot"] == slot \
                else None
            if seg is None:
                seg = {"slot": slot, "req": req, "from": st["pos"],
                       "to": st["pos"],
                       "ws": st["n_cached"] * self.cache.block_size,
                       "bounds": [],
                       "hook": self._chunk_hook(slot, st, req),
                       "complete": False}
                plan.append(seg)
            st["pos"] += c
            seg["to"] = st["pos"]
            seg["bounds"].append(st["pos"])
            # chunk accounting at the same points chunked_prefill hits
            self.prefill_stats.chunks += 1
            self.prefill_stats.prefill_tokens += c
            self.prefill_stats.peak_blocks = max(
                self.prefill_stats.peak_blocks,
                self.cache.blocks_in_use)
            budget -= c
            ran = True
            if st["pos"] >= T:
                seg["complete"] = True
                self.prefilling[slot] = False
                self.lens[slot] = T
                self.active[slot] = True
                fresh.append(slot)
        if ran:
            self.prefill_stats.prefill_steps += 1
        return ran, fresh

    def _flush_ragged_plan(self, x: Optional[Tensor] = None,
                           L: int = 1):
        """Run the pending planned prefill segments — plus, at the
        step's model point, the fused decode rows — as ONE ragged
        model call through ``PagedKVCache.ragged_views``. CPU streams
        stay bit-identical to the per-chunk launches (the view
        decomposes back into the per-phase executables; the packed
        non-attention ops are per-row invariant — the same contract
        chunked prefill rests on), and the kernel path collapses the
        step to one paged-attention dispatch per layer. ``L`` > 1
        packs a MULTI-TOKEN verify alongside the prefill chunks
        (step_multi in token-budget mode): x is [max_batch, L, d] and
        each slot contributes L rows at positions lens .. lens+L-1.
        Returns the decode hidden [max_batch, L, d] when ``x`` rode
        along, else None.

        SHARD-AWARE by construction: a ShardedServingCore model takes
        the same single packed call and fans each layer out over the
        ragged views' ``shard(s)`` accessor — one ragged launch per
        layer PER SHARD on its own pool slice, closed by exactly one
        all-reduce per layer (the mp=N mixed step stays
        one-model-call, and its streams stay bit-identical to the
        single-chip engine's)."""
        plan = self._ragged_plan
        segs = [s for s in plan if s["to"] > s["from"]]
        del plan[:]
        if not segs and x is None:
            return None
        desc: List[tuple] = [
            ("prefill", s["slot"], s["from"], s["to"] - s["from"],
             s["ws"]) for s in segs]
        if x is not None:
            desc.append(("decode", self.lens.copy(), L))
        views = self.cache.ragged_views(desc, tile_q=self.tile_q,
                                        tile_kv=self.tile_kv)
        import jax.numpy as jnp
        parts = [jnp.asarray(np.ascontiguousarray(
            s["req"].history[s["from"]:s["to"]], np.float32))
            for s in segs]
        if x is not None:
            parts.append(x.data.reshape(self.max_batch * L,
                                        x.shape[-1]))
        xp = Tensor(jnp.concatenate(parts, axis=0)[None])
        with no_grad():
            out, _ = self.model(xp, caches=views,
                                time_step=Tensor(np.int32(0)))
        hv = out.data
        lo = 0
        for s in segs:
            n = s["to"] - s["from"]
            if s["hook"] is not None:
                for b in s["bounds"]:
                    s["hook"](b)
            if s["complete"]:
                self._finish_planned_prefill(
                    s["slot"], Tensor(hv[0, lo + n - 1:lo + n]))
            lo += n
        if x is not None:
            return Tensor(hv[0, lo:lo + self.max_batch * L].reshape(
                (self.max_batch, L) + tuple(hv.shape[2:])))
        return None

    def _finish_planned_prefill(self, slot: int, last_hidden) -> None:
        """Post-launch half of prefill completion for the ragged step
        (the state transition already ran at plan time): the pages now
        exist, so register the prefix blocks and fire the admitted
        event — the same sequence ``_complete_prefill`` runs eagerly."""
        st = self._prefills.pop(slot)
        req = self._requests[slot]
        T = len(req)
        if self.prefix_cache:
            self.cache.register_prefix(slot, st["hashes"])
            self.prefix_stats.tokens_computed += T - st["start"]
            self.prefix_stats.tokens_skipped += st["start"]
        self.admitted.append((req.rid, slot, last_hidden))
        if self.collector is not None:
            self.collector.on_first_token(req.rid)
        self._fork_group(slot, last_hidden)
        self._crash("post_prefill")

    def _fork_group(self, slot: int, last_hidden) -> None:
        """COW-fork the branch slots of a freshly prefilled group
        lead: every branch gets a fresh rid, a history COPY (branches
        diverge from the shared prompt on their first decode token), a
        block table REFERENCING the lead's prompt pages
        (``PagedKVCache.fork`` — charged per reference) and its own
        admitted event carrying the SHARED prefill hidden, so the
        caller samples each branch's first token from one prefill.
        The ledger's ``on_fork`` raises the branch's high-water mark
        to the fork length WITHOUT pending rows — the shared prefill
        is priced exactly once, under the lead. Runs BEFORE the
        ``post_prefill`` crash point: a crash there replays with the
        fork already journaled in the step's effects, the mid-group
        recovery case the tests pin."""
        req = self._requests[slot]
        if req is None or req.group_n <= 1:
            return
        g = self.groups.groups.get(req.gid)
        if g is None or g["forked"]:
            return
        n = req.group_n
        T = len(req)
        reserved = list(g["reserved"])
        del g["reserved"][:]
        for i in range(1, n):
            if reserved:
                bslot = reserved.pop(0)
                self.prefilling[bslot] = False
            else:
                bslot = int(np.flatnonzero(~self.active
                                           & ~self.prefilling)[0])
            breq = PagedRequest(self._next_rid, req.history)
            self._next_rid += 1
            breq.tenant = req.tenant
            breq.gid = req.gid
            breq.branch = i
            breq.max_preemptions = req.max_preemptions
            breq.deadline_steps = req.deadline_steps
            breq.deadline_time = req.deadline_time
            breq.submit_step = req.submit_step
            self.groups.add_branch(req.gid, breq.rid)
            if self.collector is not None:
                self.collector.on_submit(breq.rid, breq.tenant, T,
                                         gid=breq.gid)
            if self.ledger is not None:
                self.ledger.on_submit(breq.rid, breq.tenant, T)
                self.ledger.on_fork(breq.rid, T)
            # attribute BEFORE the fork so every shared-page reference
            # charges the branch's tenant from the first reference
            self.cache.set_seq_tenant(bslot, breq.tenant)
            self.cache.fork(slot, bslot, T)
            self._requests[bslot] = breq
            breq.slot = bslot
            breq.admit_seq = self._next_admit_seq
            self._next_admit_seq += 1
            self.lens[bslot] = T
            self.active[bslot] = True
            self._tenant_of(breq).stats.admitted += 1
            if self.collector is not None:
                self.collector.on_admitted(breq.rid, bslot,
                                           retry=False)
            self.admitted.append((breq.rid, bslot, last_hidden))
            if self.collector is not None:
                self.collector.on_first_token(breq.rid)
            self.parallel_stats.branches += 1
            self.parallel_stats.prefill_tokens_saved += T
            self.parallel_stats.shared_blocks += \
                self.cache.blocks_needed(T)
        g["forked"] = True
        # the lead is a normal slot from here: a later preemption
        # re-prefills it alone instead of re-forking
        req.group_n = 1

    def fork_stream(self, rid: int) -> int:
        """Beam/tree primitive: clone a RUNNING stream mid-decode into
        a free slot — history copied, pages COW-shared at the current
        length (the clone's next written block splits), fresh rid
        returned. The source's group grows by the clone (a group is
        created on demand for a previously lone stream), so the group
        audit and outcome aggregation cover beam trees too. Raises
        ValueError when the rid is not active or no slot is free —
        beam scheduling is the caller's policy; the engine only
        provides the fork."""
        slot = None
        for s, r in enumerate(self._requests):
            if r is not None and r.rid == rid:
                slot = s
                break
        if slot is None or not self.active[slot]:
            raise ValueError(f"rid {rid} is not an active stream")
        free = np.flatnonzero(~self.active & ~self.prefilling)
        reserved = self.groups.reserved_slots()
        free = [int(s) for s in free if int(s) not in reserved]
        if not free:
            raise ValueError("no free slot to fork into")
        # buffered decode inputs must reach the history before it is
        # copied, or the clone would re-prefill a truncated stream
        self._flush_history()
        req = self._requests[slot]
        bslot = free[0]
        L = int(self.lens[slot])
        if req.gid is None:
            req.gid = req.rid
            g = self.groups.create(req.rid, 1)
            g["forked"] = True
            self.parallel_stats.groups += 1
        g = self.groups.groups[req.gid]
        breq = PagedRequest(self._next_rid, req.history)
        self._next_rid += 1
        breq.tenant = req.tenant
        breq.gid = req.gid
        breq.branch = len(g["rids"])
        breq.max_preemptions = req.max_preemptions
        breq.deadline_steps = req.deadline_steps
        breq.deadline_time = req.deadline_time
        breq.submit_step = req.submit_step
        g["n"] += 1
        self.groups.add_branch(req.gid, breq.rid)
        if self.collector is not None:
            self.collector.on_submit(breq.rid, breq.tenant, L,
                                     gid=breq.gid)
        if self.ledger is not None:
            self.ledger.on_submit(breq.rid, breq.tenant, L)
            self.ledger.on_fork(breq.rid, L)
        self.cache.set_seq_tenant(bslot, breq.tenant)
        self.cache.fork(slot, bslot, L)
        self._requests[bslot] = breq
        breq.slot = bslot
        breq.admit_seq = self._next_admit_seq
        self._next_admit_seq += 1
        self.lens[bslot] = L
        self.active[bslot] = True
        self._tenant_of(breq).stats.admitted += 1
        if self.collector is not None:
            self.collector.on_admitted(breq.rid, bslot, retry=False)
        self.parallel_stats.branches += 1
        self.parallel_stats.prefill_tokens_saved += L
        self.parallel_stats.shared_blocks += self.cache.blocks_needed(L)
        return breq.rid

    def cancel(self, rid: int) -> bool:
        """Deliberate early stop of one stream (best-of-n loser
        pruning, beam cuts, caller cancel): pages freed through the
        normal drop path (cached-free second chance intact — the
        content is healthy), terminal CANCELLED outcome, pending
        ledger work resolved as ``bestof_pruned`` waste. Works on
        running, mid-prefill and queued (preempted) members alike.
        Returns False for an unknown/already-terminal rid."""
        req = None
        for r in self._requests:
            if r is not None and r.rid == rid:
                req = r
                break
        if req is None:
            for r in self.queue:
                if r.rid == rid:
                    req = r
                    break
        if req is None:
            return False
        self._fail(req, RequestOutcome.CANCELLED,
                   "cancelled (early stop)")
        self._try_admit()
        return True

    # -- release / preemption / failure -------------------------------
    def release(self, slot: int) -> None:
        """Caller-side finish (e.g. EOS): free the pages, refill. The
        request's terminal RequestOutcome (FINISHED) lands in
        ``outcomes``."""
        req = self._requests[slot]
        self._drop(slot)
        if req is not None:
            self._record(req, RequestOutcome.FINISHED, "released")
        self._try_admit()

    def _record(self, req: PagedRequest, status: str,
                reason: str) -> None:
        self.outcomes.append(RequestOutcome(
            req.rid, status, reason=reason, tokens=len(req),
            preemptions=req.preemptions, step=self._step_count))
        st = self.resilience_stats
        ts = self._tenant_of(req).stats
        if status == RequestOutcome.FAILED_OOM:
            st.shed += 1
            ts.sheds += 1
        elif status == RequestOutcome.FAILED_NUMERIC:
            st.nan_failed += 1
            ts.nan_failed += 1
        elif status == RequestOutcome.FAILED_DEADLINE:
            st.deadline_failed += 1
            ts.deadline_failed += 1
        elif status == RequestOutcome.REJECTED_ADMISSION:
            st.rejected += 1
            ts.rejections += 1
        elif status == RequestOutcome.CANCELLED:
            st.cancelled += 1
            ts.cancelled += 1
        # group outcome aggregation: a member's terminal verdict
        # retires it from its group's live set (the record drops when
        # the last member lands)
        self.groups.on_terminal(req.rid)
        col = self.collector
        if self.ledger is not None:
            # the terminal verdict resolves the request's pending work
            # (goodput on FINISHED, retroactive waste on failure)
            self.ledger.on_outcome(req.rid, status)
        if col is not None:
            col.on_outcome(req.rid, status, self._step_count,
                           reason=reason)
            if status == RequestOutcome.FAILED_OOM:
                # the structured BlockOOM breakdown as an event: every
                # shed carries WHO held the pool when it fired
                col.on_event("oom_shed", dict(
                    self.cache.pool_occupancy(), rid=req.rid,
                    tenant=req.tenant, step=self._step_count))

    def _fail(self, req: PagedRequest, status: str,
              reason: str) -> None:
        """Terminal failure of ONE request: free its pages (numeric
        failures quarantine them — no cached-free second chance, the
        content is suspect), detach it from slot/queue, record the
        outcome. The engine, and every other request, keeps going."""
        if req.slot is not None:
            self._drop(req.slot,
                       quarantine=status == RequestOutcome.FAILED_NUMERIC)
            req.slot = None
        else:
            try:
                self._dequeue(req)
            except ValueError:
                pass
        self._record(req, status, reason)

    def _over_retry_budget(self, req: PagedRequest) -> bool:
        return req.max_preemptions is not None and \
            req.preemptions >= req.max_preemptions

    def _requeue_preempted(self, req: PagedRequest) -> None:
        """Readmission fairness: preempted requests re-enter the queue
        AHEAD of never-admitted ones (they carry sunk prefill/decode
        compute), ordered among themselves by original submission age
        — NOT plain appendleft, which reverses the order of two
        requests preempted in different engine passes (a re-admitted
        old request holds a fresh admit_seq, so it is evicted first
        and appendleft would then queue it BEHIND its younger peer).
        The insert is into the request's TENANT sub-queue, whose
        internal order follows the same global _queue_key contract."""
        self._bump_vtime(req.tenant)
        ten = self.tenants[req.tenant]
        key = self._queue_key(req)
        i = 0
        for r in ten.fifo:
            if self._queue_key(r) < key:
                i += 1
            else:
                break
        ten.fifo.insert(i, req)
        ten.queued += 1
        self._queue_len += 1

    def _check_deadlines(self) -> None:
        """Fail every request (active, mid-prefill or queued) whose
        per-request deadline has passed. Zero overhead unless some
        submit() actually set a deadline."""
        if not self._has_deadlines:
            return
        now = None
        held = [self._requests[int(s)] for s in
                np.flatnonzero(self.active | self.prefilling)]
        # scan the sub-queues directly: expiry does not care about the
        # merged order, so don't pay the queue property's sort here
        queued = [r for t in self.tenants.values() for r in t.fifo]
        for req in held + queued:
            if req is None:
                continue
            expired = ""
            if req.deadline_steps is not None and \
                    self._step_count - req.submit_step > \
                    req.deadline_steps:
                expired = (f"deadline of {req.deadline_steps} steps "
                           f"exceeded")
            elif req.deadline_time is not None:
                now = time.monotonic() if now is None else now
                if now >= req.deadline_time:
                    expired = "wall-clock deadline exceeded"
            if expired:
                self._fail(req, RequestOutcome.FAILED_DEADLINE, expired)

    def _flush_history(self) -> None:
        """Attribute buffered decode inputs to their requests'
        histories. Must run before any slot->request mapping change
        (drop/preempt), which is the only time histories are read."""
        if not self._pending_history:
            return
        pending, self._pending_history = self._pending_history, []
        for xt, mask in pending:
            xv = np.asarray(xt.numpy(), np.float32)
            for slot in np.flatnonzero(mask):
                req = self._requests[int(slot)]
                if req is not None:
                    # all L rows of a multi-token (speculative) step;
                    # rejected rows are trimmed back by rollback()
                    for row in xv[int(slot)]:
                        req.append_history(row)

    def _drop(self, slot: int, quarantine: bool = False) -> None:
        plan = self._ragged_plan
        if plan and any(s["slot"] == slot for s in plan):
            # ragged step: the eager path had already RUN this slot's
            # chunks before any later preemption could fire — flush
            # the pending segments so its pages are written (and its
            # completed blocks registered) before they are freed
            self._flush_ragged_plan()
        self._flush_history()
        req = self._requests[slot]
        if req is not None and req.group_n > 1 and \
                req.gid is not None:
            # an UNFORKED group lead leaving its slot (preemption /
            # failure / cancel) releases the branch-slot reservation —
            # a re-admission reserves afresh
            g = self.groups.groups.get(req.gid)
            if g is not None and g["reserved"]:
                for rs in g["reserved"]:
                    self.prefilling[rs] = False
                del g["reserved"][:]
        if quarantine:
            self.cache.quarantine_seq(slot)
        else:
            self.cache.free_seq(slot)
        self.active[slot] = False
        self.prefilling[slot] = False
        self._prefills.pop(slot, None)
        self.lens[slot] = 0
        self._requests[slot] = None

    def preempt(self, slot: int) -> None:
        """Evict a running (or mid-prefill) request: free ALL its
        pages and requeue it ahead of never-admitted requests for
        re-prefill from its history (a mid-prefill victim restarts its
        prompt stream on re-admission). A request past its
        ``max_preemptions`` retry budget FAILS (FAILED_OOM outcome)
        instead of requeueing — bounded retry, no re-prefill
        livelock."""
        req = self._requests[slot]
        if req is None:
            raise ValueError(f"slot {slot} not active")
        if self._over_retry_budget(req):
            self._fail(req, RequestOutcome.FAILED_OOM,
                       f"preemption retry budget "
                       f"({req.max_preemptions}) exhausted")
            return
        self._drop(slot)
        req.slot = None
        req.preemptions += 1
        self._tenant_of(req).stats.preemptions += 1
        self._requeue_preempted(req)
        self.preempted.append(req.rid)
        if self.collector is not None:
            self.collector.on_preempted(req.rid)

    def _oom_victims(self, req: PagedRequest) -> List[int]:
        """Eligible eviction victims for a POOL OOM hit while growing
        ``req``: the grower's OWN tenant's slots — pool pressure a
        tenant creates is resolved inside that tenant, never by
        evicting a within-quota neighbor. The one exception is the
        reserved-floor guarantee: a grower still BELOW its floor is
        entitled to the block, so the victims are the slots of tenants
        borrowing ABOVE their own floors (falling back to the grower's
        own if no one is over). With a single (default) tenant both
        branches degenerate to every held slot — the pre-tenant
        youngest-first policy, bit-identical."""
        held = [int(s) for s in
                np.flatnonzero(self.active | self.prefilling)
                if self._requests[int(s)] is not None]
        ten = self._tenant_of(req)
        if ten.reserved_blocks and \
                self.cache.tenant_charge(ten.tid) < ten.reserved_blocks:
            over = [s for s in held
                    if self._over_floor(self._requests[s].tenant)]
            if over:
                return over
        return [s for s in held
                if self._requests[s].tenant == ten.tid]

    def _over_floor(self, tid: str) -> bool:
        t = self.tenants[tid]
        return self.cache.tenant_charge(tid) > t.reserved_blocks

    def _preempt_youngest(self, cands: Optional[List[int]] = None) -> int:
        if cands is None:
            cands = [int(s) for s in
                     np.flatnonzero(self.active | self.prefilling)
                     if self._requests[int(s)] is not None]
        victim = max(cands, key=lambda s: self._requests[s].admit_seq)
        self.preempt(victim)
        return victim

    # -- decode -------------------------------------------------------
    def step(self, x: Tensor):
        """One fused decode step for every active slot. x: [max_batch,
        1, d_model] next-token embeddings (inactive rows: any values —
        they scatter into the trash block). Slots at page capacity are
        auto-released first (reported in ``finished``) so one full
        sequence never stalls the batch; rows crossing a block boundary
        allocate their next page, preempting the youngest request if
        the pool is dry. With ``prefill_token_budget`` set, the step
        FIRST spends the budget advancing pending prefill chunks
        (Sarathi-style mixed step) — and may legally run with zero
        active slots while prompts are still streaming (returns
        None). Returns hidden [max_batch, 1, d_model] (only rows
        active during this step are meaningful), or None if every
        slot finished before the step could run.

        FAILURE ISOLATION: a request that cannot be served — pool dry
        even after preempting every other request, retry budget or
        deadline blown, non-finite hidden in its row — is failed
        individually (RequestOutcome in ``outcomes``, pages freed) and
        the step completes for everyone else; no BlockOOM or fault
        ever escapes this call. Rows of failed/preempted slots in the
        returned hidden are garbage — drain the event lists."""
        idle = self._begin_step()
        ok = False
        try:
            out = self._step_impl(idle, x)
            ok = True
            return out
        finally:
            # balanced even when an injected EngineCrash unwinds the
            # step; a no-op (no clock read) without a collector. The
            # monitor only samples COMPLETED steps (aborted flag): a
            # torn step's mid-crash state is not a step-boundary
            # sample — it either replays after recovery (sampled
            # then) or the engine is abandoned
            self._end_step_telemetry(aborted=not ok)

    def _ragged_active(self) -> bool:
        """Pack this step? — ragged_step on, token-budget mode, and
        the kernel path live (or packing forced; see __init__)."""
        if not self.ragged_step or self.prefill_token_budget is None:
            return False
        if self.ragged_step == "force":
            return True
        # a compiled sharded core amortizes best when the whole mixed
        # batch rides its ONE jitted packed program — take the ragged
        # plan whenever it's legal, kernel or not
        if getattr(self.model, "prefers_packed_step", False):
            return True
        from ..incubate.nn.fused_transformer import _use_decode_kernel
        return _use_decode_kernel()

    def _step_impl(self, idle: bool, x: Tensor):
        if not self._ragged_active():
            return self._step_body(idle, x)
        # ragged mixed step: collect the step's prefill chunks into
        # self._ragged_plan and launch them packed with the decode
        # (_flush_ragged_plan) — cleared even when a crash unwinds
        self._ragged_plan = []
        try:
            return self._step_body(idle, x)
        finally:
            self._ragged_plan = None

    def _step_body(self, idle: bool, x: Tensor):
        plan = self._ragged_plan
        col = self.collector
        if col is not None:
            col.phase("prefill")
        if plan is None:
            ran_prefill, fresh = self._advance_prefills()
        else:
            ran_prefill, fresh = self._plan_prefills()
        if col is not None:
            col.phase("bookkeeping")
        if self.num_active == 0:
            if ran_prefill or self.num_prefilling > 0 \
                    or self._queue_len or not idle:
                if plan:
                    self._flush_ragged_plan()
                self._try_admit()
                return None
            raise RuntimeError("step() with no active slots")
        # 1. capacity-finished slots: report + release, keep the rest
        for slot in np.flatnonzero(self.active & (self.lens >=
                                                  self.max_len)):
            req = self._requests[int(slot)]
            self.finished.append((req.rid, int(slot),
                                  int(self.lens[slot])))
            self._drop(int(slot))
            self._record(req, RequestOutcome.FINISHED,
                         "page capacity reached")
        # slots whose prefill completed within THIS step sit the
        # decode out: the caller has not drained their admitted event
        # yet, so their row of x is garbage — they stay masked and
        # their length does not advance
        stepping = self.active.copy()
        for slot in fresh:
            stepping[slot] = False
        if not stepping.any():
            if plan:
                self._flush_ragged_plan()
            self._try_admit()
            return None
        # 2. grow pages (allocate-on-write), preempting on OOM.
        #    Oldest first: under pressure the young yield to the old.
        order = sorted(np.flatnonzero(stepping),
                       key=lambda s: self._requests[s].admit_seq)
        for slot in order:
            slot = int(slot)
            self._grow_or_shed(slot, self._requests[slot],
                               int(self.lens[slot]) + 1)
        stepping &= self.active     # growth may have evicted some
        if not stepping.any():
            if plan:
                self._flush_ragged_plan()
            self._try_admit()
            return None
        # 3. record the inputs being consumed (re-prefill history) —
        #    a Tensor ref + mask snapshot only; the device->host read
        #    is deferred to _flush_history (next drop/preempt, or the
        #    periodic bound below so long-lived batches don't pin an
        #    unbounded window of input buffers)
        if len(self._pending_history) >= 32:
            self._flush_history()
        # 3.5 sanitize: non-stepping rows may carry ANY caller values —
        #     including the NaN row of a previously failed slot fed
        #     back verbatim. They scatter k/v into the SHARED trash
        #     block, and a NaN there would poison every sequence's
        #     masked attention tail (an additive -1e30 mask cannot
        #     cancel NaN), so they are zeroed on-device first —
        #     unconditionally, to keep the "inactive rows: any
        #     values" contract sound (bitwise no-op for stepping rows)
        x = self._sanitize_masked_rows(x, stepping)
        self._pending_history.append((x, stepping.copy()))
        # 4. fused ragged step over the paged views; mid-prefill and
        #    freshly admitted slots present all-trash tables so the
        #    decode append cannot touch their pages
        masked = self.prefilling | (self.active & ~stepping)
        self.cache.set_decode_mask(masked if masked.any() else None)
        if col is not None:
            col.phase("model")
        if plan:
            # the step's planned prefill chunks and the fused decode
            # rows in ONE packed model call — one paged-attention
            # launch per layer on the kernel path
            out = self._flush_ragged_plan(x=x)
        else:
            t = Tensor(np.asarray(self.lens, np.int32))
            with no_grad():
                out, _ = self.model(x, caches=self.cache.views,
                                    time_step=t)
        if self.injector is not None:
            out = self.injector.corrupt_hidden(out)
        if col is not None:
            col.phase("bookkeeping")
        self.lens[stepping] += 1
        self._count_tokens_served(stepping, 1)
        if col is not None:
            col.on_decode([self._requests[int(s)].rid
                           for s in np.flatnonzero(stepping)
                           if self._requests[int(s)] is not None], 1)
        if self.ledger is not None:
            # the consumed row's absolute position (pre-increment len)
            self.ledger.on_decode(
                [(self._requests[int(s)].rid, int(self.lens[s]) - 1)
                 for s in np.flatnonzero(stepping)
                 if self._requests[int(s)] is not None], 1)
        self.prefill_stats.decode_steps += 1
        if ran_prefill:
            self.prefill_stats.mixed_steps += 1
        # decode-phase allocate-on-write growth moves the high-water
        # mark too, not just prefill chunks
        self.prefill_stats.peak_blocks = max(
            self.prefill_stats.peak_blocks, self.cache.peak_blocks_used)
        if self.numeric_guard:
            self._guard_numeric(out, stepping)
        # 5. continuous refill
        if col is not None:
            col.phase("admission")
        self._try_admit()
        return out

    # -- speculative decode (multi-token verify + rollback) -----------
    def step_multi(self, x: Tensor):
        """One fused MULTI-TOKEN step for every active slot: row b's L
        tokens are appended at positions lens[b] .. lens[b]+L-1 and
        scored causally in ONE model call — the speculative-decode
        verification step (inference/speculative.py). x: [max_batch,
        L, d_model]. The caller guarantees lens + L <= capacity for
        every active slot (clamp L; slots AT capacity must be released
        first) — unlike ``step`` there is no auto-release here, since
        a capacity-finished slot cannot ride a multi-token call at
        all. Page growth covers all L positions (preempting youngest
        on OOM, as in ``step``); ``rollback`` drops the rejected tail.
        Returns hidden [max_batch, L, d_model].

        COMPOSES with ``prefill_token_budget`` (the PR 10 residual):
        the step first spends the budget advancing pending prefill
        chunks — packed WITH the verify rows into one ragged launch on
        the kernel path (the ragged kernel and ``ragged_views`` speak
        mixed q_lens natively) — and slots mid-prefill, or whose
        prefill completed within this very step, sit the verify out
        exactly as they sit out ``step``'s decode: their rows of x are
        sanitized, their tables present as trash, their lens do not
        advance, and their admitted event fires for the NEXT round's
        pending token. May return None while prompts are still
        streaming with no verifiable slot."""
        L = int(x.shape[1])
        idle = self._begin_step(kind="verify")
        ok = False
        try:
            if not self._ragged_active():
                out = self._step_multi_impl(idle, x, L)
            else:
                self._ragged_plan = []
                try:
                    out = self._step_multi_impl(idle, x, L)
                finally:
                    self._ragged_plan = None
            ok = True
            return out
        finally:
            self._end_step_telemetry(aborted=not ok)

    def _step_multi_impl(self, idle: bool, x: Tensor, L: int):
        col = self.collector
        plan = self._ragged_plan
        # token-budget mode: spend the prefill budget first (eagerly,
        # or into the ragged plan), exactly like _step_body
        if col is not None:
            col.phase("prefill")
        if plan is None:
            ran_prefill, fresh = self._advance_prefills()
        else:
            ran_prefill, fresh = self._plan_prefills()
        if col is not None:
            col.phase("bookkeeping")
        if self.num_active == 0:
            if ran_prefill or self.num_prefilling > 0 \
                    or self._queue_len or not idle:
                # deadline failures can empty the batch mid-stream;
                # the caller sees None + the outcome events, never an
                # exception
                if plan:
                    self._flush_ragged_plan()
                self._try_admit()
                return None
            raise RuntimeError("step_multi() with no active slots")
        # slots whose prefill completed within THIS step sit the
        # verify out (their admitted event is undrained — their rows
        # of x are garbage), same contract as _step_body
        stepping = self.active.copy()
        for slot in fresh:
            stepping[slot] = False
        if not stepping.any():
            if plan:
                self._flush_ragged_plan()
            self._try_admit()
            return None
        over = stepping & (self.lens + L > self.max_len)
        if over.any():
            if plan:
                # the planning pass already transitioned prefill state
                # (positions, stats, completions): flush the recorded
                # chunks so their pages exist before unwinding, or the
                # caller's retry would decode against prompts the
                # scheduler believes were written
                self._flush_ragged_plan()
            raise ValueError(
                f"slots {np.flatnonzero(over).tolist()} cannot take "
                f"{L} tokens within capacity {self.max_len}; clamp L "
                f"or release them first")
        # grow pages to cover the whole write range, oldest first
        order = sorted(np.flatnonzero(stepping),
                       key=lambda s: self._requests[s].admit_seq)
        for slot in order:
            slot = int(slot)
            self._grow_or_shed(slot, self._requests[slot],
                               int(self.lens[slot]) + L,
                               write_from=int(self.lens[slot]))
        stepping &= self.active     # growth may have evicted some
        if not stepping.any():
            if plan:
                self._flush_ragged_plan()
            self._try_admit()
            return None
        if len(self._pending_history) >= 32:
            self._flush_history()
        # see step(): a NaN fed for an inactive row must not reach the
        # shared trash block (zeroed unconditionally, bitwise no-op
        # for active rows)
        x = self._sanitize_masked_rows(x, stepping)
        self._pending_history.append((x, stepping.copy()))
        masked = self.prefilling | (self.active & ~stepping)
        self.cache.set_decode_mask(masked if masked.any() else None)
        if col is not None:
            col.phase("model")
        if plan:
            # the step's planned prefill chunks and the L-row verify
            # packed into ONE ragged model call
            out = self._flush_ragged_plan(x=x, L=L)
        else:
            t = Tensor(np.asarray(self.lens, np.int32))
            with no_grad():
                out, _ = self.model(x, caches=self.cache.views,
                                    time_step=t)
        if self.injector is not None:
            out = self.injector.corrupt_hidden(out)
        if col is not None:
            col.phase("bookkeeping")
        self.lens[stepping] += L
        self._count_tokens_served(stepping, L)
        if col is not None:
            col.on_decode([self._requests[int(s)].rid
                           for s in np.flatnonzero(stepping)
                           if self._requests[int(s)] is not None], L)
        if self.ledger is not None:
            # L verified rows per slot at positions [len-L, len)
            self.ledger.on_decode(
                [(self._requests[int(s)].rid, int(self.lens[s]) - L)
                 for s in np.flatnonzero(stepping)
                 if self._requests[int(s)] is not None], L)
        self.prefill_stats.decode_steps += 1
        if ran_prefill:
            self.prefill_stats.mixed_steps += 1
        self.prefill_stats.peak_blocks = max(
            self.prefill_stats.peak_blocks, self.cache.peak_blocks_used)
        if self.numeric_guard:
            self._guard_numeric(out, stepping)
        if col is not None:
            col.phase("admission")
        self._try_admit()
        return out

    def rollback(self, slot: int, new_len: int) -> None:
        """Roll an active slot back to ``new_len`` consumed tokens
        (speculative rejection): the pages past the boundary are
        released block-table-tail-first (refcount/cached-free aware —
        PagedKVCache.truncate), the recorded history is trimmed so a
        later preempt -> re-prefill replays only ACCEPTED tokens, and
        the slot keeps decoding from ``new_len``."""
        if not self.active[slot]:
            raise ValueError(f"slot {slot} not active")
        new_len = int(new_len)
        if new_len < 1 or new_len > int(self.lens[slot]):
            raise ValueError(
                f"rollback of slot {slot} to {new_len} outside "
                f"[1, {int(self.lens[slot])}]")
        rejected = int(self.lens[slot]) - new_len
        # buffered inputs must reach the history BEFORE trimming it
        self._flush_history()
        self._requests[slot].truncate_history(new_len,
                                              self.cache.block_size)
        self.cache.truncate(slot, new_len)
        old_len = new_len + rejected
        self.lens[slot] = new_len
        if self.collector is not None and rejected > 0:
            self.collector.on_rollback(self._requests[slot].rid,
                                       rejected)
        if self.ledger is not None and rejected > 0:
            self.ledger.on_rollback(self._requests[slot].rid,
                                    new_len, old_len)

    # -- resilience ---------------------------------------------------
    def _crash(self, phase: str) -> None:
        """Consult the injector's crash schedule (CrashInjector): a
        scheduled hit raises EngineCrash OUT of the engine, simulating
        process death mid-step — recovery rebuilds from snapshot +
        journal (inference/recovery.py). No-op for a plain
        FaultInjector and zero overhead with no injector at all."""
        if self.injector is not None:
            self.injector.crash_point(phase)

    def _begin_step(self, kind: str = "step") -> bool:
        """Step-top bookkeeping shared by step()/step_multi():
        advance the step counter (the fault injector's clock) and
        enforce per-request deadlines. Returns whether the engine was
        ALREADY empty on entry — that is caller misuse and still
        raises, while an engine emptied by this step's own failures
        returns None to the caller. Opens the telemetry step span
        LAST (after the ``begin`` crash point), so a step that dies
        at its top never leaves a dangling span."""
        self._step_count += 1
        if self.injector is not None:
            self.injector.begin_step(self._step_count)
            self.injector.crash_point("begin")
        idle = self.num_active == 0 and self.num_prefilling == 0 \
            and not self._queue_len
        self._check_deadlines()
        for tid, ten in self.tenants.items():
            ten.stats.blocks_held = self.cache.tenant_charge(tid)
        if self.collector is not None:
            self.collector.begin_step(self._step_count, kind)
        return idle

    def _queue_gauges(self) -> dict:
        """Queue/slot depths — the ONE source feeding both the
        registry's ``queue`` namespace and the per-step gauge track."""
        return {"depth": self._queue_len,
                "active": self.num_active,
                "prefilling": self.num_prefilling}

    def _end_step_telemetry(self, aborted: bool = False) -> None:
        """Close the step span and sample the per-step gauges from
        ground truth (pool tiers, queue/slot depths, per-tenant
        charge), then hand the step to the health monitor. One call,
        in the step's ``finally`` — the timeline stays balanced even
        when a fault or injected crash unwinds the step early
        (``aborted``); the MONITOR skips aborted steps (a torn step
        is not a step-boundary state — it replays after recovery or
        the engine is abandoned, so sampling it would diverge the
        series from an uninterrupted run's)."""
        col = self.collector
        charges = None
        if not aborted and (col is not None or
                            self.ledger is not None):
            # ONE per-tenant charge walk shared by the collector's
            # gauge track and the ledger's block-step bill. Unlike
            # the occupancy blocks-per-tenant histogram (which drops
            # zeros), this reports every REGISTERED tenant — a
            # charge falling to 0 must emit a 0, not vanish
            charges = {tid: self.cache.tenant_charge(tid)
                       for tid in self.tenants}
        if col is not None:
            if aborted:
                # close the torn step's span flagged; no gauges — the
                # mid-crash state is not a step-boundary sample
                col.end_step(aborted=True)
            else:
                # the ONE tier source, O(1) scalars only — per-step
                # gauges must not pay the occupancy histograms'
                # O(max_seqs) scan
                occ = self.cache.pool_occupancy(tiers_only=True)
                col.end_step({
                    "pool": {"active": occ["active"],
                             "cached_free": occ["cached_free"],
                             "free": occ["free"]},
                    "queue": self._queue_gauges(),
                    "tenant_blocks": charges,
                })
        if self.ledger is not None:
            if aborted:
                # a torn step is not a billing boundary: drop its
                # partial work-log sample (event tallies stand)
                self.ledger.on_step_abort()
            else:
                # block-step billing integrates the per-tenant charge
                # at every completed step boundary; the collector's
                # registry rides along so the ledger can pair the
                # step's analytic work with its measured model-span
                # duration (MFU/MBU)
                self.ledger.on_step(
                    self._step_count, charges,
                    span_src=(col.registry if col is not None
                              else None))
        if self.monitor is not None and not aborted:
            self.monitor.on_step(self._step_count)

    def _count_tokens_served(self, stepping: np.ndarray,
                             n: int) -> None:
        """Attribute this fused call's consumed decode tokens to the
        stepping slots' tenants (the per-tenant throughput signal)."""
        for slot in np.flatnonzero(stepping):
            req = self._requests[int(slot)]
            if req is not None:
                self._tenant_of(req).stats.tokens_served += n

    def _grow_or_shed(self, slot: int, req: PagedRequest, length: int,
                      *, start_block: int = 0,
                      write_from: Optional[int] = None) -> bool:
        """Cover ``length`` tokens for ``slot`` (allocate-on-write +
        COW split), preempting the youngest ELIGIBLE request on
        pressure — possibly the grower itself (it then re-queues for
        re-prefill). The ONE eviction/shed policy behind decode
        growth, multi-token growth and chunked-prefill growth;
        returns True when the slot is still alive (and covered).

        Tenant-aware pressure handling, checked in order:

          1. TENANT QUOTA: growth past the tenant's block cap evicts
             the tenant's OWN youngest; with nothing of its own left
             to evict the grower is SHED (FAILED_OOM naming the
             quota) — a neighbor never pays for a flooder's cap.
          2. RESERVED FLOORS: a tenant at-or-over its own floor may
             not dip the free pool below other tenants' unmet floors;
             it evicts within itself, or (sole member) self-evicts
             and waits queued — floor pressure is transient (it
             clears when the entitled tenant charges up), so the
             grower is preempted, not shed.
          3. POOL OOM: victims come from ``_oom_victims`` (the
             grower's own tenant; over-floor borrowers when the
             grower is below its floor). Pool dry with no eligible
             victim but the grower itself -> SHED, as before.
        """
        if not (self.active[slot] or self.prefilling[slot]):
            return False    # already evicted growing an earlier slot
        ten = self._tenant_of(req)
        while self.active[slot] or self.prefilling[slot]:
            need_new = self.cache.blocks_needed(length) \
                - len(self.cache.seq_blocks[slot])
            if need_new > 0 and ten.quota_blocks is not None and \
                    self.cache.tenant_charge(ten.tid) + need_new \
                    > ten.quota_blocks:
                ten.stats.quota_hits += 1
                own = [int(s) for s in
                       np.flatnonzero(self.active | self.prefilling)
                       if self._requests[int(s)] is not None
                       and self._requests[int(s)].tenant == ten.tid]
                if len(own) <= 1:
                    self._fail(req, RequestOutcome.FAILED_OOM,
                               f"tenant {ten.tid!r} block quota "
                               f"({ten.quota_blocks}) exhausted: "
                               f"{self.cache.tenant_charge(ten.tid)} "
                               f"held + {need_new} needed")
                else:
                    self._preempt_youngest(own)
                continue
            if need_new > 0 and \
                    self.cache.tenant_charge(ten.tid) \
                    >= ten.reserved_blocks:
                unmet = self._unmet_floors(exclude=ten.tid)
                if unmet and self.free_blocks - need_new < unmet:
                    own = [int(s) for s in
                           np.flatnonzero(self.active
                                          | self.prefilling)
                           if self._requests[int(s)] is not None
                           and self._requests[int(s)].tenant == ten.tid]
                    # sole member: self-evict and wait queued (the
                    # floor clears when its owner charges up); with
                    # peers, the tenant's youngest yields
                    self._preempt_youngest(own)
                    continue
            try:
                self.cache.ensure(slot, length, start_block=start_block,
                                  write_from=write_from)
                return True
            except BlockOOM as e:
                if self.collector is not None:
                    # every pool OOM is a telemetry instant carrying
                    # the structured occupancy breakdown (who held
                    # the pool when it fired)
                    self.collector.on_event("block_oom", dict(
                        e.details, rid=req.rid, tenant=req.tenant,
                        step=self._step_count))
                # shed only when no victim but the grower itself is
                # left: the below-floor branch of _oom_victims returns
                # over-floor BORROWERS, a list that never contains the
                # grower — a single entry there is still an eviction
                # the floor guarantee promises, not a dead end
                cands = self._oom_victims(req)
                if not any(s != slot for s in cands):
                    self._fail(req, RequestOutcome.FAILED_OOM,
                               f"pool exhausted even after preempting "
                               f"every eligible request: {e}")
                else:
                    self._preempt_youngest(cands)
        return False

    def _sanitize_masked_rows(self, x, stepping: np.ndarray):
        """Zero the rows of ``x`` that are NOT stepping this call, on
        device (one fused where, no host sync). Stepping rows pass
        through BITWISE unchanged; non-stepping rows' (ignored) trash-
        block writes become finite, so one request's NaN can never
        leak into another's masked attention tail."""
        import jax.numpy as jnp
        mask = jnp.asarray(stepping.reshape(-1, 1, 1))
        return Tensor(jnp.where(mask, x.data,
                                jnp.zeros((), x.data.dtype)))

    def _guard_numeric(self, out, stepping: np.ndarray) -> None:
        """Per-slot numeric guard: one [B]-bool reduction on device,
        one small host read. A non-finite value in a slot's output row
        fails THAT request (FAILED_NUMERIC — its K/V pages may be
        poisoned, so they are quarantined: freed with their prefix
        index entries dropped, no cached-free second chance) and the
        step stands for every other slot; attention is per-row, so a
        NaN cannot cross slots inside the fused call."""
        import jax.numpy as jnp
        finite = np.asarray(jnp.isfinite(out.data)
                            .reshape(out.shape[0], -1).all(axis=1))
        bad = stepping & ~finite
        for slot in np.flatnonzero(bad):
            req = self._requests[int(slot)]
            if req is None:
                continue
            self._fail(req, RequestOutcome.FAILED_NUMERIC,
                       f"non-finite hidden in slot {int(slot)} at "
                       f"step {self._step_count}")

    def check_invariants(self) -> bool:
        """Audit engine + pool bookkeeping (see PagedKVCache.
        check_invariants for the pool-level list); raises
        AssertionError on violation. Engine-level: every active or
        prefilling slot maps to a request that points back at it,
        queued requests hold no slot, and every active slot's table
        covers its length. Run it after every step under the test
        suite's ``--audit-invariants`` flag, or from a serving loop's
        debug path."""
        reserved = self.groups.reserved_slots()
        for slot in np.flatnonzero(self.active | self.prefilling):
            if int(slot) in reserved:
                # branch-slot reservation of an unforked group lead:
                # held (prefilling) but deliberately requestless
                assert self.prefilling[int(slot)] and \
                    self._requests[int(slot)] is None and \
                    int(slot) not in self._prefills and \
                    self.lens[int(slot)] == 0, \
                    f"reserved branch slot {int(slot)} inconsistent"
                continue
            req = self._requests[int(slot)]
            assert req is not None and req.slot == int(slot), \
                f"slot {int(slot)} active without a matching request"
        for req in self.queue:
            assert req.slot is None, \
                f"queued request {req.rid} still holds slot {req.slot}"
        assert not (self.active & self.prefilling).any(), \
            "slot both active and prefilling"
        for slot in self._prefills:
            assert self.prefilling[slot], \
                f"prefill state for non-prefilling slot {slot}"
        # tenant layer: every live request's tenant is registered, the
        # cache's slot attribution mirrors the engine's, and no tenant
        # sits past its quota (enforcement gates every growth path;
        # set_tenant refuses quotas below the current charge)
        for slot in np.flatnonzero(self.active | self.prefilling):
            req = self._requests[int(slot)]
            if req is None:        # reserved branch slot (audited above)
                continue
            assert req.tenant in self.tenants, \
                f"slot {int(slot)} request of unknown tenant " \
                f"{req.tenant!r}"
            assert self.cache.seq_tenant[int(slot)] == req.tenant, \
                (f"slot {int(slot)} cache attribution "
                 f"{self.cache.seq_tenant[int(slot)]!r} != request "
                 f"tenant {req.tenant!r}")
        queued_by_tenant: Dict[str, int] = {}
        for r in self.queue:
            assert r.tenant in self.tenants, \
                f"queued request {r.rid} of unknown tenant {r.tenant!r}"
            queued_by_tenant[r.tenant] = \
                queued_by_tenant.get(r.tenant, 0) + 1
        total_q = 0
        for tid, ten in self.tenants.items():
            assert ten.queued == queued_by_tenant.get(tid, 0) \
                == len(ten.fifo), \
                (f"tenant {tid!r} queued gauge {ten.queued} != "
                 f"{queued_by_tenant.get(tid, 0)} request(s) actually "
                 f"queued (sub-queue holds {len(ten.fifo)})")
            total_q += len(ten.fifo)
            # sub-queue internal order follows the global merge key
            # (preempted by rid, then fresh by enqueue order)
            keys = [self._queue_key(r) for r in ten.fifo]
            assert keys == sorted(keys), \
                (f"tenant {tid!r} sub-queue out of admission order: "
                 f"{[r.rid for r in ten.fifo]}")
            assert all(r.tenant == tid for r in ten.fifo), \
                f"foreign request in tenant {tid!r} sub-queue"
            if ten.quota_blocks is not None:
                held = self.cache.tenant_charge(tid)
                assert held <= ten.quota_blocks, \
                    (f"tenant {tid!r} holds {held} block(s) over its "
                     f"quota {ten.quota_blocks}")
        assert self._queue_len == total_q, \
            (f"queue depth gauge {self._queue_len} != {total_q} "
             f"request(s) across the sub-queues")
        self._audit_groups()
        self.cache.check_invariants(lens=self.lens, active=self.active)
        self.resilience_stats.audits += 1
        return True

    def _audit_groups(self) -> None:
        """Fork-shared page audit: for every live group, every pool
        block's MULTIPLICITY across the member slots' block tables is
        covered by the allocator's refcount (each branch-table
        reference holds one count — the one-charge-per-reference
        policy made physical). ``>=`` not ``==``: the prefix cache and
        the cached-free index may hold further legitimate references
        on top of the group's own. Also audits the group records
        themselves: members map back to the group, reserved slots are
        requestless holders, and only unforked groups reserve."""
        by_slot = {r.rid: s for s, r in enumerate(self._requests)
                   if r is not None}
        for gid, g in self.groups.groups.items():
            assert g["rids"][0] == gid, \
                f"group {gid} lead rid mismatch: {g['rids']}"
            assert set(g["live"]) <= set(g["rids"]), \
                f"group {gid} live set exceeds its members"
            if g["forked"]:
                assert not g["reserved"], \
                    f"forked group {gid} still holds reserved slots"
            slots = [by_slot[rid] for rid in g["rids"]
                     if rid in by_slot]   # queued / preempted /
            if not slots:                 # terminal members hold no
                continue                  # table to audit
            rep = self.cache.share_report(slots)
            for b, m in rep["multiplicity"].items():
                assert rep["refcount"][b] >= m, \
                    (f"group {gid}: block {b} referenced by {m} member "
                     f"table(s) but refcount is {rep['refcount'][b]}")

    # -- page migration (disaggregated serving) -----------------------
    def export_request_slice(self, rid: int) -> Optional[dict]:
        """Migration export (inference/router.py): the wire-format
        slice of ``rid``'s finished prefix pages — its chain-hash
        identity paired with the pool blocks that hold them
        (``PagedKVCache.export_slice``). Only pages a different pool
        could ADOPT ride along: full blocks the slot has actually
        computed (an active slot's decoded extent, a mid-prefill
        slot's chunk frontier). Returns None when the request is
        unknown, still queued, or holds no full block yet — the
        router then migrates cold (plain resubmission). A pure read:
        no allocator or scheduler state moves."""
        self._flush_history()
        req = None
        for r in self._requests:
            if r is not None and r.rid == rid:
                req = r
                break
        if req is None or req.slot is None:
            return None
        slot = int(req.slot)
        if self.prefilling[slot]:
            covered = int(self._prefills[slot]["pos"])
        else:
            covered = int(self.lens[slot])
        n_full = covered // self.cache.block_size
        if n_full <= 0:
            return None
        hashes = req.block_hashes(self.cache.block_size)[:n_full]
        if not hashes:
            return None
        return self.cache.export_slice(slot, hashes)

    def import_slice(self, slc: dict) -> int:
        """Adopt a migrated slice into this engine's pool
        (``PagedKVCache.import_slice``): pages land cached-free +
        hash-indexed, so the migrated request's resubmission hits
        them through the normal prefix-cache admission path."""
        return self.cache.import_slice(slc)

    # -- checkpoint / restore -----------------------------------------
    @staticmethod
    def _stats_rec(st) -> dict:
        return {name: getattr(st, name) for name in st.__slots__}

    @staticmethod
    def _stats_set(st, rec: dict) -> None:
        for name, v in rec.items():
            setattr(st, name, v)

    def _req_rec(self, req: PagedRequest, now: float) -> dict:
        """Picklable record of one request. Wall-clock deadlines are
        stored as REMAINING seconds at snapshot time — the monotonic
        clock does not survive a process, so restore re-bases them."""
        return {
            "rid": req.rid,
            "history": np.array(req.history, np.float32, copy=True),
            "hashes": list(req._hashes),
            "slot": req.slot,
            "admit_seq": req.admit_seq,
            "preemptions": req.preemptions,
            "max_preemptions": req.max_preemptions,
            "deadline_steps": req.deadline_steps,
            "deadline_remaining": (None if req.deadline_time is None
                                   else req.deadline_time - now),
            "submit_step": req.submit_step,
            "tenant": req.tenant,
            "gid": req.gid,
            "branch": req.branch,
            "group_n": req.group_n,
        }

    def snapshot(self) -> dict:
        """Checkpoint EVERYTHING a restored engine needs to continue
        bit-identically: the pool snapshot (PagedKVCache.snapshot),
        every live request (queued, mid-prefill and running — history,
        memoized chain hashes, retry/deadline budgets), queue order,
        per-slot state (lens/active/prefilling + mid-chunk prefill
        frontiers), the step clock and admission sequencer, all stats
        siblings, and any undrained event lists. Buffered decode
        inputs are flushed to histories first, so the snapshot is a
        pure host-side read of a step-boundary state. Telemetry
        (``collector``) is deliberately EXCLUDED: its wall-clock
        timestamps are observational, never behavioral, so restore
        wires the caller's collector fresh instead of replaying
        stale clocks into a new process."""
        self._flush_history()
        now = time.monotonic()
        reqs: Dict[int, PagedRequest] = {
            r.rid: r for r in list(self.queue)
            + [q for q in self._requests if q is not None]}
        return {
            "kind": "paged_engine",
            "config": {
                "max_batch": self.max_batch,
                "block_size": self.cache.block_size,
                "num_blocks": self.cache.num_blocks,
                "max_blocks_per_seq": self.cache.max_blocks_per_seq,
                "dtype": self.dtype,
                "watermark_blocks": self.watermark_blocks,
                "prefix_cache": self.prefix_cache,
                "chunk_tokens": self.chunk_tokens,
                "prefill_token_budget": self.prefill_token_budget,
                "max_preemptions": self.max_preemptions,
                "numeric_guard": self.numeric_guard,
                "ragged_step": self.ragged_step,
                "tile_q": self.tile_q,
                "tile_kv": self.tile_kv,
            },
            "cache": self.cache.snapshot(),
            "requests": [self._req_rec(r, now) for r in reqs.values()],
            "queue": [r.rid for r in self.queue],
            "slot_rids": [None if r is None else r.rid
                          for r in self._requests],
            "lens": self.lens.copy(),
            "active": self.active.copy(),
            "prefilling": self.prefilling.copy(),
            "prefills": {int(s): {"pos": st["pos"], "start": st["start"],
                                  "n_cached": st["n_cached"],
                                  "hashes": list(st["hashes"])}
                         for s, st in self._prefills.items()},
            "counters": {"next_rid": self._next_rid,
                         "next_admit_seq": self._next_admit_seq,
                         "step_count": self._step_count,
                         "has_deadlines": self._has_deadlines},
            "groups": self.groups.snapshot(),
            # tenant isolation state: configs, WFQ virtual times (the
            # list order IS the registration order — the WFQ
            # tie-break), and per-tenant stats; restore rebuilds the
            # registry so quotas/weights/fairness continue exactly
            "tenants": [{"id": t.tid,
                         "quota_blocks": t.quota_blocks,
                         "reserved_blocks": t.reserved_blocks,
                         "weight": t.weight,
                         "vtime": t.vtime,
                         "stats": self._stats_rec(t.stats)}
                        for t in self.tenants.values()],
            "vclock": self._vclock,
            "stats": {"prefix": self._stats_rec(self.prefix_stats),
                      "prefill": self._stats_rec(self.prefill_stats),
                      "resilience":
                          self._stats_rec(self.resilience_stats),
                      "parallel":
                          self._stats_rec(self.parallel_stats)},
            "events": {
                "admitted": [(rid, slot,
                              None if h is None
                              else np.asarray(h.numpy()))
                             for rid, slot, h in self.admitted],
                "finished": list(self.finished),
                "preempted": list(self.preempted),
            },
            "outcomes": [oc.as_dict() for oc in self.outcomes],
        }

    @classmethod
    def restore(cls, model, snap: dict, *, injector=None,
                collector=None, monitor=None, ledger=None,
                num_blocks: Optional[int] = None) -> "PagedServingEngine":
        """Rebuild an engine from a ``snapshot`` around the caller's
        model (weights are the caller's problem — a snapshot holds
        serving state, not parameters). ``num_blocks`` rehomes the
        pool into a different-size target (PagedKVCache.restore).
        The injector is wired fresh (fault schedules stay keyed by
        the RESTORED step clock, so a replayed step re-injects the
        same faults — required for deterministic replay), and so is
        the collector: snapshots carry NO telemetry state (wall-clock
        timestamps never enter engine-behavioral state), the caller's
        collector simply keeps observing the restored engine. Ends
        with a full engine + deep pool audit."""
        cfg = snap["config"]
        nb = cfg["num_blocks"] if num_blocks is None else int(num_blocks)
        # the constructor's cache is discarded two lines down for the
        # restored one — build it with a 2-block placeholder pool so
        # recovery never holds two full pools at once (a production
        # pool is sized near device memory; 2x there would OOM the
        # recovery path itself). Geometry that outlives the swap
        # (max_len) comes from max_blocks_per_seq, which is passed
        # resolved, and is re-derived from the restored cache below.
        eng = cls(model, cfg["max_batch"], cfg["block_size"], 2,
                  max_blocks_per_seq=cfg["max_blocks_per_seq"],
                  dtype=cfg["dtype"],
                  watermark_blocks=cfg["watermark_blocks"],
                  prefix_cache=cfg["prefix_cache"],
                  chunk_tokens=cfg["chunk_tokens"],
                  prefill_token_budget=cfg["prefill_token_budget"],
                  injector=injector, collector=collector,
                  monitor=monitor, ledger=ledger,
                  max_preemptions=cfg["max_preemptions"],
                  numeric_guard=cfg["numeric_guard"],
                  # pre-ragged snapshots restore onto the (equivalent)
                  # ragged default; the knobs are scheduling-neutral
                  ragged_step=cfg.get("ragged_step", True),
                  tile_q=cfg.get("tile_q"),
                  tile_kv=cfg.get("tile_kv"))
        # nb may differ from the cache snapshot's geometry (a resized
        # engine config, or the explicit override): the pool restore
        # rehomes content-addressed blocks either way. The MESH WIDTH
        # comes from the CALLER'S MODEL, not the snapshot — the pool
        # payload is canonical (full-head pages), so a snapshot taken
        # on an mp=N fleet restores behind a single-chip model and
        # vice versa (tensor-parallel snapshot portability)
        eng.cache = PagedKVCache.restore(
            snap["cache"], num_blocks=nb,
            mp=getattr(model, "mp", 1),
            shard_devices=getattr(model, "shard_devices", None))
        if injector is not None:
            eng.cache.allocator.fault_hook = \
                lambda n: injector.on_alloc("target", n)
        eng.max_len = eng.cache.capacity_per_seq
        now = time.monotonic()
        # tenant registry (version-gated: pre-tenant snapshots carry
        # no "tenants" key and restore to the implicit default-only
        # registry the constructor already built)
        for trec in snap.get("tenants", []):
            ten = Tenant(trec["id"],
                         quota_blocks=trec["quota_blocks"],
                         reserved_blocks=trec["reserved_blocks"],
                         weight=trec["weight"])
            ten.vtime = trec["vtime"]
            cls._stats_set(ten.stats, trec["stats"])
            eng.tenants[ten.tid] = ten
        eng._vclock = snap.get("vclock", 0.0)
        reqs: Dict[int, PagedRequest] = {}
        for rec in snap["requests"]:
            req = PagedRequest(rec["rid"], rec["history"])
            req._hashes = list(rec["hashes"])
            req.tenant = rec.get("tenant", DEFAULT_TENANT)
            req.slot = rec["slot"]
            req.admit_seq = rec["admit_seq"]
            req.preemptions = rec["preemptions"]
            req.max_preemptions = rec["max_preemptions"]
            req.deadline_steps = rec["deadline_steps"]
            if rec["deadline_remaining"] is not None:
                req.deadline_time = now + rec["deadline_remaining"]
            req.submit_step = rec["submit_step"]
            # pre-group snapshots carry no branch fields: they restore
            # as the lone streams they were
            req.gid = rec.get("gid")
            req.branch = rec.get("branch", 0)
            req.group_n = rec.get("group_n", 1)
            reqs[req.rid] = req
        eng._requests = [None if rid is None else reqs[rid]
                         for rid in snap["slot_rids"]]
        # reconcile the pool's slot attribution with the requests —
        # a no-op for tenant-era snapshots, and the version gate that
        # lifts a pre-tenant snapshot's unattributed slots onto the
        # implicit default tenant (charge moves with them)
        for slot, r in enumerate(eng._requests):
            if r is not None:
                eng.cache.set_seq_tenant(slot, r.tenant)
        # re-shard the snapshot's global queue-order list into the
        # per-tenant FIFO sub-queues: the saved order is merged-key
        # order, so per-tenant suborder is preserved by appending in
        # sequence (enqueue seqs are reassigned monotonically — only
        # their relative order is behavioral)
        for rid in snap["queue"]:
            r = reqs[rid]
            eng._resolve_tenant(r.tenant)   # auto-register if needed
            eng._enqueue(r)
        eng.lens = np.array(snap["lens"], np.int32)
        eng.active = np.array(snap["active"], bool)
        eng.prefilling = np.array(snap["prefilling"], bool)
        eng._prefills = {int(s): dict(st)
                         for s, st in snap["prefills"].items()}
        c = snap["counters"]
        eng._next_rid = c["next_rid"]
        eng._next_admit_seq = c["next_admit_seq"]
        eng._step_count = c["step_count"]
        eng._has_deadlines = c["has_deadlines"]
        # branch groups (version-gated: pre-group snapshots restore
        # to an empty table)
        eng.groups.restore(snap.get("groups", {}))
        cls._stats_set(eng.prefix_stats, snap["stats"]["prefix"])
        cls._stats_set(eng.prefill_stats, snap["stats"]["prefill"])
        cls._stats_set(eng.resilience_stats,
                       snap["stats"]["resilience"])
        cls._stats_set(eng.parallel_stats,
                       snap["stats"].get("parallel", {}))
        ev = snap["events"]
        eng.admitted = [(rid, slot,
                         None if h is None else Tensor(h))
                        for rid, slot, h in ev["admitted"]]
        eng.finished = list(ev["finished"])
        eng.preempted = list(ev["preempted"])
        eng.outcomes = [RequestOutcome(**oc) for oc in snap["outcomes"]]
        eng.check_invariants()
        if monitor is not None:
            # monitor state is DERIVED, never snapshotted: a fresh
            # monitor re-baselines its interval-delta snapshot at the
            # restored step (counters restore exactly, so resampling
            # the replay reproduces the dead incarnation's samples);
            # a monitor that lived through the crash keeps its live
            # history — rebase is a no-op for it
            monitor.rebase(eng._step_count)
        return eng
