"""MoE decode serving: routed expert FFN behind the FusedMultiTransformer
cache protocol.

``MoeServingCore`` subclasses FusedMultiTransformer and overrides exactly
one seam — ``_ffn_block`` — so the attention schedule, the three cache
branches (dense preallocated / paged decode / ragged packed prefill) and
every page/snapshot/journal invariant are inherited unchanged. A MoE
``TokenServingModel`` therefore drops into every engine mode (paged,
prefix-cached, speculative, chunked-prefill, recoverable, tenant-quota'd)
by construction: the engines only ever see the cache protocol.

Per layer the FFN becomes (GShard token-choice routing, ref
incubate/moe.py MoELayer and arxiv 2006.16668):

    gate logits -> softmax -> top-k -> capacity-position assignment
    -> dispatch to experts -> grouped expert FFN -> weighted combine

Two dispatch paths compute the same function:

* **CPU reference** (default off-TPU): a per-expert einsum loop. Every
  expert runs over all N rows and the result is multiplied by the
  capacity-respecting combine weight column — an EXACT zero for every
  (token, expert) pair that is not routed or that overflowed capacity.
  The output is a left-fold ``out += y_e * w[:, e]`` in ascending expert
  order: static shapes, no data-dependent gathers, bit-reproducible.
* **kernel path** (TPU, or ``use_kernel=True`` anywhere for parity
  testing): tokens are scattered into a static capacity layout
  ``[E * cap_pad, d]`` (expert e's rows live at ``e * cap_pad + pos``)
  and the expert FFN runs as two ``ops.pallas.grouped_gemm.gmm`` calls
  over expert-stacked weights ``[E, ...]``. The combine gathers each
  token's k rows back and folds them in ascending expert order so the
  summation association matches the reference fold exactly. The layout
  is fully static (capacity positions, not sorted prefix offsets), which
  is the shape the compiled-step path (inference/compiled_step.py) needs
  to lower dispatch/combine to all-to-all inside its one-program-per-step
  shard_map (GSPMD, arxiv 2105.04663; collective sequences for array
  redistribution, arxiv 2112.01075).

**Capacity overflow = residual bypass.** ``cap = max(int(cf * N * k / E),
k)`` per forward call (N is that call's row count — a ragged packed
prefill step routes with the capacity of its packed row total). A token
slot whose capacity position lands at or past ``cap`` keeps combine
weight 0, so the expert contribution is an exact zero and the token rides
the residual stream through the layer unchanged — deterministic shedding
to identity, never an error. Engines feed full fixed-batch rows including
zero rows for inactive slots; those rows route deterministically (uniform
softmax -> expert 0 by top-k tie order) and consume capacity like any
other row, which is why per-expert load counts include them.

**Expert parallelism.** ``shard_experts(ep)`` partitions the stacked
expert weights over ``parallel.mesh.serving_shard_devices(ep)`` —
contiguous expert ranges per shard, host-staged loop exactly like the
PR 15/17 ``mp`` serving shards. Because non-routed contributions are
exact zeros, the combine is a disjoint sum: the fold walks shards in
expert order with ONE running accumulator, so the sequence of additions
(and therefore every bit of the output) is identical to the unsharded
fold. Gate and attention stay replicated.

Per-expert load / overflow accumulate as device-side arrays on the hot
path (no host sync); ``moe_metrics()`` is the cold scrape the engine
attaches to its MetricsRegistry under the ``moe.*`` namespace.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.op import apply, unwrap
from ..framework.tensor import Parameter
from .. import nn
from ..incubate.nn.fused_transformer import (FusedMultiTransformer,
                                             _use_decode_kernel)
from ..ops.pallas.grouped_gemm import gmm


def moe_capacity(capacity_factor, n_tokens, top_k, num_experts):
    """Per-call expert capacity (GShard): ``max(int(cf*N*k/E), k)``."""
    return max(int(capacity_factor * n_tokens * top_k / num_experts), top_k)


def _act_fn(name):
    # F.gelu is exact erf; jax.nn.gelu defaults to tanh-approximate.
    if name == "gelu":
        return lambda h: jax.nn.gelu(h, approximate=False)
    return getattr(jax.nn, name)


def _route_impl(lg, k, E, cap):
    """GShard top-k routing with capacity positions (incubate/moe.py
    ``_gshard_routing``), flattened for the serving dispatch paths.

    Returns ``(w, expert, pos, keep, val, load, dropped)``:
      w       [N, E] capacity-respecting combine weights (exact 0 for
              non-routed and overflowed pairs — the residual-bypass mask)
      expert  [k, N] int32 expert id per top-k slot
      pos     [k, N] int32 capacity position within the expert
      keep    [k, N] bool, pos < cap
      val     [k, N] combine weight per slot (0 where dropped)
      load    [E] int32 kept assignments per expert (this call)
      dropped [E] int32 overflowed assignments per expert (this call)
    """
    probs = jax.nn.softmax(lg, axis=-1)
    topv, topi = jax.lax.top_k(probs, k)
    topv = topv / jnp.sum(topv, -1, keepdims=True)

    offset = jnp.zeros((E,), jnp.int32)
    w = jnp.zeros(lg.shape, lg.dtype)
    load = jnp.zeros((E,), jnp.int32)
    dropped = jnp.zeros((E,), jnp.int32)
    es, ps, ks, vs = [], [], [], []
    for slot in range(k):
        idx = topi[:, slot]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)
        pos = jnp.sum(((jnp.cumsum(onehot, axis=0) - 1)
                       + offset[None, :]) * onehot, -1)
        keep = pos < cap
        val = jnp.where(keep, topv[:, slot], 0.0)
        w = w + onehot.astype(lg.dtype) * val[:, None]
        kept_oh = onehot * keep[:, None].astype(jnp.int32)
        load = load + jnp.sum(kept_oh, axis=0)
        dropped = dropped + jnp.sum(onehot - kept_oh, axis=0)
        es.append(idx.astype(jnp.int32))
        ps.append(pos.astype(jnp.int32))
        ks.append(keep)
        vs.append(val)
        offset = offset + jnp.sum(onehot, axis=0)
    return (w, jnp.stack(es), jnp.stack(ps), jnp.stack(ks), jnp.stack(vs),
            load, dropped)


def _expert_contrib_impl(x, we, w1, b1, w2, b2, act):
    """One expert's weighted residual contribution: ``act(x@w1+b1)@w2+b2``
    scaled by the combine-weight column (exact 0 for non-routed rows).

    This single impl is the unit of bit-reproducibility: the unsharded
    fold and every ``shard_experts`` shard run the SAME code object with
    the SAME shapes, so the eager-op jit cache hands back the same
    executable and the contributions are bitwise identical wherever the
    expert weights live.
    """
    h = _act_fn(act)(x @ w1 + b1)
    y = h @ w2 + b2
    return y * we[:, None]


def _grouped_ffn_impl(x, expert, pos, keep, val, w1, b1, w2, b2,
                      E, cap_pad, block_m, act):
    """Kernel-path dispatch/combine around two grouped GEMMs.

    Static capacity layout: row ``e * cap_pad + pos`` holds the token
    assigned to expert ``e`` at capacity position ``pos``; overflowed
    slots scatter out of bounds and are dropped. ``cap_pad`` is the
    capacity rounded up to ``block_m`` so every gmm m-block belongs to
    exactly one expert. Unfilled rows compute garbage through the FFN
    and are never gathered back. The combine folds each token's k slot
    contributions in ascending EXPERT order — the same summation
    association as the reference per-expert fold, so both paths agree
    bit-for-bit at dims where the row-wise GEMM is row-count invariant.
    """
    k, n = expert.shape
    rows = E * cap_pad
    row_e = jnp.repeat(jnp.arange(E, dtype=jnp.int32), cap_pad)
    block_expert = jnp.repeat(jnp.arange(E, dtype=jnp.int32),
                              cap_pad // block_m)
    plhs = jnp.zeros((rows, x.shape[1]), x.dtype)
    for slot in range(k):
        ridx = jnp.where(keep[slot], expert[slot] * cap_pad + pos[slot],
                         rows)
        plhs = plhs.at[ridx].set(x, mode="drop")
    h = gmm(plhs, w1, block_expert, block_m=block_m) + b1[row_e]
    h = _act_fn(act)(h)
    y = gmm(h, w2, block_expert, block_m=block_m) + b2[row_e]

    contribs = []
    for slot in range(k):
        ridx = jnp.clip(expert[slot] * cap_pad + pos[slot], 0, rows - 1)
        contribs.append(y[ridx] * val[slot][:, None])
    g = jnp.stack(contribs)                       # [k, N, d]
    order = jnp.argsort(expert, axis=0)           # slots by expert id
    out = jnp.zeros_like(x)
    for j in range(k):
        out = out + jnp.take_along_axis(g, order[j][None, :, None],
                                        axis=0)[0]
    return out


class MoeServingCore(FusedMultiTransformer):
    """Token-choice MoE decoder stack speaking the serving cache protocol.

    Construction replaces each block's dense ``ffn1``/``ffn2`` with a
    router (``blk.gate``) and expert-stacked parameters ``moe_w1 [E,d,f]``,
    ``moe_b1 [E,f]``, ``moe_w2 [E,f,d]``, ``moe_b2 [E,d]`` — drawn as E
    independent Xavier Linears (deterministic under ``paddle.seed``) and
    stacked, so ``shard_experts`` can partition axis 0 over devices.

    ``use_kernel``: None = grouped-GEMM path on TPU, per-expert einsum
    reference elsewhere; True/False force a path (True on CPU runs the
    gmm interpret kernel — the parity-test configuration).
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 num_experts=4, top_k=2, capacity_factor=1.25,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 epsilon=1e-5, num_layers=1, use_kernel=None, block_m=8):
        if num_experts < top_k:
            raise ValueError(f"num_experts={num_experts} < top_k={top_k}")
        super().__init__(embed_dim, num_heads, dim_feedforward,
                         dropout_rate=dropout_rate, activation=activation,
                         normalize_before=normalize_before, epsilon=epsilon,
                         num_layers=num_layers)
        self.num_experts = int(num_experts)
        self.top_k = int(top_k)
        self.capacity_factor = float(capacity_factor)
        self.moe_ffn_dim = int(dim_feedforward)
        self._use_kernel = use_kernel
        self._block_m = int(block_m)
        for blk in self.layers:
            # expert weights drawn as E independent Linears so the init
            # distribution matches a dense ffn1/ffn2 per expert, then
            # stacked on a leading expert axis for sharding/gmm
            fc1 = [nn.Linear(embed_dim, dim_feedforward)
                   for _ in range(num_experts)]
            fc2 = [nn.Linear(dim_feedforward, embed_dim)
                   for _ in range(num_experts)]
            del blk.ffn1
            del blk.ffn2
            blk.gate = nn.Linear(embed_dim, num_experts)
            blk.moe_w1 = Parameter(jnp.stack([unwrap(l.weight) for l in fc1]))
            blk.moe_b1 = Parameter(jnp.stack([unwrap(l.bias) for l in fc1]))
            blk.moe_w2 = Parameter(jnp.stack([unwrap(l.weight) for l in fc2]))
            blk.moe_b2 = Parameter(jnp.stack([unwrap(l.bias) for l in fc2]))
        self._ep = None
        self._ep_devices = None
        self._ep_weights = None
        self._calls = 0
        self._rows = 0
        self._load = [jnp.zeros((self.num_experts,), jnp.int32)
                      for _ in range(self.num_layers)]
        self._dropped = [jnp.zeros((self.num_experts,), jnp.int32)
                         for _ in range(self.num_layers)]
        # Per-op eager, never whole-forward capture: the forward is
        # side-effectful by design (device-side load/overflow
        # accumulators above), and — more load-bearing — the per-expert
        # combine fold must execute as a sequence of standalone cached
        # executables so the unsharded and shard_experts dispatches run
        # the SAME programs on the same shapes. A whole-forward capture
        # would hand each layout to XLA as one differently-fusable
        # program and void the bitwise ep-equivalence contract.
        from ..framework import layer_jit
        layer_jit.mark_unsafe(self)

    # ---- configuration surface --------------------------------------

    @property
    def moe_spec(self):
        """Static routing spec — the WorkModel pricing hook."""
        return {"num_experts": self.num_experts, "top_k": self.top_k,
                "capacity_factor": self.capacity_factor,
                "ffn_dim": self.moe_ffn_dim}

    def _kernel_on(self):
        if self._use_kernel is None:
            return _use_decode_kernel()
        return bool(self._use_kernel)

    def shard_experts(self, ep, devices=None):
        """Partition the expert-stacked weights over ``ep`` shards.

        Contiguous expert ranges per shard, device-resident via
        ``serving_shard_devices`` (LOGICAL shards on repeated devices when
        the platform has fewer — the host-staged loop does not care).
        Returns self; dispatch switches to the shard loop in
        ``_combine_fold``. The kernel path stays single-program — with
        distinct devices the compiled-step lowering would express the
        dispatch/combine as all-to-all instead (see module docstring).
        """
        from ..parallel.mesh import serving_shard_devices
        ep = int(ep)
        if ep < 1 or self.num_experts % ep:
            raise ValueError(
                f"ep={ep} must divide num_experts={self.num_experts}")
        devs = list(devices) if devices is not None \
            else serving_shard_devices(ep)[:ep]
        per = self.num_experts // ep
        weights = []
        for blk in self.layers:
            shards = []
            for s in range(ep):
                lo = s * per
                sl = tuple(jax.device_put(unwrap(p)[lo:lo + per], devs[s])
                           for p in (blk.moe_w1, blk.moe_b1,
                                     blk.moe_w2, blk.moe_b2))
                shards.append(sl)
            weights.append(shards)
        self._ep = ep
        self._ep_devices = devs
        self._ep_weights = weights
        return self

    def truncated(self, num_layers):
        """First-``num_layers`` weight-SHARING twin — the MoE analogue of
        the dense truncated draft (speculative.TokenServingModel)."""
        if not (0 < num_layers <= self.num_layers):
            raise ValueError(f"num_layers must be in [1, {self.num_layers}]")
        clone = MoeServingCore(
            self.embed_dim, self.num_heads, self.moe_ffn_dim,
            num_experts=self.num_experts, top_k=self.top_k,
            capacity_factor=self.capacity_factor,
            activation=self._act_name,
            normalize_before=self.normalize_before,
            num_layers=num_layers, use_kernel=self._use_kernel,
            block_m=self._block_m)
        clone.layers = nn.LayerList(
            [self.layers[i] for i in range(num_layers)])
        return clone

    # ---- metrics ----------------------------------------------------

    def moe_metrics(self):
        """Cold scrape for MetricsRegistry.attach("moe", ...): pulls the
        device-side per-expert accumulators to host. Flattens to
        ``moe.load.<e>``, ``moe.overflow.<e>``, ``moe.routed_tokens``,
        ``moe.dropped_tokens``, ``moe.overflow_rate`` ... — the signal
        catalog the expert-collapse detector samples."""
        load = np.zeros((self.num_experts,), np.int64)
        drop = np.zeros((self.num_experts,), np.int64)
        for i in range(self.num_layers):
            load += np.asarray(self._load[i]).astype(np.int64)
            drop += np.asarray(self._dropped[i]).astype(np.int64)
        routed = int(load.sum())
        dropped = int(drop.sum())
        total = routed + dropped
        return {
            "experts": self.num_experts,
            "top_k": self.top_k,
            "ep": self._ep or 0,
            "calls": self._calls,
            "rows": self._rows,
            "routed_tokens": routed,
            "dropped_tokens": dropped,
            "overflow_rate": (dropped / total) if total else 0.0,
            "load": {str(e): int(load[e]) for e in range(self.num_experts)},
            "overflow": {str(e): int(drop[e])
                         for e in range(self.num_experts)},
        }

    # ---- snapshot / restore -----------------------------------------

    def snapshot(self):
        """Routing config + per-expert counters (JSON-clean). Weights ride
        state_dict() like any Layer; this is the serving-side state."""
        return {
            "kind": "moe_serving_core",
            "config": {
                "num_experts": self.num_experts,
                "top_k": self.top_k,
                "capacity_factor": self.capacity_factor,
                "ffn_dim": self.moe_ffn_dim,
                "block_m": self._block_m,
                "use_kernel": self._use_kernel,
                "ep": self._ep,
            },
            "counters": {
                "calls": self._calls,
                "rows": self._rows,
                "load": [[int(v) for v in np.asarray(a)]
                         for a in self._load],
                "overflow": [[int(v) for v in np.asarray(a)]
                             for a in self._dropped],
            },
        }

    def restore(self, snap):
        cfg = snap["config"]
        if (cfg["num_experts"] != self.num_experts
                or cfg["top_k"] != self.top_k
                or cfg["capacity_factor"] != self.capacity_factor
                or cfg["ffn_dim"] != self.moe_ffn_dim
                or cfg["block_m"] != self._block_m):
            raise ValueError("snapshot routing config mismatch")
        self._use_kernel = cfg["use_kernel"]
        if cfg["ep"] and cfg["ep"] != self._ep:
            self.shard_experts(cfg["ep"])
        cnt = snap["counters"]
        self._calls = int(cnt["calls"])
        self._rows = int(cnt["rows"])
        self._load = [jnp.asarray(v, jnp.int32) for v in cnt["load"]]
        self._dropped = [jnp.asarray(v, jnp.int32)
                         for v in cnt["overflow"]]

    # ---- dispatch ---------------------------------------------------

    def _ffn_block(self, i, blk, x):
        residual = x
        h = blk.ffn_ln(x) if self.normalize_before else x
        h = self._moe_ffn(i, blk, h)
        x = residual + h
        if not self.normalize_before:
            x = blk.ffn_ln(x)
        return x

    def _moe_ffn(self, i, blk, h):
        from ..ops.manipulation import reshape
        shape = h.shape
        x2 = reshape(h, [-1, shape[-1]])
        n = x2.shape[0]
        cap = moe_capacity(self.capacity_factor, n, self.top_k,
                           self.num_experts)
        logits = blk.gate(x2)
        w, expert, pos, keep, val, load, dropped = apply(
            _route_impl, (logits,),
            {"k": self.top_k, "E": self.num_experts, "cap": cap},
            differentiable=False, op_name="moe_route")
        # device-side accumulate (raw arrays — no host sync, no tape).
        # Skipped inside a foreign trace (some outer layer capturing
        # through us): storing a tracer would poison the accumulators;
        # our own capture is already opted out in __init__.
        raw_load = unwrap(load)
        if not isinstance(raw_load, jax.core.Tracer):
            self._load[i] = self._load[i] + raw_load
            self._dropped[i] = self._dropped[i] + unwrap(dropped)
            if i == 0:
                self._calls += 1
                self._rows += n
        if self._ep is not None:
            out = self._combine_fold(i, blk, x2, w)
        elif self._kernel_on():
            cap_pad = -(-cap // self._block_m) * self._block_m
            out = apply(
                _grouped_ffn_impl,
                (x2, expert, pos, keep, val,
                 blk.moe_w1, blk.moe_b1, blk.moe_w2, blk.moe_b2),
                {"E": self.num_experts, "cap_pad": cap_pad,
                 "block_m": self._block_m, "act": self._act_name},
                differentiable=False, op_name="moe_grouped_ffn")
        else:
            out = self._combine_fold(i, blk, x2, w)
        return reshape(out, shape)

    def _combine_fold(self, i, blk, x2, w):
        """Reference combine: left-fold of per-expert contributions in
        ascending expert order. One running accumulator walks every
        expert — sharded or not — so the addition sequence is identical
        for any ``ep`` (non-routed contributions are exact zeros; the
        zero-padded disjoint-sum discipline of the PR 15 combine)."""
        act = self._act_name
        out = None
        if self._ep is None:
            groups = [((blk.moe_w1, blk.moe_b1, blk.moe_w2, blk.moe_b2),
                       0, None)]
        else:
            per = self.num_experts // self._ep
            groups = [(self._ep_weights[i][s], s * per,
                       self._ep_devices[s]) for s in range(self._ep)]
        for (w1, b1, w2, b2), lo, dev in groups:
            xs = x2 if dev is None else jax.device_put(unwrap(x2), dev)
            ws = w if dev is None else jax.device_put(unwrap(w), dev)
            local_e = w1.shape[0]
            for e in range(local_e):
                contrib = apply(
                    _expert_contrib_impl,
                    (xs, ws[:, lo + e], w1[e], b1[e], w2[e], b2[e]),
                    {"act": act},
                    differentiable=False, op_name="moe_expert_contrib")
                if dev is not None:
                    contrib = jax.device_put(unwrap(contrib),
                                             self._ep_devices[0])
                out = contrib if out is None else out + contrib
        return out
