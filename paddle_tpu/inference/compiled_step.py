"""ONE jitted shard_map program per sharded serving step.

PR 15's ShardedServingCore is host-staged: per layer, per shard,
Python issues the qkv GEMM, the paged-attention launch and a
device_put-hopping all-reduce — O(shards x layers) dispatches per
model call, and the reduced tensor round-trips host numpy. This
module lowers the SAME schedule (the one PR 15 proved bit-exact:
disjoint zero-padded head sums closed by exactly one collective per
layer) into a single ``jax.jit(shard_map(body))`` program over a
``Mesh(("mp",))`` — the GSPMD programming model (PAPERS.md, arxiv
2105.04663) applied to the serving stack:

  * the per-shard KV pools ride as DONATED, head-sharded arguments
    (``NamedSharding(P(None, None, "mp", None, None))`` on the
    ``[num_blocks, 2, H/mp, bs, D]`` pools; int8 scale pages
    alongside) — append-scatter and attention read/write
    device-resident state, zero host round-trips. Assembly of the
    global array from the cache's per-shard entries and the rebind
    from the donated outputs are both zero-copy metadata ops
    (``jax.make_array_from_single_device_arrays`` /
    ``addressable_shards``), so ``PagedKVCache`` keeps its flat
    per-shard list — COW splits, prefill scatters, snapshots and
    slice export between compiled calls see ordinary committed
    per-device arrays and need no changes.
  * inside the mapped body each layer runs per-shard qkv + the
    per-segment attention decomposition and closes with EXACTLY ONE
    ``jax.lax.psum``. Two closure modes (``out_shard``):
    ``"replicated"`` psums the zero-padded disjoint head sums and
    runs the out-projection replicated — IEEE-exact (x + 0 == x;
    each element has one nonzero contributor), the CPU-proof twin of
    the legacy ``_allreduce``; ``"rows"`` is the true Megatron
    second GEMM — each shard multiplies its head slice against its
    ROW slice of ``out_proj.weight`` and psums the partial sums.
    Rows mode belongs on the compiled path (TPU default): a K-split
    GEMM is not column-stable on CPU at serving widths, the same
    trap class as ``qkv_shard="activations"``.
  * the CPU attention body is the EXACT per-segment decomposition
    the eager views run (one multi-row masked sdpa per prefill
    chunk, batch-of-1-row sdpa for decode, the L-fold for verify
    rows — ``_sdpa_jnp`` itself), so compiled mp=N streams stay
    bit-identical to the mp=1 eager engine. On TPU the ragged pallas
    kernel slots into the same body (ROADMAP hardware leg);
    ``paged_attention_ragged`` is already callable under shard_map.

Compile-cache discipline: programs are cached per STATIC BUCKET key.
Prefill chunk lengths bucket to the next power of two (minimum 2;
length-1 chunks stay singleton — padding a 1-row chunk to 2 rows
would swap the GEMV-class sdpa for the multi-row one, the
MIN_PREFILL_SUFFIX_ROWS trap in reverse), with pad rows routed to
the trash block on write and dropped on unpack; decode/verify
segments are naturally static ``(B, L)``. Retrace count ==
``len(self._fns)`` is exported through ``sharded.retraces`` and
bounded in tests. Pad/unpack row gathers run EAGERLY outside the
program (tiny ops, cached per shape by jax itself) so real row
counts never leak into the program key.

HOT-PATH PURITY (tools/check_static.py ``compiled-step-purity``):
nothing on the per-step call path — ``forward`` / ``_run_*`` /
``_dispatch`` / the traced bodies — may pull device data to host
(``np.asarray``/``device_get``/``.item``/...) or hop devices
(``device_put``). Host metadata (numpy routing built from the
layout's np fields) flows IN via ``jnp.asarray`` as operands; that
direction is the normal feed and is allowed. Setup (``__init__``,
``_setup_weights``) is the allowlisted boundary where weights are
placed once.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ..framework.tensor import Tensor
from ..nn.functional.attention import _sdpa_jnp
from ..ops.pallas.paged_attention import gather_pages

_POOL_SPEC = P(None, None, "mp", None, None)
_SCALE_SPEC = P(None, None, "mp", None)


def _bucket(n: int) -> int:
    """Prefill-chunk length bucket: next power of two, minimum 2 —
    EXCEPT length 1, which stays 1 (a 1-row sdpa is the GEMV-class
    executable; padding it to 2 rows would change its bits vs the
    eager step, the same accumulation trap MIN_PREFILL_SUFFIX_ROWS
    exists for)."""
    n = int(n)
    if n <= 1:
        return n
    return max(2, 1 << (n - 1).bit_length())


def _act_fn(name: str):
    if name == "gelu":
        # F.gelu's default: exact (erf) gelu, not the tanh approximation
        return lambda a: jax.nn.gelu(a, approximate=False)
    f = getattr(jax.nn, name, None)
    if f is None:
        raise ValueError(f"activation {name!r} has no jax.nn twin")
    return f


def _ln(x, w, b, eps):
    # mirror of nn/functional/norm.py layer_norm at normalized_shape
    # == [E]: float32 mean/var, rsqrt, affine, cast back
    mean = jnp.mean(x.astype(jnp.float32), axis=-1, keepdims=True)
    var = jnp.var(x.astype(jnp.float32), axis=-1, keepdims=True)
    out = (x.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + eps)
    if w is not None:
        out = out * w.astype(jnp.float32).reshape([x.shape[-1]])
    if b is not None:
        out = out + b.astype(jnp.float32).reshape([x.shape[-1]])
    return out.astype(x.dtype)


def _linear(a, w, b):
    # mirror of nn/functional/common.py linear
    if b is None:
        return a @ w
    return a @ w + b


def _count_psums(fn, args) -> int:
    """Trace ``fn`` and count psum primitives in the jaxpr (recursing
    into sub-jaxprs) — the traced-lowering collective count the
    dispatch instrumentation exports as ``sharded.psums_per_call``."""
    jaxpr = jax.make_jaxpr(fn)(*args)

    def walk(jx) -> int:
        n = 0
        for eqn in jx.eqns:
            if "psum" in eqn.primitive.name:
                n += 1
            for v in eqn.params.values():
                for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                    inner = getattr(sub, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        n += walk(inner)
                    elif hasattr(sub, "eqns"):
                        n += walk(sub)
        return n
    return walk(jaxpr.jaxpr)


class CompiledStepRunner:
    """Per-core compiler + program cache + dispatch counters for the
    compiled sharded serving step. Owns the serving Mesh(("mp",)),
    the pre-placed weight pytree, and one jitted program per static
    bucket key. ``ShardedServingCore.forward`` hands it every paged
    call when ``compiled_step`` engages; it returns the hidden
    states + (unchanged) views, with the cache's per-shard pool
    entries rebound to the donated outputs' shards."""

    def __init__(self, core):
        from ..parallel.mesh import serving_mesh
        mesh = serving_mesh(core.mp, core.shard_devices)
        if mesh is None:
            raise ValueError(
                "compiled_step needs mp distinct shard devices (a "
                "real mesh); logical shards on one device stay on "
                "the legacy host-staged path")
        self.core = core
        self.mesh = mesh
        self._fns: Dict[tuple, tuple] = {}      # key -> (fn, psums)
        self.jit_calls = 0
        self.last_dispatches = 0
        self._last_psums = 0
        self._weights: Optional[list] = None
        self._wspecs: Optional[list] = None
        self._ln_eps: List[float] = []
        self._ffn_ln_eps: List[float] = []
        self._pool_sh = NamedSharding(self.mesh, _POOL_SPEC)
        self._scale_sh = NamedSharding(self.mesh, _SCALE_SPEC)

    # -- counters (MetricsRegistry surface) ---------------------------
    @property
    def retraces(self) -> int:
        return len(self._fns)

    def metrics(self) -> dict:
        return {"jit_calls": self.jit_calls,
                "retraces": self.retraces,
                "dispatches_per_step": self.last_dispatches,
                "psums_per_call": self._last_psums}

    def reset_counters(self) -> None:
        self.jit_calls = 0
        self.last_dispatches = 0

    # -- weight placement (setup boundary: runs once) -----------------
    def _setup_weights(self) -> None:
        core = self.core
        mesh = self.mesh
        repl = NamedSharding(mesh, P())

        def put(t):
            return None if t is None else jax.device_put(t.data, repl)

        W, S = [], []
        for i, blk in enumerate(core.base.layers):
            w, s = {}, {}

            def keep(name, arr, spec=P()):
                if arr is not None:
                    w[name] = arr
                    s[name] = spec
            keep("ln_w", put(blk.ln.weight))
            keep("ln_b", put(blk.ln.bias))
            keep("ffn_ln_w", put(blk.ffn_ln.weight))
            keep("ffn_ln_b", put(blk.ffn_ln.bias))
            self._ln_eps.append(float(blk.ln._epsilon))
            self._ffn_ln_eps.append(float(blk.ffn_ln._epsilon))
            if core.qkv_shard == "weights":
                # reuse the core's per-shard column slices, already
                # committed one per device — assembly is zero-copy.
                # The global column order interleaves shards' q/k/v
                # blocks, which is irrelevant: the body only ever
                # sees its LOCAL [E, 3*Hs*hd] slice.
                parts = [core._qkv_w[i][s_].data
                         for s_ in range(core.mp)]
                E = parts[0].shape[0]
                width = sum(p.shape[1] for p in parts)
                keep("qkv_w", jax.make_array_from_single_device_arrays(
                    (E, width), NamedSharding(mesh, P(None, "mp")),
                    parts), P(None, "mp"))
                if core._qkv_b[i][0] is not None:
                    bparts = [core._qkv_b[i][s_].data
                              for s_ in range(core.mp)]
                    keep("qkv_b", jax.make_array_from_single_device_arrays(
                        (width,), NamedSharding(mesh, P("mp")),
                        bparts), P("mp"))
            else:
                keep("qkv_w", put(blk.qkv.weight))
                keep("qkv_b", put(blk.qkv.bias))
            if core.out_shard == "rows":
                # true Megatron second GEMM: shard s owns the row
                # block [s*Hs*hd, (s+1)*Hs*hd) — contiguous because
                # att.reshape(..., H*hd) orders (head, dim) and
                # shards hold contiguous head ranges
                keep("out_w", jax.device_put(
                    blk.out_proj.weight.data,
                    NamedSharding(mesh, P("mp", None))), P("mp", None))
            else:
                keep("out_w", put(blk.out_proj.weight))
            keep("out_b", put(blk.out_proj.bias))
            keep("ffn1_w", put(blk.ffn1.weight))
            keep("ffn1_b", put(blk.ffn1.bias))
            keep("ffn2_w", put(blk.ffn2.weight))
            keep("ffn2_b", put(blk.ffn2.bias))
            W.append(w)
            S.append(s)
        self._weights = W
        self._wspecs = S

    # -- pool assembly / rebind (zero-copy both ways) -----------------
    def _assemble(self, cache) -> Tuple[list, list]:
        L, mp = cache.num_layers, cache.mp
        Hs = cache.heads_per_shard
        pshape = (cache.num_blocks, 2, Hs * mp, cache.block_size,
                  cache.head_dim)
        pools = [jax.make_array_from_single_device_arrays(
            pshape, self._pool_sh,
            [cache.pools[cache.pool_index(li, s)].data
             for s in range(mp)]) for li in range(L)]
        if not cache.quantized:
            return pools, []
        sshape = pshape[:3] + (cache.block_size,)
        scales = [jax.make_array_from_single_device_arrays(
            sshape, self._scale_sh,
            [cache.scales[cache.pool_index(li, s)].data
             for s in range(mp)]) for li in range(L)]
        return pools, scales

    # -- program build ------------------------------------------------
    def _get_fn(self, key, meta, pools_g, scales_g, ops):
        hit = self._fns.get(key)
        if hit is not None:
            return hit
        if self._weights is None:
            self._setup_weights()
        body = self._make_body(meta)
        nl = self.core.num_layers
        pool_specs = [_POOL_SPEC] * nl
        scale_specs = [_SCALE_SPEC] * nl if meta["quantized"] else []
        ops_spec = jax.tree_util.tree_map(lambda _: P(), ops)
        smap = shard_map(
            body, mesh=self.mesh,
            in_specs=(pool_specs, scale_specs, self._wspecs, ops_spec),
            out_specs=(P(), pool_specs, scale_specs),
            check_rep=False)
        psums = _count_psums(smap, (pools_g, scales_g, self._weights,
                                    ops))
        fn = jax.jit(smap, donate_argnums=(0, 1))
        self._fns[key] = (fn, psums)
        return fn, psums

    def _dispatch(self, key, meta, cache, ops):
        """Assemble pools -> run the (cached) program -> rebind the
        cache's per-shard entries from the donated outputs. Returns
        the hidden states (global, replicated)."""
        pools_g, scales_g = self._assemble(cache)
        fn, psums = self._get_fn(key, meta, pools_g, scales_g, ops)
        hidden, new_pools, new_scales = fn(pools_g, scales_g,
                                           self._weights, ops)
        # donation invalidated the input buffers: rebind IMMEDIATELY
        # so no eager path can touch a dead pool entry
        for li in range(cache.num_layers):
            cache.rebind_shard_pools(
                li, new_pools[li],
                new_scales[li] if new_scales else None)
        self.jit_calls += 1
        self.last_dispatches = 1
        self._last_psums = psums
        return hidden

    # -- entry: view-type dispatch ------------------------------------
    def forward(self, src, caches, time_step):
        """Serve one model call through the compiled program. Returns
        (hidden Tensor, caches) or None when the view type is not
        one the compiled step serves (the caller falls back to the
        legacy host-staged loop)."""
        from .paged_cache import (PagedLayerCache, PagedPrefillView,
                                  PagedRaggedView)
        v0 = caches[0]
        if isinstance(v0, PagedRaggedView):
            return self._run_ragged(src, caches)
        if isinstance(v0, PagedPrefillView):
            return self._run_chunk(src, caches, time_step)
        if isinstance(v0, PagedLayerCache):
            return self._run_decode(src, caches, time_step)
        return None

    def _norm_t(self, time_step, b):
        t = time_step.data if isinstance(time_step, Tensor) \
            else jnp.asarray(time_step, jnp.int32)
        return jnp.broadcast_to(t.reshape(-1).astype(jnp.int32), (b,))

    def _geom(self, cache) -> dict:
        core = self.core
        return {"quantized": bool(cache.quantized),
                "bs": cache.block_size,
                "MB": cache.max_blocks_per_seq,
                "E": core.embed_dim, "H": core.num_heads,
                "Hs": core.heads_per_shard, "hd": core.head_dim,
                "nlayers": core.num_layers,
                "qkv_mode": core.qkv_shard,
                "out_mode": core.out_shard,
                "act": core._act_name,
                "normalize_before": bool(core.normalize_before)}

    # -- ragged (packed mixed step) -----------------------------------
    def _run_ragged(self, src, caches):
        lay = caches[0]._layout
        cache = caches[0]._cache
        R_real = lay.total_rows
        segs_static: List[tuple] = []
        pad_idx: List[int] = []     # padded row -> [0, R_real] (R_real = zero row)
        real_idx: List[int] = []    # packed row -> its padded position
        blk_pad: List[np.ndarray] = []
        off_pad: List[np.ndarray] = []
        starts: List[int] = []
        lens_np = None
        lo_pad = 0
        for seg in lay.segs:
            kind, lo, hi = seg[0], seg[1], seg[2]
            n = hi - lo
            if kind == "prefill":
                cpad = _bucket(n)
                segs_static.append(("p", cpad))
                starts.append(int(seg[4]))
                pad_idx.extend(range(lo, hi))
                pad_idx.extend([R_real] * (cpad - n))
                real_idx.extend(range(lo_pad, lo_pad + n))
                blk_pad.append(lay.blk_np[lo:hi])
                off_pad.append(lay.off_np[lo:hi])
                if cpad > n:
                    # pad rows write the trash block at offset 0 —
                    # duplicate indices there are fine, nothing reads
                    # it unmasked (same rule as adopted-prefix rows)
                    blk_pad.append(np.zeros(cpad - n, np.int32))
                    off_pad.append(np.zeros(cpad - n, np.int32))
                lo_pad += cpad
            else:
                lens_np, L = seg[3], seg[4]
                B = n // L
                segs_static.append(("d", B, L))
                pad_idx.extend(range(lo, hi))
                real_idx.extend(range(lo_pad, lo_pad + n))
                blk_pad.append(lay.blk_np[lo:hi])
                off_pad.append(lay.off_np[lo:hi])
                lo_pad += n
        R_pad = lo_pad
        meta = self._geom(cache)
        meta.update(kind="ragged", segs=tuple(segs_static))
        key = ("ragged", meta["segs"], meta["quantized"])

        x0 = src.data[0]
        if R_pad != R_real:
            xz = jnp.concatenate(
                [x0, jnp.zeros((1, x0.shape[-1]), x0.dtype)], axis=0)
            xp = jnp.take(xz, jnp.asarray(pad_idx, np.int32),
                          axis=0)[None]
        else:
            xp = src.data
        ops = {"x": xp,
               "blk": jnp.asarray(np.concatenate(blk_pad)
                                  .astype(np.int32)),
               "off": jnp.asarray(np.concatenate(off_pad)
                                  .astype(np.int32)),
               "bt": lay.bt_all.data}
        if starts:
            ops["starts"] = jnp.asarray(starts, jnp.int32)
        if lens_np is not None:
            ops["lens"] = jnp.asarray(lens_np, jnp.int32)
        hidden = self._dispatch(key, meta, cache, ops)
        if R_pad != R_real:
            hidden = jnp.take(hidden[0],
                              jnp.asarray(real_idx, np.int32),
                              axis=0)[None]
        return Tensor(hidden), list(caches)

    # -- chunked prefill (one slot, batch-1) --------------------------
    def _run_chunk(self, src, caches, time_step):
        view = caches[0]
        cache = view._cache
        C = int(src.shape[1])
        cpad = _bucket(C)
        meta = self._geom(cache)
        meta.update(kind="chunk", C=cpad)
        key = ("chunk", cpad, meta["quantized"])
        xp = src.data
        if cpad > C:
            xp = jnp.concatenate(
                [xp, jnp.zeros((1, cpad - C, xp.shape[-1]),
                               xp.dtype)], axis=1)
        ops = {"x": xp,
               "t": self._norm_t(time_step, 1),
               "ws": jnp.asarray([view._write_start], jnp.int32),
               "nreal": jnp.asarray([C], jnp.int32),
               "bt": cache.bt_row_tensor(view._slot).data}
        hidden = self._dispatch(key, meta, cache, ops)
        if cpad > C:
            hidden = jax.lax.slice_in_dim(hidden, 0, C, axis=1)
        return Tensor(hidden), list(caches)

    # -- fused decode / multi-token verify ----------------------------
    def _run_decode(self, src, caches, time_step):
        cache = caches[0]._cache
        B, L = int(src.shape[0]), int(src.shape[1])
        meta = self._geom(cache)
        meta.update(kind="decode", B=B, L=L)
        key = ("decode", B, L, meta["quantized"])
        ops = {"x": src.data,
               "t": self._norm_t(time_step, B),
               "bt": cache.bt_tensor().data}
        hidden = self._dispatch(key, meta, cache, ops)
        return Tensor(hidden), list(caches)

    # -- the mapped body ----------------------------------------------
    def _make_body(self, meta):
        """Build the shard_map body for one static bucket. The body
        mirrors the eager sharded step FORMULA FOR FORMULA — the
        layer_norm/linear/sdpa impls, the append-scatter routing of
        paged_cache's factories, the per-segment decomposition of
        the paged views — so the compiled program's streams are
        bit-identical to the host-staged ones on CPU. Collectives:
        exactly one psum per layer (replicated mode pads disjoint
        head sums; rows mode psums the out-GEMM partials)."""
        from .paged_cache import _quant_rows
        kind = meta["kind"]
        nl, quantized = meta["nlayers"], meta["quantized"]
        E, H, Hs, hd = meta["E"], meta["H"], meta["Hs"], meta["hd"]
        bs = meta["bs"]
        qkv_mode, out_mode = meta["qkv_mode"], meta["out_mode"]
        normalize_before = meta["normalize_before"]
        act = _act_fn(meta["act"])
        ln_eps, ffn_eps = list(self._ln_eps), list(self._ffn_ln_eps)

        def qkv(h, w, s):
            y = _linear(h, w["qkv_w"], w.get("qkv_b"))
            b_, l_ = y.shape[0], y.shape[1]
            width = y.shape[-1] // 3
            parts = [jax.lax.slice_in_dim(y, j * width,
                                          (j + 1) * width, axis=-1)
                     for j in range(3)]
            if qkv_mode == "weights":
                return [p.reshape(b_, l_, Hs, hd) for p in parts]
            full = [p.reshape(b_, l_, H, hd) for p in parts]
            return [jax.lax.dynamic_slice_in_dim(p, s * Hs, Hs,
                                                 axis=2)
                    for p in full]

        def gather(pool, bt_rows, sc):
            if sc is None:
                return gather_pages(pool, bt_rows)
            return gather_pages(pool, bt_rows, sc)

        def close_layer(li, resid, att, w):
            # att: [b, l, Hs, hd] local head slice -> one psum
            s = jax.lax.axis_index("mp")
            b_, l_ = att.shape[0], att.shape[1]
            if out_mode == "replicated":
                pad = jnp.zeros((b_, l_, H, hd), att.dtype)
                pad = jax.lax.dynamic_update_slice(
                    pad, att, (0, 0, s * Hs, 0))
                full = jax.lax.psum(pad, "mp")
                attn = _linear(full.reshape(b_, l_, E), w["out_w"],
                               w.get("out_b"))
            else:
                part = att.reshape(b_, l_, Hs * hd) @ w["out_w"]
                attn = jax.lax.psum(part, "mp")
                if w.get("out_b") is not None:
                    attn = attn + w["out_b"]
            x = resid + attn
            if not normalize_before:
                x = _ln(x, w.get("ln_w"), w.get("ln_b"), ln_eps[li])
            resid = x
            hh = _ln(x, w.get("ffn_ln_w"), w.get("ffn_ln_b"),
                     ffn_eps[li]) if normalize_before else x
            hh = _linear(act(_linear(hh, w["ffn1_w"],
                                     w.get("ffn1_b"))),
                         w["ffn2_w"], w.get("ffn2_b"))
            x = resid + hh
            if not normalize_before:
                x = _ln(x, w.get("ffn_ln_w"), w.get("ffn_ln_b"),
                        ffn_eps[li])
            return x

        def append_rows(pool, sc, k, v, blk, off):
            # mirror of _ragged_append(_q): k/v [1, R, Hs, hd]
            if quantized:
                kq, ks = _quant_rows(k[0])
                vq, vs = _quant_rows(v[0])
                pool = pool.at[blk, 0, :, off, :].set(kq)
                pool = pool.at[blk, 1, :, off, :].set(vq)
                sc = sc.at[blk, 0, :, off].set(ks)
                sc = sc.at[blk, 1, :, off].set(vs)
                return pool, sc
            pool = pool.at[blk, 0, :, off, :].set(
                k[0].astype(pool.dtype))
            pool = pool.at[blk, 1, :, off, :].set(
                v[0].astype(pool.dtype))
            return pool, sc

        if kind == "ragged":
            segs = meta["segs"]

            def attn_ragged(pool, sc, q, ops):
                bt = ops["bt"]
                outs = []
                row = btr = p_i = 0
                for seg in segs:
                    if seg[0] == "p":
                        C = seg[1]
                        qs = q[:, row:row + C]
                        kf, vf = gather(pool, bt[btr:btr + 1], sc)
                        S = kf.shape[1]
                        qpos = (ops["starts"][p_i]
                                + jnp.arange(C)[:, None])
                        kpos = jnp.arange(S)[None, :]
                        mask = jnp.where(kpos <= qpos, 0.0,
                                         -1e30).astype(jnp.float32)
                        o = _sdpa_jnp(qs, kf, vf, mask, 0.0, False,
                                      None)
                        outs.append(o[0])
                        row += C
                        btr += 1
                        p_i += 1
                    else:
                        B, L = seg[1], seg[2]
                        lens = ops["lens"]
                        kf, vf = gather(pool, bt[btr:btr + B], sc)
                        S = kf.shape[1]
                        kpos = jnp.arange(S)[None, None, None, :]
                        if L == 1:
                            qd = q[0, row:row + B][:, None]
                            qpos = (lens[:, None, None, None]
                                    + jnp.arange(1)[None, None, :,
                                                    None])
                            mask = jnp.where(kpos <= qpos, 0.0,
                                             -1e30).astype(jnp.float32)
                            o = _sdpa_jnp(qd, kf, vf, mask, 0.0,
                                          False, None)
                        else:
                            qd = q[0, row:row + B * L][:, None]
                            kff = jnp.repeat(kf, L, axis=0)
                            vff = jnp.repeat(vf, L, axis=0)
                            tf = (jnp.repeat(lens, L)
                                  + jnp.tile(jnp.arange(
                                      L, dtype=jnp.int32), B))
                            qpos = tf[:, None, None, None]
                            mask = jnp.where(kpos <= qpos, 0.0,
                                             -1e30).astype(jnp.float32)
                            o = _sdpa_jnp(qd, kff, vff, mask, 0.0,
                                          False, None)
                        outs.append(o[:, 0])
                        row += B * L
                        btr += B
                return jnp.concatenate(outs, axis=0)[None]

            def body(pools, scales, W, ops):
                x = ops["x"]
                s = jax.lax.axis_index("mp")
                new_pools, new_scales = [], []
                for li in range(nl):
                    pool = pools[li]
                    sc = scales[li] if quantized else None
                    w = W[li]
                    resid = x
                    h = _ln(x, w.get("ln_w"), w.get("ln_b"),
                            ln_eps[li]) if normalize_before else x
                    q, k, v = qkv(h, w, s)
                    pool, sc = append_rows(pool, sc, k, v,
                                           ops["blk"], ops["off"])
                    att = attn_ragged(pool, sc, q, ops)
                    x = close_layer(li, resid, att, w)
                    new_pools.append(pool)
                    if quantized:
                        new_scales.append(sc)
                return x, new_pools, new_scales
            return body

        if kind == "chunk":
            C = meta["C"]

            def body(pools, scales, W, ops):
                x = ops["x"]
                t, ws, nreal = ops["t"], ops["ws"], ops["nreal"]
                bt = ops["bt"]
                s = jax.lax.axis_index("mp")
                # mirror of _make_append_chunk routing, with pad rows
                # (>= nreal) ALSO routed to the trash block
                pos = t[:, None] + jnp.arange(C, dtype=t.dtype)[None, :]
                blk = jnp.take_along_axis(bt, pos // bs, axis=1)
                rows = jnp.arange(C)[None, :]
                blk = jnp.where((pos >= ws) & (rows < nreal[0]),
                                blk, 0)
                off = pos % bs
                new_pools, new_scales = [], []
                for li in range(nl):
                    pool = pools[li]
                    sc = scales[li] if quantized else None
                    w = W[li]
                    resid = x
                    h = _ln(x, w.get("ln_w"), w.get("ln_b"),
                            ln_eps[li]) if normalize_before else x
                    q, k, v = qkv(h, w, s)
                    if quantized:
                        kq, ks = _quant_rows(k)
                        vq, vs = _quant_rows(v)
                        pool = pool.at[blk, 0, :, off, :].set(kq)
                        pool = pool.at[blk, 1, :, off, :].set(vq)
                        sc = sc.at[blk, 0, :, off].set(ks)
                        sc = sc.at[blk, 1, :, off].set(vs)
                    else:
                        pool = pool.at[blk, 0, :, off, :].set(
                            k.astype(pool.dtype))
                        pool = pool.at[blk, 1, :, off, :].set(
                            v.astype(pool.dtype))
                    kf, vf = gather(pool, bt, sc)
                    S = kf.shape[1]
                    qpos = t[0] + jnp.arange(C)[:, None]
                    kpos = jnp.arange(S)[None, :]
                    mask = jnp.where(kpos <= qpos, 0.0,
                                     -1e30).astype(jnp.float32)
                    att = _sdpa_jnp(q, kf, vf, mask, 0.0, False, None)
                    x = close_layer(li, resid, att, w)
                    new_pools.append(pool)
                    if quantized:
                        new_scales.append(sc)
                return x, new_pools, new_scales
            return body

        # kind == "decode": the PagedLayerCache step (L == 1 plain
        # decode; L > 1 the multi-token verify with the L axis folded
        # into the batch axis — the bit-identity fold)
        B, L = meta["B"], meta["L"]

        def body(pools, scales, W, ops):
            x = ops["x"]
            t, bt = ops["t"], ops["bt"]
            s = jax.lax.axis_index("mp")
            if L == 1:
                blk = jnp.take_along_axis(bt, (t // bs)[:, None],
                                          axis=1)[:, 0]
                off = t % bs
            else:
                pos = (t[:, None]
                       + jnp.arange(L, dtype=t.dtype)[None, :])
                blk = jnp.take_along_axis(bt, pos // bs, axis=1)
                off = pos % bs
            new_pools, new_scales = [], []
            for li in range(nl):
                pool = pools[li]
                sc = scales[li] if quantized else None
                w = W[li]
                resid = x
                h = _ln(x, w.get("ln_w"), w.get("ln_b"),
                        ln_eps[li]) if normalize_before else x
                q, k, v = qkv(h, w, s)
                if quantized:
                    kq, ks = _quant_rows(k[:, 0] if L == 1 else k)
                    vq, vs = _quant_rows(v[:, 0] if L == 1 else v)
                    pool = pool.at[blk, 0, :, off, :].set(kq)
                    pool = pool.at[blk, 1, :, off, :].set(vq)
                    sc = sc.at[blk, 0, :, off].set(ks)
                    sc = sc.at[blk, 1, :, off].set(vs)
                else:
                    pool = pool.at[blk, 0, :, off, :].set(
                        (k[:, 0] if L == 1 else k).astype(pool.dtype))
                    pool = pool.at[blk, 1, :, off, :].set(
                        (v[:, 0] if L == 1 else v).astype(pool.dtype))
                kf, vf = gather(pool, bt, sc)
                S = kf.shape[1]
                kpos = jnp.arange(S)[None, None, None, :]
                if L == 1:
                    qpos = (t[:, None, None, None]
                            + jnp.arange(1)[None, None, :, None])
                    mask = jnp.where(kpos <= qpos, 0.0,
                                     -1e30).astype(jnp.float32)
                    att = _sdpa_jnp(q, kf, vf, mask, 0.0, False,
                                    None)
                else:
                    qd = q.reshape((B * L, 1) + q.shape[2:])
                    kff = jnp.repeat(kf, L, axis=0)
                    vff = jnp.repeat(vf, L, axis=0)
                    tf = (jnp.repeat(t, L)
                          + jnp.tile(jnp.arange(L, dtype=t.dtype), B))
                    qpos = tf[:, None, None, None]
                    mask = jnp.where(kpos <= qpos, 0.0,
                                     -1e30).astype(jnp.float32)
                    att = _sdpa_jnp(qd, kff, vff, mask, 0.0, False,
                                    None)
                    att = att.reshape((B, L) + att.shape[2:])
                x = close_layer(li, resid, att, w)
                new_pools.append(pool)
                if quantized:
                    new_scales.append(sc)
            return x, new_pools, new_scales
        return body
