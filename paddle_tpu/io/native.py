"""Native collation binding (see native_collate.cpp). Falls back to
numpy silently when the host toolchain is unavailable — the pipeline is
correct either way, just slower."""
from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional

import numpy as np

_lock = threading.Lock()
_lib = None
_tried = False

# below this many bytes a plain np.stack wins (thread spawn overhead)
MIN_NATIVE_BYTES = 1 << 20


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            from ..utils.cpp_extension import load
            src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "native_collate.cpp")
            _lib = load("paddle_tpu_native_collate", [src],
                        extra_ldflags=["-lpthread"])
            _lib.collate_copy.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_long,
                ctypes.c_long, ctypes.c_void_p, ctypes.c_int]
            _lib.collate_copy.restype = None
        except Exception:
            _lib = None
        return _lib


def native_available() -> bool:
    return _load() is not None


def collate_stack(arrays: List[np.ndarray],
                  nthreads: int = 0) -> Optional[np.ndarray]:
    """np.stack(arrays) through the parallel C++ collator. Returns None
    when the native path does not apply (caller falls back)."""
    lib = _load()
    if lib is None or not arrays:
        return None
    first = arrays[0]
    if first.dtype.hasobject:
        return None  # raw memcpy of PyObject* would skip increfs
    bytes_per = first.nbytes
    if bytes_per * len(arrays) < MIN_NATIVE_BYTES:
        return None
    contig = []
    for a in arrays:
        if a.shape != first.shape or a.dtype != first.dtype:
            return None  # ragged: numpy path handles the error/pad
        contig.append(np.ascontiguousarray(a))
    out = np.empty((len(contig),) + first.shape, first.dtype)
    ptrs = (ctypes.c_void_p * len(contig))(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in contig])
    lib.collate_copy(ptrs, len(contig), bytes_per,
                     out.ctypes.data_as(ctypes.c_void_p), nthreads)
    return out
