"""paddle.io: Dataset / Sampler / DataLoader (ref: /root/reference/python/
paddle/io/ — reader.py:219 DataLoader, dataloader/dataloader_iter.py).

The TPU-native loader is host-side: numpy collation + a background-thread
prefetch queue feeding device transfers, which is the right shape for a
single-controller jax runtime (multiprocess workers are used for heavy
__getitem__ via a thread pool here; the reference's subprocess pool exists
to dodge the GIL for Python transforms)."""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Iterable, List, Optional

import numpy as np

from ..framework.tensor import Tensor

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "random_split", "Sampler", "SequenceSampler",
    "RandomSampler", "WeightedRandomSampler", "BatchSampler",
    "DistributedBatchSampler", "DataLoader", "get_worker_info",
    "default_collate_fn",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    lengths = list(lengths)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(np.floor(total * l)) for l in lengths]
        lengths[-1] += total - sum(lengths)
    if sum(lengths) != total:
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(total)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards indices across data-parallel ranks (ref: python/paddle/io/
    dataloader/batch_sampler.py DistributedBatchSampler). On the TPU
    single-controller runtime rank/nranks come from the mesh data axis."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import env as dist_env
            num_replicas = num_replicas or dist_env.get_data_world_size()
            rank = rank if rank is not None else dist_env.get_data_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - n)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


def _stack(arrays):
    """np.stack with the parallel C++ collator on large batches (the
    reference's C++ DataFeed batch assembly; see native_collate.cpp)."""
    from .native import collate_stack
    out = collate_stack(arrays)
    return out if out is not None else np.stack(arrays)


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return Tensor(_stack([np.asarray(s.numpy()) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(_stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=None, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        if num_workers is None:
            # dataloader autotuning (ref incubate/autotune.py): pick a
            # prefetch worker count ONLY when the user left it unset —
            # an explicit num_workers=0 means deliberate sync loading
            num_workers = 0
            try:
                from ..incubate.autotune import suggested_num_workers
                num_workers = suggested_num_workers() or 0
            except ImportError:  # pragma: no cover
                pass
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 2)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _make_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for idxs in self.batch_sampler:
                samples = [self.dataset[i] for i in idxs]
                yield self.collate_fn(samples)

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._make_batches()
            return
        # background-thread prefetch pipeline
        q: "queue.Queue" = queue.Queue(
            maxsize=self.prefetch_factor * max(self.num_workers, 1))
        sentinel = object()

        def producer():
            try:
                for b in self._make_batches():
                    q.put(b)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
