// Native batch collation for the data pipeline (the TPU-host analog of
// the reference's C++ DataFeed/LoDTensor batch assembly,
// ref: /root/reference/paddle/fluid/framework/data_feed.cc).
//
// Python's np.stack copies samples one memcpy at a time on one thread;
// for large image/audio batches the host copy becomes the input-pipeline
// bottleneck while the TPU waits. This library does the same assembly
// with parallel std::threads. Built JIT via paddle_tpu.utils.
// cpp_extension.load (g++ -O2 -shared), bound through ctypes.
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Copy n samples, each `bytes` bytes, from srcs[i] to dst + i*bytes.
// nthreads <= 0 picks hardware_concurrency (capped at 16).
void collate_copy(const void** srcs, long n, long bytes, void* dst,
                  int nthreads) {
  if (n <= 0 || bytes <= 0) return;
  int nt = nthreads > 0 ? nthreads
                        : static_cast<int>(
                              std::thread::hardware_concurrency());
  if (nt > 16) nt = 16;
  if (nt < 1) nt = 1;
  if (nt == 1 || n == 1) {
    char* out = static_cast<char*>(dst);
    for (long i = 0; i < n; ++i) {
      std::memcpy(out + i * bytes, srcs[i], bytes);
    }
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve(nt);
  long per = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    long begin = t * per;
    long end = begin + per < n ? begin + per : n;
    if (begin >= end) break;
    workers.emplace_back([=]() {
      char* out = static_cast<char*>(dst);
      for (long i = begin; i < end; ++i) {
        std::memcpy(out + i * bytes, srcs[i], bytes);
      }
    });
  }
  for (auto& w : workers) w.join();
}

// Interleaved gather for scalar labels: dst[i] = *(int64 srcs[i]).
void gather_i64(const long long** srcs, long n, long long* dst) {
  for (long i = 0; i < n; ++i) dst[i] = *srcs[i];
}

}  // extern "C"
