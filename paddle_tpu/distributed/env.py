"""Distributed environment state.

On TPU there is one controller process per host and the device mesh carries
parallelism (vs. the reference's one-process-per-GPU PADDLE_TRAINER_* env,
ref: /root/reference/python/paddle/distributed/launch/controllers/
collective.py:97-125). Rank/world_size here describe the *logical* position
used by samplers and fleet topology; they are derived from the active
HybridCommunicateGroup when fleet is initialized, else from jax process
env."""
from __future__ import annotations

import os

_state = {
    "initialized": False,
    "hcg": None,
}


def set_hcg(hcg):
    _state["hcg"] = hcg


def get_hcg():
    return _state["hcg"]


def mark_initialized():
    _state["initialized"] = True


def is_initialized():
    return _state["initialized"]


def get_rank():
    import jax
    if _state["hcg"] is not None:
        return _state["hcg"].get_global_rank()
    return int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))


def get_world_size():
    import jax
    if _state["hcg"] is not None:
        return _state["hcg"].get_world_size()
    env = os.environ.get("PADDLE_TRAINERS_NUM")
    return int(env) if env else jax.process_count()


def get_data_world_size():
    """Size of the data-parallel axis (sharding × dp under hybrid)."""
    if _state["hcg"] is not None:
        return (_state["hcg"].get_data_parallel_world_size()
                * _state["hcg"].get_sharding_parallel_world_size())
    return get_world_size()


def get_data_rank():
    if _state["hcg"] is not None:
        return (_state["hcg"].get_data_parallel_rank()
                * _state["hcg"].get_sharding_parallel_world_size()
                + _state["hcg"].get_sharding_parallel_rank())
    return get_rank()
