"""Launcher CLI: python -m paddle_tpu.distributed.launch [...] train.py

ref: /root/reference/python/paddle/distributed/launch/main.py +
controllers/collective.py:37,97-125 (build_pod computes PADDLE_TRAINER_*
env and spawns one worker per device; master KV rendezvous in
controllers/master.py).

TPU single-controller model: ONE process per HOST drives all local chips
through the mesh, so --devices selects chips, --nnodes/--master configure
jax.distributed for multi-host pods, and per-device worker processes are
unnecessary. The PADDLE_TRAINER_* env is still exported for scripts that
read it."""
from __future__ import annotations

import argparse
import os
import runpy
import sys


def _parse():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--master", default=None,
                   help="master endpoint ip:port for multi-host rendezvous")
    p.add_argument("--nnodes", default="1")
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    p.add_argument("--nproc_per_node", type=int, default=None)
    p.add_argument("--devices", "--gpus", "--xpus", default=None,
                   help="chip ids to use, e.g. 0,1,2,3")
    p.add_argument("--job_id", default="default")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--run_mode", default="collective")
    p.add_argument("--server_num", default=None)
    p.add_argument("--trainer_num", default=None)
    p.add_argument("--elastic_level", type=int, default=-1)
    p.add_argument("script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def launch():
    args = _parse()
    nnodes = int(str(args.nnodes).split(":")[0])

    if args.devices:
        ids = args.devices.split(",")
        os.environ["TPU_VISIBLE_DEVICES"] = args.devices
        os.environ["CUDA_VISIBLE_DEVICES"] = args.devices

    os.environ.setdefault("PADDLE_TRAINER_ID", str(args.rank))
    os.environ.setdefault("PADDLE_TRAINERS_NUM", str(nnodes))
    if args.master:
        os.environ["PADDLE_MASTER"] = args.master
        host, port = args.master.split(":")
        os.environ.setdefault("MASTER_ADDR", host)
        os.environ.setdefault("MASTER_PORT", port)

    if args.elastic_level >= 1 and (args.nproc_per_node or 1) > 1:
        # supervisor mode (ref ElasticManager relaunch, manager.py:220):
        # spawn nproc workers, relaunch the pod when one dies
        from ..fleet.elastic import ElasticSupervisor
        nproc = args.nproc_per_node
        cmds, envs = [], []
        for r in range(nproc):
            env = dict(os.environ)
            env["PADDLE_TRAINER_ID"] = str(r)
            env["PADDLE_TRAINERS_NUM"] = str(nproc)
            cmds.append([sys.executable, args.script] + args.script_args)
            envs.append(env)
        sys.exit(ElasticSupervisor(cmds, envs).run())

    if args.master and nnodes > 1:
        import jax
        jax.distributed.initialize(args.master, num_processes=nnodes,
                                   process_id=args.rank)

    sys.argv = [args.script] + args.script_args
    runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    launch()
