"""RPC between training workers (ref: /root/reference/python/paddle/
distributed/rpc/rpc.py — init_rpc:73, rpc_sync:141, rpc_async:179,
barrier/store plumbing :38-71).

The reference builds this on brpc + a TCPStore master. TPU-native rebuild:
the control plane stays entirely on the host (RPC never touches the
device graph), so this is a pure-Python implementation over
multiprocessing.connection — a rank-0 registry Listener plays the
reference's master store, and each worker serves calls on its own
Listener in a daemon thread. Works same-host and cross-host (TCP), authenticated with a
shared authkey derived from the master endpoint.
"""
from __future__ import annotations

import os
import threading
import time
from collections import namedtuple
from concurrent.futures import ThreadPoolExecutor
from multiprocessing.connection import Client, Listener
from typing import Any, Dict, List, Optional

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_DEFAULT_RPC_TIMEOUT = 30.0

_state: Dict[str, Any] = {
    "inited": False, "name": None, "rank": None, "world_size": None,
    "workers": {}, "service": None, "master": None, "authkey": None,
    "pool": None,
}


def _auth(master_endpoint: str, rank: int = 0) -> bytes:
    """Connection authkey. The service EXECUTES PICKLED CALLABLES, so the
    key must never be derivable from the (public) endpoint — on a shared
    host any local user can reach 127.0.0.1:<port>. Cross-host: set
    PADDLE_RPC_AUTHKEY to the same random value on every worker (the
    launcher does this for spawned jobs). Loopback without the env var:
    rank 0 generates a random secret and shares it through a user-only
    (0600) keyfile — same user, same trust boundary."""
    secret = os.environ.get("PADDLE_RPC_AUTHKEY")
    if secret:
        return secret.encode()
    host = master_endpoint.rsplit(":", 1)[0]
    if host not in ("127.0.0.1", "localhost", "::1"):
        raise RuntimeError(
            "cross-host rpc needs PADDLE_RPC_AUTHKEY set (a shared "
            "random secret): an endpoint-derived key would let any host "
            "that can reach the service port execute code in the "
            "trainer process")
    import hashlib
    import secrets
    import tempfile
    tag = hashlib.sha256(master_endpoint.encode()).hexdigest()[:16]
    path = os.path.join(tempfile.gettempdir(),
                        f"paddle_tpu_rpc_{os.getuid()}_{tag}.key")
    if rank == 0:
        key = secrets.token_bytes(32)
        try:
            os.remove(path)
        except FileNotFoundError:
            pass
        except PermissionError:
            raise RuntimeError(
                f"rpc keyfile {path} exists and belongs to another user — "
                "refusing the shared-tempdir key; set PADDLE_RPC_AUTHKEY")
        try:
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
        except FileExistsError:
            raise RuntimeError(
                f"rpc keyfile {path} reappeared (another process owns "
                "it); set PADDLE_RPC_AUTHKEY for this job")
        with os.fdopen(fd, "wb") as f:
            f.write(key)
        _state["keyfile"] = path
        return key
    deadline = time.time() + _DEFAULT_RPC_TIMEOUT
    while True:
        try:
            st = os.stat(path)
            if st.st_uid != os.getuid():
                raise RuntimeError(
                    f"rpc keyfile {path} owned by another user — refusing "
                    "the shared-tempdir key; set PADDLE_RPC_AUTHKEY")
            with open(path, "rb") as f:
                key = f.read()
            if len(key) == 32:
                return key
        except FileNotFoundError:
            pass
        if time.time() > deadline:
            raise TimeoutError(
                f"init_rpc: rank 0 never published the rpc keyfile {path}")
        time.sleep(0.05)


class _MasterRegistry(threading.Thread):
    """Rank-0 registry: collects WorkerInfos, hands the full table to each
    worker once all ranks registered (the reference's barrier store)."""

    def __init__(self, endpoint, world_size, authkey):
        super().__init__(daemon=True)
        ip, port = endpoint.rsplit(":", 1)
        self._listener = Listener((ip, int(port)), authkey=authkey)
        self._world = world_size
        self._infos: Dict[int, WorkerInfo] = {}
        self._cv = threading.Condition()
        self._stop = False
        self._barrier_count = 0

    def run(self):
        from multiprocessing import AuthenticationError
        while not self._stop:
            try:
                conn = self._listener.accept()
            except AuthenticationError:
                # a peer dialed in with a wrong/stale key — drop THAT
                # connection, keep serving (the peer re-reads the keyfile
                # and retries; dying here would hang every rank)
                continue
            except (OSError, EOFError):
                break
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            msg = conn.recv()
            if msg[0] == "register":
                info = WorkerInfo(*msg[1])
                with self._cv:
                    self._infos[info.rank] = info
                    self._cv.notify_all()
                    self._cv.wait_for(
                        lambda: len(self._infos) >= self._world
                        or self._stop)
                conn.send(sorted(self._infos.values(),
                                 key=lambda w: w.rank))
            elif msg[0] == "barrier":
                # shutdown barrier (the reference's barrier store): no
                # worker tears its service down before every worker is
                # done issuing RPCs
                with self._cv:
                    self._barrier_count += 1
                    self._cv.notify_all()
                    self._cv.wait_for(
                        lambda: self._barrier_count >= self._world
                        or self._stop)
                conn.send("go")
            elif msg[0] == "stop":
                self.stop()
        except (EOFError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop = True
        with self._cv:
            self._cv.notify_all()
        try:
            self._listener.close()
        except OSError:
            pass


class _Service(threading.Thread):
    """Per-worker request server: recv (fn, args, kwargs) → run → reply."""

    def __init__(self, authkey, bind_ip="127.0.0.1"):
        super().__init__(daemon=True)
        self._listener = Listener((bind_ip, 0), authkey=authkey)
        self.port = self._listener.address[1]
        self._stop = False

    def run(self):
        from multiprocessing import AuthenticationError
        while not self._stop:
            try:
                conn = self._listener.accept()
            except AuthenticationError:
                # a peer dialed in with a wrong/stale key — drop THAT
                # connection, keep serving (the peer re-reads the keyfile
                # and retries; dying here would hang every rank)
                continue
            except (OSError, EOFError):
                break
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            fn, args, kwargs = conn.recv()
            try:
                result = fn(*args, **kwargs)
                conn.send(("ok", result))
            except Exception as e:  # ship the failure to the caller
                conn.send(("err", e))
        except (EOFError, OSError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop = True
        try:
            self._listener.close()
        except OSError:
            pass


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None):
    """ref rpc.py:73 — start this worker's service and rendezvous through
    the master registry. rank/world_size/master_endpoint fall back to
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER_ENDPOINT."""
    if _state["inited"]:
        raise RuntimeError("init_rpc called twice")
    rank = int(os.environ.get("PADDLE_TRAINER_ID", -1)) \
        if rank is None else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", -1)) \
        if world_size is None else world_size
    master_endpoint = os.environ.get("PADDLE_MASTER_ENDPOINT",
                                     master_endpoint) \
        if master_endpoint is None else master_endpoint
    if rank < 0 or world_size <= 0 or not master_endpoint:
        raise ValueError("init_rpc needs name, rank, world_size and "
                         "master_endpoint (args or PADDLE_* env)")
    authkey = _auth(master_endpoint, rank)

    master = None
    if rank == 0:
        master = _MasterRegistry(master_endpoint, world_size, authkey)
        master.start()

    # cross-host: when the master is not loopback, advertise the IP this
    # host uses to reach it (overridable with PADDLE_LOCAL_IP) and bind
    # the service on all interfaces so peers can dial in
    mhost = master_endpoint.rsplit(":", 1)[0]
    loopback = mhost in ("127.0.0.1", "localhost", "::1")
    my_ip = os.environ.get("PADDLE_LOCAL_IP")
    if my_ip is None:
        if loopback:
            my_ip = "127.0.0.1"
        else:
            import socket
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.connect((mhost, 1))
                my_ip = s.getsockname()[0]
    service = _Service(authkey,
                       bind_ip="127.0.0.1" if loopback else "0.0.0.0")
    service.start()
    info = (name, rank, my_ip, service.port)

    # register with the master (retry while rank 0 comes up)
    from multiprocessing import AuthenticationError
    mhost, mport = master_endpoint.rsplit(":", 1)
    deadline = time.time() + _DEFAULT_RPC_TIMEOUT
    workers: List[WorkerInfo] = []
    while True:
        try:
            conn = Client((mhost, int(mport)), authkey=authkey)
            conn.send(("register", info))
            workers = conn.recv()
            conn.close()
            break
        except AuthenticationError:
            # a stale keyfile from a previous job: rank 0 republishes on
            # startup, so re-read the key and restart our service with it
            if time.time() > deadline:
                service.stop()
                raise TimeoutError(
                    f"init_rpc: authentication with master "
                    f"{master_endpoint} kept failing (stale key?)")
            time.sleep(0.1)
            new_key = _auth(master_endpoint, rank)
            if new_key != authkey:
                authkey = new_key
                service.stop()
                service = _Service(
                    authkey,
                    bind_ip="127.0.0.1" if loopback else "0.0.0.0")
                service.start()
                info = (name, rank, my_ip, service.port)
        except (ConnectionError, OSError):
            if time.time() > deadline:
                service.stop()
                raise TimeoutError(
                    f"init_rpc: cannot reach master {master_endpoint}")
            time.sleep(0.05)

    _state.update(inited=True, name=name, rank=rank,
                  world_size=world_size, service=service, master=master,
                  authkey=authkey, master_endpoint=master_endpoint,
                  workers={w.name: w for w in workers},
                  pool=ThreadPoolExecutor(max_workers=8))


def _require_init():
    if not _state["inited"]:
        raise RuntimeError("call paddle.distributed.rpc.init_rpc first")


def rpc_sync(to: str, fn, args=None, kwargs=None,
             timeout=_DEFAULT_RPC_TIMEOUT):
    """ref rpc.py:141 — run fn(*args, **kwargs) on worker `to`, return the
    result (raises the remote exception locally)."""
    _require_init()
    w = _state["workers"].get(to)
    if w is None:
        raise ValueError(f"unknown rpc worker {to!r}; known: "
                         f"{sorted(_state['workers'])}")
    conn = Client((w.ip, w.port), authkey=_state["authkey"])
    try:
        conn.send((fn, tuple(args or ()), dict(kwargs or {})))
        if timeout and timeout > 0 and not conn.poll(timeout):
            raise TimeoutError(f"rpc to {to!r} timed out after {timeout}s")
        status, payload = conn.recv()
    finally:
        conn.close()
    if status == "err":
        raise payload
    return payload


def rpc_async(to: str, fn, args=None, kwargs=None,
              timeout=_DEFAULT_RPC_TIMEOUT):
    """ref rpc.py:179 — returns a future with wait()/result()."""
    _require_init()
    fut = _state["pool"].submit(rpc_sync, to, fn, args, kwargs, timeout)
    fut.wait = fut.result  # paddle's FutureWrapper API
    return fut


def get_worker_info(name: str) -> WorkerInfo:
    _require_init()
    return _state["workers"][name]


def get_all_worker_infos() -> List[WorkerInfo]:
    _require_init()
    return sorted(_state["workers"].values(), key=lambda w: w.rank)


def get_current_worker_info() -> WorkerInfo:
    _require_init()
    return _state["workers"][_state["name"]]


def shutdown():
    """ref rpc.py shutdown — barrier across workers (nobody stops serving
    while a peer may still call in), then stop the service (and the
    registry on rank 0)."""
    if not _state["inited"]:
        return
    if _state["pool"] is not None:
        _state["pool"].shutdown(wait=True)
    if _state["world_size"] > 1:
        mhost, mport = _state["master_endpoint"].rsplit(":", 1)
        try:
            conn = Client((mhost, int(mport)), authkey=_state["authkey"])
            conn.send(("barrier",))
            conn.recv()
            conn.close()
        except (ConnectionError, OSError, EOFError):
            pass  # master already gone: best effort
    if _state["service"] is not None:
        _state["service"].stop()
    if _state["master"] is not None:
        _state["master"].stop()
        keyfile = _state.get("keyfile")
        if keyfile:                     # rank 0: retire the job's keyfile
            try:
                os.remove(keyfile)
            except OSError:
                pass
    _state.update(inited=False, name=None, rank=None, world_size=None,
                  workers={}, service=None, master=None, authkey=None,
                  pool=None)
