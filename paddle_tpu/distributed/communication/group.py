"""Communication groups.

A reference ProcessGroup is a NCCL communicator over a rank list
(ref: /root/reference/paddle/fluid/distributed/collective/process_group.h:53).
Here a Group names a mesh axis (or an ad-hoc 1-D mesh over chosen devices);
collectives over the group are XLA collectives over that axis."""
from __future__ import annotations

from typing import List, Optional

import jax

from ...parallel import mesh as mesh_mod


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    def __init__(self, ranks: List[int], gid: int = 0, axis: Optional[str] = None,
                 name: Optional[str] = None):
        self.ranks = list(ranks)
        self.id = gid
        self.axis = axis          # mesh axis name when axis-aligned
        self._name = name or f"group_{gid}"

    @property
    def nranks(self) -> int:
        return len(self.ranks)

    @property
    def world_size(self) -> int:
        return len(self.ranks)

    @property
    def name(self):
        return self._name

    @property
    def process_group(self):
        return self

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def rank(self):
        from .. import env
        return self.get_group_rank(env.get_rank())

    def __repr__(self):
        return f"Group(id={self.id}, axis={self.axis}, ranks={self.ranks})"


_groups = {}
_group_counter = [0]
_world_group: Optional[Group] = None


def _new_group_id():
    _group_counter[0] += 1
    return _group_counter[0]


def get_world_group() -> Group:
    global _world_group
    if _world_group is None:
        n = len(jax.devices())
        _world_group = Group(list(range(n)), 0, axis=None, name="world")
    return _world_group


def new_group(ranks=None, backend=None, timeout=None, axis=None) -> Group:
    if ranks is None:
        return get_world_group()
    g = Group(list(ranks), _new_group_id(), axis=axis)
    _groups[g.id] = g
    return g


def axis_group(axis: str, ranks: List[int]) -> Group:
    g = Group(ranks, _new_group_id(), axis=axis, name=f"{axis}_group")
    _groups[g.id] = g
    return g


def _resolve(group: Optional[Group]) -> Group:
    return group if group is not None else get_world_group()
