from .group import Group, ReduceOp, get_world_group, new_group  # noqa: F401
from .ops import (P2POp, all_gather, all_gather_object, all_reduce,  # noqa: F401
                  all_to_all, alltoall, alltoall_single, barrier,
                  batch_isend_irecv, broadcast, irecv, isend, recv, reduce,
                  reduce_scatter, scatter, send, wait)

# stream variants (ref: python/paddle/distributed/communication/stream/) —
# XLA issues collectives asynchronously already; sync_op is accepted and
# completion is exposed via wait().
class stream:
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    all_to_all = staticmethod(alltoall)
    alltoall = staticmethod(alltoall)
    alltoall_single = staticmethod(alltoall_single)
    broadcast = staticmethod(broadcast)
    reduce = staticmethod(reduce)
    reduce_scatter = staticmethod(reduce_scatter)
    scatter = staticmethod(scatter)
    send = staticmethod(send)
    recv = staticmethod(recv)
