"""Collective communication API (ref: /root/reference/python/paddle/
distributed/communication/ — all_reduce.py, all_gather.py, ...; C++ kernels
paddle/fluid/distributed/collective/process_group_nccl.cc:174).

Two execution contexts:
1. Inside a shard_map per-device region (how fleet layers / pipeline
   schedules use them): lowered to lax.psum / all_gather / ppermute /
   all_to_all over the group's mesh axis — XLA collectives on ICI.
2. Eager on global arrays: the array is interpreted as carrying per-rank
   values along the group axis (sharded) and the collective is run as a
   jitted shard_map over the global mesh. Replicated inputs are already
   "synchronized" in the GSPMD world, so sum-reduce of a replicated tensor
   is the identity (the reference's allreduce-of-synced-grads pattern).
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ...framework.op import unwrap, wrap
from ...framework.tensor import Tensor
from ...parallel import mesh as mesh_mod
from .group import Group, ReduceOp, _resolve, get_world_group


def _axis_of(group: Group) -> Optional[str]:
    return group.axis


def _in_spmd(axis: str) -> bool:
    return mesh_mod.inside_spmd_region(axis) if axis else False


def _reduce_fn(op):
    return {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
            ReduceOp.MIN: jax.lax.pmin,
            "sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin,
            }.get(op, jax.lax.psum)


def _sharded_axis(t, axis):
    """Which dim of the global array is sharded over `axis`, or None."""
    arr = unwrap(t)
    shd = getattr(arr, "sharding", None)
    if isinstance(shd, NamedSharding):
        for i, s in enumerate(shd.spec):
            names = s if isinstance(s, tuple) else (s,)
            if axis in [n for n in names if n]:
                return i
    return None


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    group = _resolve(group)
    axis = _axis_of(group)
    if axis and _in_spmd(axis):
        out = _reduce_fn(op)(unwrap(tensor), axis)
        if isinstance(tensor, Tensor):
            tensor._data = out
            return tensor
        return wrap(out)
    arr0 = unwrap(tensor)
    from jax.sharding import SingleDeviceSharding
    if jax.process_count() > 1 and isinstance(
            getattr(arr0, "sharding", None), SingleDeviceSharding):
        # true multi-controller: each process holds a process-LOCAL value
        # (single-device array); lift to a global [n_devices, ...] array
        # over the group axis (world maps to 'dp'), reduce under jit
        # (Gloo/ICI collective), read the replicated result back. This is
        # the ProcessGroup::AllReduce semantic of the reference
        # (process_group_nccl.cc:174). Global/replicated jax.Arrays fall
        # through to the GSPMD path below, where allreduce-of-synced
        # values is the identity.
        import numpy as _np
        ax = axis or "dp"
        mesh = mesh_mod.get_mesh()
        n = mesh.shape[ax]
        if n == 1:
            # size-1 group: reduce is the identity regardless of the
            # rest of the mesh
            return tensor
        if any(v > 1 for k, v in mesh.shape.items() if k != ax):
            # On a hybrid mesh the per-process addressable extent along
            # `ax` is not local_device_count, and — worse — a group
            # reduce over `ax` has a DIFFERENT result per coordinate of
            # the other axes, which this single-global-value path cannot
            # represent. Hybrid groups must reduce inside the jitted
            # SPMD region instead.
            raise NotImplementedError(
                f"multi-controller eager all_reduce needs group axis "
                f"{ax!r} to span the whole mesh (1-D world); on a hybrid "
                f"mesh {dict(mesh.shape)} run the collective inside the "
                f"jitted SPMD region (jax.lax.psum under shard_map/jit)")
        local_n = jax.local_device_count()
        a = _np.asarray(arr0)
        if op not in (ReduceOp.SUM, ReduceOp.AVG, ReduceOp.MAX,
                      ReduceOp.MIN):
            raise NotImplementedError(
                f"multi-process all_reduce op {op!r} is not supported")
        # each process contributes its value on local_n device rows
        # (dtype-preserving: no pre-scaling); SUM over-counts by local_n
        # and is corrected after the reduce — exactly divisible, so
        # integer tensors keep their dtype. AVG/MAX/MIN need no
        # correction (each process is equally over-represented).
        tile = _np.broadcast_to(a[None], (local_n,) + a.shape)
        gs = NamedSharding(mesh, PartitionSpec(ax))
        garr = jax.make_array_from_process_local_data(
            gs, _np.ascontiguousarray(tile), (n,) + tuple(a.shape))
        word = {ReduceOp.SUM: jnp.sum, ReduceOp.MAX: jnp.max,
                ReduceOp.MIN: jnp.min, ReduceOp.AVG: jnp.mean}[op]
        out = jax.jit(lambda g: word(g, axis=0),
                      out_shardings=NamedSharding(
                          mesh, PartitionSpec()))(garr)
        if op == ReduceOp.SUM and local_n > 1:
            if jnp.issubdtype(out.dtype, jnp.integer):
                out = out // local_n
            else:
                out = out / local_n
        local = jnp.asarray(out.addressable_data(0))
        if isinstance(tensor, Tensor):
            tensor._data = local
            return tensor
        return wrap(local)
    # eager/global view
    dim = _sharded_axis(tensor, axis) if axis else None
    if dim is None:
        # replicated along the group ⇒ values already equal; SUM of shared
        # value across a synced group is the value itself in global view
        return tensor
    arr = unwrap(tensor)
    mesh = mesh_mod.get_mesh()
    from jax.experimental.shard_map import shard_map
    spec = [None] * arr.ndim
    spec[dim] = axis
    in_spec = PartitionSpec(*spec)

    def body(a):
        return _reduce_fn(op)(a, axis)

    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(in_spec,),
                           out_specs=PartitionSpec(*([None] * arr.ndim))))
    out = fn(arr)
    tensor._data = out
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    group = _resolve(group)
    gaxis = _axis_of(group)
    if gaxis and _in_spmd(gaxis):
        out = jax.lax.all_gather(unwrap(tensor), gaxis)
        parts = [wrap(out[i]) for i in range(out.shape[0])]
        if tensor_list is not None:
            tensor_list.extend(parts)
        return parts
    # global view: tensor is either sharded over gaxis (gather its shards) or
    # replicated (every "rank" holds the same value)
    n = group.nranks
    parts = [Tensor(unwrap(tensor)) for _ in range(n)] if \
        _sharded_axis(tensor, gaxis) is None else _split_shards(tensor, gaxis)
    if tensor_list is not None:
        tensor_list.extend(parts)
    return parts


def _split_shards(tensor, axis):
    arr = unwrap(tensor)
    dim = _sharded_axis(tensor, axis)
    n = mesh_mod.mesh_axis_size(axis)
    size = arr.shape[dim] // n
    return [Tensor(jax.lax.slice_in_dim(arr, i * size, (i + 1) * size, axis=dim))
            for i in range(n)]


def all_gather_object(object_list, obj, group=None):
    group = _resolve(group)
    object_list.extend([obj] * group.nranks)


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    group = _resolve(group)
    axis = _axis_of(group)
    src = tensor_list if tensor_list is not None else tensor
    if axis and _in_spmd(axis):
        if isinstance(src, (list, tuple)):
            stacked = jnp.stack([unwrap(t) for t in src])
        else:
            stacked = unwrap(src)
        out = jax.lax.psum_scatter(stacked, axis, scatter_dimension=0,
                                   tiled=False)
        tensor._data = out
        return tensor
    return tensor


def broadcast(tensor, src=0, group=None, sync_op=True):
    group = _resolve(group)
    axis = _axis_of(group)
    if axis and _in_spmd(axis):
        arr = unwrap(tensor)
        src_rank = group.get_group_rank(src) if src in group.ranks else src
        idx = jax.lax.axis_index(axis)
        # select src's value: gather then index (XLA folds this)
        gathered = jax.lax.all_gather(arr, axis)
        tensor._data = gathered[src_rank]
        return tensor
    # global view: replicated arrays are already equal on all ranks
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    group = _resolve(group)
    if tensor_list:
        idx = group.rank() if group.rank() >= 0 else 0
        tensor._data = unwrap(tensor_list[idx])
    return tensor


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    group = _resolve(group)
    axis = _axis_of(group)
    if axis and _in_spmd(axis):
        stacked = jnp.stack([unwrap(t) for t in in_tensor_list])
        out = jax.lax.all_to_all(stacked, axis, split_axis=0, concat_axis=0,
                                 tiled=False)
        parts = [wrap(out[i]) for i in range(out.shape[0])]
        if out_tensor_list is not None:
            out_tensor_list.extend(parts)
        return parts
    if out_tensor_list is not None:
        out_tensor_list.extend(in_tensor_list)
    return in_tensor_list


all_to_all = alltoall


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    group = _resolve(group)
    axis = _axis_of(group)
    if axis and _in_spmd(axis):
        out = jax.lax.all_to_all(unwrap(in_tensor), axis, split_axis=0,
                                 concat_axis=0, tiled=True)
        out_tensor._data = out
        return out_tensor
    out_tensor._data = unwrap(in_tensor)
    return out_tensor


def send(tensor, dst=0, group=None, sync_op=True):
    group = _resolve(group)
    axis = _axis_of(group)
    if axis and _in_spmd(axis):
        # point-to-point on TPU = ppermute ring shift
        n = group.nranks
        perm = [(i, dst if n == 0 else (i + 1) % n) for i in range(n)]
        return wrap(jax.lax.ppermute(unwrap(tensor), axis, perm))
    return tensor


def recv(tensor, src=0, group=None, sync_op=True):
    return tensor


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group)


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    results = []
    for p in p2p_op_list:
        results.append(p.op(p.tensor, p.peer, p.group))
    return results


def barrier(group=None):
    jax.effects_barrier() if hasattr(jax, "effects_barrier") else None
    for d in jax.devices():
        pass
    return None


def wait(tensor, group=None, use_calc_stream=True):
    u = unwrap(tensor)
    if hasattr(u, "block_until_ready"):
        u.block_until_ready()
    return tensor
