"""paddle.distributed.passes (ref: /root/reference/python/paddle/
distributed/passes/pass_base.py — PassContext:20, new_pass:133,
PassManager:353; the auto_parallel_* passes rewrite per-rank
ProgramDescs).

TPU mapping: program rewriting is XLA's job. The pass OBJECTS exist with
the reference's registry/apply API so strategy code ports unchanged, and
each pass records what GSPMD/XLA mechanism supersedes it; passes with a
live equivalent route to it (sharding → optimizer-state PartitionSpecs).
"""
from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["new_pass", "PassManager", "PassContext", "PassBase",
           "register_pass"]

_REGISTRY: Dict[str, type] = {}


def register_pass(name):
    def wrap(cls):
        cls.name = name
        _REGISTRY[name] = cls
        return cls
    return wrap


class PassContext:
    """ref pass_base.py:20."""

    def __init__(self):
        self._attrs = {}
        self._applied_passes = []

    def set_attr(self, key, value):
        self._attrs[key] = value

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)


class PassBase:
    """ref pass_base.py PassBase — check_enabled + apply contract."""

    name = "base"
    # what replaces this pass on the TPU backend (shown in repr/logs)
    tpu_equivalent = "handled by XLA/GSPMD compilation"

    def __init__(self):
        self._attrs = {}

    def set_attr(self, key, value):
        self._attrs[key] = value
        return self

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)

    def check_enabled(self):
        return True

    def apply(self, main_programs, startup_programs=None, context=None):
        """Default: the transformation is performed by the compiler; the
        pass records itself in the context and leaves the program
        untouched (programs here are traced jax computations — there is
        no per-op IR to edit)."""
        if context is not None:
            context._applied_passes.append(self.name)
        return main_programs

    def __repr__(self):
        return f"<Pass {self.name!r} (tpu: {self.tpu_equivalent})>"


@register_pass("auto_parallel_amp")
class _AmpPass(PassBase):
    tpu_equivalent = "amp.auto_cast policy + bf16-native compute"

    def apply(self, main_programs, startup_programs=None, context=None):
        from ...amp.auto_cast import amp_state
        st = amp_state()
        if self.get_attr("custom_white_list"):
            st.white = set(st.white) | set(self.get_attr(
                "custom_white_list"))
        if self.get_attr("custom_black_list"):
            st.black = set(st.black) | set(self.get_attr(
                "custom_black_list"))
        return super().apply(main_programs, startup_programs, context)


@register_pass("auto_parallel_sharding")
class _ShardingPass(PassBase):
    tpu_equivalent = ("optimizer-state PartitionSpecs over the "
                      "'sharding' mesh axis")

    def apply(self, main_programs, startup_programs=None, context=None):
        opt = self.get_attr("optimizer")
        if opt is not None:
            from ..fleet.meta_parallel.sharding import shard_accumulators
            shard_accumulators(opt)
        return super().apply(main_programs, startup_programs, context)


@register_pass("auto_parallel_recompute")
class _RecomputePass(PassBase):
    tpu_equivalent = "jax.checkpoint on the marked segments"


@register_pass("auto_parallel_gradient_merge_pass")
class _GradientMergePass(PassBase):
    tpu_equivalent = "fleet.meta_optimizers GradientMergeOptimizer"


@register_pass("auto_parallel_fp16")
class _Fp16Pass(_AmpPass):
    tpu_equivalent = "bf16 compute dtype (fp16 maps to bf16 on TPU)"


@register_pass("fuse_optimizer")
class _FuseOptimizerPass(PassBase):
    tpu_equivalent = "the optimizer's fused jitted update (_make_fused)"


@register_pass("fused_attention")
class _FusedAttentionPass(PassBase):
    tpu_equivalent = "pallas flash attention via nn.functional"


@register_pass("fused_feedforward")
class _FusedFeedforwardPass(PassBase):
    tpu_equivalent = "XLA elementwise-into-GEMM fusion"


def new_pass(name, pass_attrs: Optional[dict] = None):
    """ref pass_base.py:133."""
    cls = _REGISTRY.get(name)
    if cls is None:
        # unknown passes still construct (the reference registry is
        # open-ended); they apply as compiler-handled no-ops
        cls = type(f"_GenericPass_{name}", (PassBase,), {"name": name})
    p = cls()
    for k, v in (pass_attrs or {}).items():
        p.set_attr(k, v)
    return p


class PassManager:
    """ref pass_base.py:353 — ordered pass application."""

    def __init__(self, passes: List[PassBase]):
        self._passes = list(passes)
        self._context = PassContext()

    @property
    def context(self):
        return self._context

    @property
    def names(self):
        return [p.name for p in self._passes]

    def apply(self, main_programs, startup_programs=None):
        for p in self._passes:
            if p.check_enabled():
                p.apply(main_programs, startup_programs, self._context)
        return main_programs
