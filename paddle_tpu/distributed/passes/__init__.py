"""paddle.distributed.passes (ref: /root/reference/python/paddle/
distributed/passes/pass_base.py — PassContext:20, new_pass:133,
PassManager:353; the auto_parallel_* passes rewrite per-rank
ProgramDescs).

TPU mapping: program rewriting is XLA's job. The pass OBJECTS exist with
the reference's registry/apply API so strategy code ports unchanged, and
each pass records what GSPMD/XLA mechanism supersedes it; passes with a
live equivalent route to it (sharding → optimizer-state PartitionSpecs).
"""
from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["new_pass", "PassManager", "PassContext", "PassBase",
           "register_pass"]

_REGISTRY: Dict[str, type] = {}


def register_pass(name):
    def wrap(cls):
        cls.name = name
        _REGISTRY[name] = cls
        return cls
    return wrap


class PassContext:
    """ref pass_base.py:20."""

    def __init__(self):
        self._attrs = {}
        self._applied_passes = []

    def set_attr(self, key, value):
        self._attrs[key] = value

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)


class PassBase:
    """ref pass_base.py PassBase — check_enabled + apply contract."""

    name = "base"
    # what replaces this pass on the TPU backend (shown in repr/logs)
    tpu_equivalent = "handled by XLA/GSPMD compilation"

    def __init__(self):
        self._attrs = {}

    def set_attr(self, key, value):
        self._attrs[key] = value
        return self

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)

    def check_enabled(self):
        return True

    def apply(self, main_programs, startup_programs=None, context=None):
        """Default: the transformation is performed by the compiler; the
        pass records itself in the context and leaves the program
        untouched (programs here are traced jax computations — there is
        no per-op IR to edit)."""
        if context is not None:
            context._applied_passes.append(self.name)
        return main_programs

    def __repr__(self):
        return f"<Pass {self.name!r} (tpu: {self.tpu_equivalent})>"


@register_pass("auto_parallel_amp")
class _AmpPass(PassBase):
    tpu_equivalent = "amp.auto_cast policy + bf16-native compute"

    def apply(self, main_programs, startup_programs=None, context=None):
        from ...amp.auto_cast import amp_state
        st = amp_state()
        if self.get_attr("custom_white_list"):
            st.white = set(st.white) | set(self.get_attr(
                "custom_white_list"))
        if self.get_attr("custom_black_list"):
            st.black = set(st.black) | set(self.get_attr(
                "custom_black_list"))
        return super().apply(main_programs, startup_programs, context)


@register_pass("auto_parallel_sharding")
class _ShardingPass(PassBase):
    tpu_equivalent = ("optimizer-state PartitionSpecs over the "
                      "'sharding' mesh axis")

    def apply(self, main_programs, startup_programs=None, context=None):
        opt = self.get_attr("optimizer")
        if opt is not None:
            from ..fleet.meta_parallel.sharding import shard_accumulators
            shard_accumulators(opt)
        return super().apply(main_programs, startup_programs, context)


@register_pass("auto_parallel_recompute")
class _RecomputePass(PassBase):
    """Wraps the marked layers so their forward runs under jax.checkpoint
    (ref auto_parallel_recompute.py — segment rewrite into
    recompute blocks). attrs: model (Layer), optional segments (list of
    sublayer names or Layer objects; default: the whole model)."""

    tpu_equivalent = "jax.checkpoint on the marked segments"

    def apply(self, main_programs, startup_programs=None, context=None):
        model = self.get_attr("model")
        if model is not None:
            from ...nn.layer.layers import Layer
            segments = self.get_attr("segments") \
                or self.get_attr("checkpoints") or [model]
            resolved = []
            for s in segments:
                if isinstance(s, str):
                    sub = dict(model.named_sublayers()).get(s)
                    if sub is None:
                        raise ValueError(
                            f"recompute pass: no sublayer named {s!r}")
                    resolved.append(sub)
                elif isinstance(s, Layer):
                    resolved.append(s)
            for lyr in resolved:
                _wrap_layer_recompute(lyr)
        return super().apply(main_programs, startup_programs, context)


def _wrap_layer_recompute(lyr):
    if getattr(lyr, "_recompute_wrapped", False):
        return
    from ...nn.layer.layers import Layer
    from ..fleet.recompute import recompute

    class _Seg(Layer):
        """Parameter-carrying shim so recompute() traces the segment's
        params as checkpoint inputs (gradients flow)."""

        def __init__(self, inner, orig):
            super().__init__()
            self._inner = inner          # registers params via sublayer
            self._orig = orig

        def forward(self, *a, **kw):
            return self._orig(*a, **kw)

    seg = _Seg(lyr, lyr.forward)

    def fwd(*a, **kw):
        return recompute(seg, *a, **kw)

    lyr.forward = fwd
    lyr._recompute_wrapped = True


@register_pass("auto_parallel_gradient_merge_pass")
class _GradientMergePass(PassBase):
    """Wraps attr 'optimizer' in GradientMergeOptimizer(k_steps, avg) and
    publishes it as context attr 'optimizer' (ref
    auto_parallel_gradient_merge.py — the reference rewrites the program
    to accumulate grads k steps; here accumulation is the tape's native
    behavior and merging = the wrapper's deferred step)."""

    tpu_equivalent = "fleet.meta_optimizers GradientMergeOptimizer"

    def apply(self, main_programs, startup_programs=None, context=None):
        opt = self.get_attr("optimizer")
        if opt is not None:
            from ..fleet.meta_optimizers.gradient_merge import \
                GradientMergeOptimizer
            merged = GradientMergeOptimizer(
                opt, k_steps=int(self.get_attr("k_steps", 2) or 2),
                avg=bool(self.get_attr("avg", True)))
            self.merged_optimizer = merged
            if context is not None:
                context.set_attr("optimizer", merged)
        return super().apply(main_programs, startup_programs, context)


@register_pass("auto_parallel_fp16")
class _Fp16Pass(_AmpPass):
    tpu_equivalent = "bf16 compute dtype (fp16 maps to bf16 on TPU)"


@register_pass("fuse_optimizer")
class _FuseOptimizerPass(PassBase):
    """Pre-compiles attr 'optimizer's fused jitted update for the current
    parameter set (the mechanism the reference's fuse_optimizer pass
    builds per-program; here the optimizer always steps through
    _make_fused — this pass warms that compile)."""

    tpu_equivalent = "the optimizer's fused jitted update (_make_fused)"

    def apply(self, main_programs, startup_programs=None, context=None):
        opt = self.get_attr("optimizer")
        if opt is not None:
            opt.prebuild_fused()
        return super().apply(main_programs, startup_programs, context)


@register_pass("fused_attention")
class _FusedAttentionPass(PassBase):
    """Forces the Pallas flash-attention route: turns the kernel flag on
    and widens the AMP white list so attention matmuls take the MXU path
    (ref fused_attention_pass.cc pattern-match; here routing is a flag
    read by nn.functional.scaled_dot_product_attention)."""

    tpu_equivalent = "pallas flash attention via nn.functional"

    def apply(self, main_programs, startup_programs=None, context=None):
        from ...flags import set_flags
        set_flags({"FLAGS_enable_pallas_kernels": True})
        from ...amp.auto_cast import amp_state
        st = amp_state()
        st.white = set(st.white) | {"flash_attention", "attention"}
        return super().apply(main_programs, startup_programs, context)


@register_pass("fused_feedforward")
class _FusedFeedforwardPass(PassBase):
    """Routes every nn.TransformerEncoderLayer in attr 'model' through
    incubate.nn.functional.fused_feedforward (one fused FFN expression:
    (pre/post-)LN + linear + act + dropout + linear + dropout + residual
    — ref fused_feedforward_op.cu schedule)."""

    tpu_equivalent = "incubate fused_feedforward / XLA GEMM fusion"

    def apply(self, main_programs, startup_programs=None, context=None):
        model = self.get_attr("model")
        if model is not None:
            from ...nn.layer.transformer import TransformerEncoderLayer
            targets = [model] if isinstance(
                model, TransformerEncoderLayer) else [
                s for _, s in model.named_sublayers()
                if isinstance(s, TransformerEncoderLayer)]
            for lyr in targets:
                _wrap_layer_fused_ffn(lyr)
        return super().apply(main_programs, startup_programs, context)


def _wrap_layer_fused_ffn(lyr):
    if getattr(lyr, "_fused_ffn", False):
        return
    act_name = getattr(lyr.activation, "__name__", "relu")

    def fwd(src, src_mask=None, cache=None, _l=lyr):
        from ...incubate.nn import functional as IF
        residual = src
        if _l.normalize_before:
            src = _l.norm1(src)
        if cache is None:
            src = _l.self_attn(src, src, src, src_mask)
        else:
            src, cache = _l.self_attn(src, src, src, src_mask, cache)
        src = residual + _l.dropout1(src)
        if not _l.normalize_before:
            src = _l.norm1(src)
        src = IF.fused_feedforward(
            src, _l.linear1.weight, _l.linear2.weight,
            linear1_bias=_l.linear1.bias, linear2_bias=_l.linear2.bias,
            ln1_scale=_l.norm2.weight if _l.normalize_before else None,
            ln1_bias=_l.norm2.bias if _l.normalize_before else None,
            ln2_scale=None if _l.normalize_before else _l.norm2.weight,
            ln2_bias=None if _l.normalize_before else _l.norm2.bias,
            dropout1_rate=_l.dropout.p, dropout2_rate=_l.dropout2.p,
            activation=act_name,
            ln1_epsilon=getattr(_l.norm2, "_epsilon", 1e-5),
            ln2_epsilon=getattr(_l.norm2, "_epsilon", 1e-5),
            pre_layer_norm=_l.normalize_before, training=_l.training)
        return src if cache is None else (src, cache)

    lyr.forward = fwd
    lyr._fused_ffn = True


def new_pass(name, pass_attrs: Optional[dict] = None):
    """ref pass_base.py:133."""
    cls = _REGISTRY.get(name)
    if cls is None:
        # unknown passes still construct (the reference registry is
        # open-ended); they apply as compiler-handled no-ops
        cls = type(f"_GenericPass_{name}", (PassBase,), {"name": name})
    p = cls()
    for k, v in (pass_attrs or {}).items():
        p.set_attr(k, v)
    return p


class PassManager:
    """ref pass_base.py:353 — ordered pass application."""

    def __init__(self, passes: List[PassBase]):
        self._passes = list(passes)
        self._context = PassContext()

    @property
    def context(self):
        return self._context

    @property
    def names(self):
        return [p.name for p in self._passes]

    def apply(self, main_programs, startup_programs=None):
        for p in self._passes:
            if p.check_enabled():
                p.apply(main_programs, startup_programs, self._context)
        return main_programs
