"""paddle.distributed.utils (ref: /root/reference/python/paddle/
distributed/utils/__init__.py)."""
from .log_utils import get_logger  # noqa: F401
from .moe_utils import global_gather, global_scatter  # noqa: F401

__all__ = ["get_logger", "global_scatter", "global_gather"]
