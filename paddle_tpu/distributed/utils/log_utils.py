"""ref: /root/reference/python/paddle/distributed/utils/log_utils.py."""
import logging

__all__ = ["get_logger"]


def get_logger(log_level="INFO", name="paddle_tpu.distributed"):
    logger = logging.getLogger(name)
    if isinstance(log_level, str):
        log_level = getattr(logging, log_level.upper())
    logger.setLevel(log_level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s-%(levelname)s: %(message)s"))
        logger.addHandler(h)
    return logger
