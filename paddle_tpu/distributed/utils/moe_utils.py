"""MoE token dispatch (ref: /root/reference/python/paddle/distributed/
utils/moe_utils.py — global_scatter:20 / global_gather:146, the
variable-count all-to-all under the reference MoELayer).

TPU design note: XLA requires STATIC shapes, so the production dispatch
path is the capacity-padded all-to-all in `incubate.moe` (GShard) — the
exact design the GShard/Switch papers use on TPU. These functions keep
the reference's count-based API for porting:

  * single-process (no jax.distributed world): exact semantics via
    repeat/gather on the host-traced counts — counts define a
    permutation, no communication needed.
  * multi-process: raises, pointing at incubate.moe's static-shape
    dispatch (variable-count send/recv cannot compile to one XLA
    program).
"""
from __future__ import annotations

import numpy as np

from ...framework.op import apply
from ...framework.tensor import Tensor

__all__ = ["global_scatter", "global_gather"]


def _world_size():
    import jax
    return jax.process_count()


def _require_single_process(op):
    if _world_size() > 1:
        raise NotImplementedError(
            f"{op} with variable per-expert counts cannot compile to a "
            f"static-shape XLA program across processes; use the "
            f"capacity-padded dispatch in paddle.incubate.moe (MoELayer/"
            f"GShard all-to-all), which is the TPU-native equivalent")


def global_scatter(x, local_count, global_count, group=None,
                   use_calc_stream=True):
    """ref moe_utils.py:20. Single-process: rows of x are taken in
    expert order — local_count[i] rows go to expert (i % n_expert) —
    which equals receiving global_count in the same order."""
    _require_single_process("global_scatter")
    lc = np.asarray(local_count.numpy()
                    if isinstance(local_count, Tensor) else local_count)
    # expert-major concatenation of the count-segmented rows of x
    starts = np.concatenate([[0], np.cumsum(lc)[:-1]])
    order = []
    n = lc.shape[0]
    for i in range(n):
        order.extend(range(int(starts[i]), int(starts[i] + lc[i])))
    idx = np.asarray(order, np.int32)

    def impl(a):
        return a[idx] if idx.size else a[:0]
    return apply(impl, (x,), op_name="global_scatter")


def global_gather(x, local_count, global_count, group=None,
                  use_calc_stream=True):
    """ref moe_utils.py:146 — the inverse permutation of
    global_scatter."""
    _require_single_process("global_gather")
    lc = np.asarray(local_count.numpy()
                    if isinstance(local_count, Tensor) else local_count)
    starts = np.concatenate([[0], np.cumsum(lc)[:-1]])
    order = []
    n = lc.shape[0]
    for i in range(n):
        order.extend(range(int(starts[i]), int(starts[i] + lc[i])))
    idx = np.asarray(order, np.int32)
    inv = np.empty_like(idx)
    inv[idx] = np.arange(idx.size, dtype=np.int32)

    def impl(a):
        return a[inv] if inv.size else a[:0]
    return apply(impl, (x,), op_name="global_gather")
