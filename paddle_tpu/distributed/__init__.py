"""paddle.distributed (ref: /root/reference/python/paddle/distributed/
__init__.py). NCCL ProcessGroups → jax Mesh axes; collectives → XLA
collectives over ICI/DCN (SURVEY.md §5)."""
from . import env  # noqa: F401
from . import fleet  # noqa: F401
from .communication import (Group, P2POp, ReduceOp, all_gather,  # noqa: F401
                            all_gather_object, all_reduce, all_to_all,
                            alltoall, alltoall_single, barrier,
                            batch_isend_irecv, broadcast, get_world_group,
                            irecv, isend, new_group, recv, reduce,
                            reduce_scatter, scatter, send, stream, wait)
from .parallel import (DataParallel, get_rank, get_world_size,  # noqa: F401
                       init_parallel_env)
from . import sharding  # noqa: F401
from . import auto_parallel  # noqa: F401
from .auto_parallel import (ProcessMesh, Replicate, Shard, dtensor_from_fn,  # noqa: F401
                            reshard, shard_tensor)
from . import checkpoint  # noqa: F401
from . import launch  # noqa: F401
from . import rpc  # noqa: F401
from . import passes  # noqa: F401
from . import utils  # noqa: F401
from . import io  # noqa: F401
from .utils import global_gather, global_scatter  # noqa: F401


def is_initialized():
    return env.is_initialized()


def is_available():
    return True


def get_backend(group=None):
    return "xla"


def destroy_process_group(group=None):
    pass


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """ref: python/paddle/distributed/spawn.py:426. In the single-controller
    TPU runtime parallelism lives in the mesh, not processes: run func once;
    it sees all devices."""
    func(*args)
    return None
