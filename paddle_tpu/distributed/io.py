"""paddle.distributed.io (ref: /root/reference/python/paddle/
distributed/io.py — save/load_persistables + distributed
save_inference_model). Single-controller GSPMD: every process holds the
global (sharded) arrays, so the distributed save IS the sharded
checkpoint writer in distributed.checkpoint; these wrappers keep the
reference entry points."""
from __future__ import annotations

__all__ = ["save_persistables", "load_persistables",
           "is_persistable"]


def is_persistable(var):
    return bool(getattr(var, "persistable", False))


def save_persistables(executor, dirname, main_program=None, filename=None):
    from ..framework.io import save
    import os
    os.makedirs(dirname, exist_ok=True)
    if main_program is None:
        from ..framework.symbolic import default_main_program
        main_program = default_main_program()
    state = {}
    for t in getattr(main_program, "_state_updates", []):
        target = t[0]
        if is_persistable(target):
            state[target.name] = target
    save(state, os.path.join(dirname, filename or "persistables.pdparams"))
    return state


def load_persistables(executor, dirname, main_program=None, filename=None):
    from ..framework.io import load
    import os
    return load(os.path.join(dirname,
                             filename or "persistables.pdparams"))
