"""DataParallel + init_parallel_env (ref: /root/reference/python/paddle/
distributed/parallel.py — DataParallel:186, init_parallel_env:915,
TCPStore rendezvous :1076).

On TPU the data-parallel contract — per-rank batches, gradients averaged
across ranks before the update (reference's EagerReducer fused-allreduce,
paddle/fluid/distributed/collective/reducer.cc:741,1048) — is delivered by
GSPMD: the global batch is sharded over the 'dp' mesh axis and the mean
loss's gradient IS the dp-averaged gradient. DataParallel therefore shards
inputs and keeps the reference API (scale_loss, no_sync) as light shims.
Multi-host: init_parallel_env maps to jax.distributed.initialize."""
from __future__ import annotations

import contextlib
import os

import jax
from jax.sharding import PartitionSpec

from ..framework.op import apply
from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer
from ..parallel import mesh as mesh_mod
from . import env as dist_env


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        # make sure a mesh exists with a dp axis covering local devices
        mesh_mod.get_mesh()

    def forward(self, *inputs, **kwargs):
        inputs = tuple(self._shard_input(x) for x in inputs)
        return self._layers(*inputs, **kwargs)

    def _shard_input(self, x):
        if isinstance(x, Tensor) and x.ndim > 0 and \
                mesh_mod.mesh_axis_size("dp") > 1 and \
                x.shape[0] % mesh_mod.mesh_axis_size("dp") == 0:
            spec = [None] * x.ndim
            spec[0] = "dp"
            x._data = mesh_mod.shard_tensor_data(x.data,
                                                 PartitionSpec(*spec))
        return x

    def scale_loss(self, loss):
        # grads are already dp-averaged under GSPMD (mean loss over the
        # global batch); kept for API parity (ref: parallel.py scale_loss)
        return loss

    @contextlib.contextmanager
    def no_sync(self):
        yield

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


def init_parallel_env():
    """ref: parallel.py:915 — on TPU pods this is jax.distributed.initialize
    driven by the launcher's env; single-host it just installs the default
    mesh."""
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get(
        "MASTER_ADDR")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    # NB: do not call jax.process_count() here — it would initialize the
    # backend and make jax.distributed.initialize impossible
    already = jax.distributed.is_initialized()
    if coord and nprocs > 1 and not already:
        port = os.environ.get("MASTER_PORT", "8476")
        addr = coord if ":" in coord else f"{coord}:{port}"
        try:
            jax.distributed.initialize(addr, num_processes=nprocs,
                                       process_id=rank)
        except Exception as e:
            # a dead rendezvous must be loud: silently continuing would
            # train nprocs independent replicas
            raise RuntimeError(
                f"jax.distributed.initialize({addr!r}, num_processes="
                f"{nprocs}, process_id={rank}) failed") from e
    mesh_mod.get_mesh()
    dist_env.mark_initialized()
    from .communication.group import get_world_group
    return get_world_group()


def get_rank(group=None):
    if group is not None:
        return group.rank()
    return dist_env.get_rank()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return dist_env.get_world_size()
