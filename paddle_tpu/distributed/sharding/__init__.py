"""paddle.distributed.sharding (ref: /root/reference/python/paddle/
distributed/sharding/group_sharded.py)."""
from ..fleet.meta_parallel.sharding import (group_sharded_parallel,  # noqa: F401
                                            save_group_sharded_model)
