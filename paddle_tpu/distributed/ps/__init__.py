"""Parameter-server API stubs (ref: /root/reference/python/paddle/
distributed/ps/the_one_ps.py + paddle/fluid/distributed/ps/ — the brpc
PS, HeterPS and BoxPS stacks).

DESCOPED BY DESIGN (SURVEY.md §7): the brpc/GPU parameter server is a
CUDA-cluster-specific serving of huge sparse embeddings; the TPU-native
counterpart is sharded embeddings over the mesh (mp/sharding axes) with
XLA all-to-all — see fleet.layers.mpu.VocabParallelEmbedding and the
'sharding' axis in models/llama_spmd. These stubs keep the reference's
import surface alive so PS-mode scripts fail at RUN time with a pointed
message, not at import."""
from __future__ import annotations

__all__ = ["TheOnePSRuntime", "PsProgramBuilder", "DistributedInfer",
           "ParameterServerRuntime"]

_MSG = ("the brpc/Heter parameter server is descoped on TPU "
        "(SURVEY.md §7): use mesh-sharded embeddings "
        "(paddle_tpu.distributed.fleet.layers.mpu.VocabParallelEmbedding "
        "or the auto_parallel 'sharding' axis) instead of PS tables")


class _PsStub:
    def __init__(self, *args, **kwargs):
        pass

    def _raise(self):
        raise NotImplementedError(_MSG)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)

        def method(*a, **kw):
            raise NotImplementedError(_MSG)
        return method


class TheOnePSRuntime(_PsStub):
    """ref: distributed/ps/the_one_ps.py."""


class ParameterServerRuntime(_PsStub):
    """ref: fleet/runtime/the_one_ps.py."""


class PsProgramBuilder(_PsStub):
    """ref: distributed/ps/utils/ps_program_builder.py."""


class DistributedInfer(_PsStub):
    """ref: distributed/ps/utils/public.py DistributedInfer."""
