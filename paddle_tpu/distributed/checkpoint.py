"""Distributed checkpointing (ref: /root/reference — per-rank save/load with
PP/TP remapping fleet/utils/pp_parallel_adaptor.py; auto-parallel
dist_saver.py + converter.py reshard checkpoints across meshes).

TPU-native: orbax sharded, async-capable checkpointing of global arrays.
Because parameters are GLOBAL logical tensors (not per-rank shards), the
reference's pp/tp re-mapping adaptors reduce to loading with a different
NamedSharding — restore takes the target mesh/sharding and orbax reshards."""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..framework.tensor import Tensor


def _flatten_state(state_dict):
    return {k: (v.data if isinstance(v, Tensor) else v)
            for k, v in state_dict.items()}


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    async_save: bool = False):
    """Sharded save of a (possibly distributed) state dict."""
    try:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.abspath(path), _flatten_state(state_dict),
                   force=True)
        return
    except Exception:
        # portable fallback: gather to host + pickle
        from ..framework.io import save
        save(state_dict, os.path.join(path, "state.pdparams")
             if os.path.isdir(path) or not path.endswith(".pdparams")
             else path)


def load_state_dict(path: str, target_state_dict=None, shardings=None):
    """Load; if `target_state_dict` given, restore INTO its tensors keeping
    their current shardings (cross-mesh reshard happens here)."""
    try:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        if target_state_dict is not None:
            targets = {
                k: jax.ShapeDtypeStruct(
                    tuple(v.shape), np.dtype(v.dtype),
                    sharding=v.data.sharding if hasattr(v.data, "sharding")
                    else None)
                for k, v in target_state_dict.items()
                if isinstance(v, Tensor)}
            restored = ckptr.restore(
                os.path.abspath(path),
                restore_args=jax.tree_util.tree_map(
                    lambda s: ocp.ArrayRestoreArgs(
                        sharding=s.sharding, global_shape=s.shape,
                        dtype=s.dtype), targets))
            for k, v in restored.items():
                if k in target_state_dict:
                    target_state_dict[k]._data = v
            return target_state_dict
        return {k: Tensor(v) for k, v in ckptr.restore(
            os.path.abspath(path)).items()}
    except Exception:
        from ..framework.io import load
        p = os.path.join(path, "state.pdparams") if not \
            path.endswith(".pdparams") else path
        state = load(p)
        if target_state_dict is not None:
            for k, v in state.items():
                if k in target_state_dict:
                    target_state_dict[k].set_value(v)
            return target_state_dict
        return state


class PPParallelAdaptor:
    """ref: fleet/utils/pp_parallel_adaptor.py — remap a checkpoint saved
    under one pp/tp layout to another. Global-view checkpoints make this a
    key-rename + reshard exercise."""

    @staticmethod
    def convert(state_dict, src_pp=1, dst_pp=1, layer_key="layers"):
        # keys are layout-independent in the global view; pass through
        return state_dict
