"""Distributed checkpointing (ref: /root/reference — per-rank save/load with
PP/TP remapping fleet/utils/pp_parallel_adaptor.py; auto-parallel
dist_saver.py + converter.py reshard checkpoints across meshes).

TPU-native: orbax sharded, async-capable checkpointing of global arrays.
Because parameters are GLOBAL logical tensors (not per-rank shards), the
reference's mesh re-mapping reduces to restoring with a different
NamedSharding — ``load_state_dict(target_state_dict=...)`` reshards into
the targets' current shardings, whatever mesh they live on. The pickle
fallback is used ONLY when orbax is not importable; format dispatch at
load time is by on-disk layout, never by swallowing orbax errors."""
from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Union

import jax
import numpy as np

from ..framework.tensor import Tensor


def _flatten_state(state_dict):
    return {k: (v.data if isinstance(v, Tensor) else v)
            for k, v in state_dict.items()}


def _pickle_path(path: str) -> str:
    return path if path.endswith(".pdparams") \
        else os.path.join(path, "state.pdparams")


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    async_save: bool = False):
    """Sharded save of a (possibly distributed) state dict."""
    try:
        import orbax.checkpoint as ocp
    except ImportError:
        # portable fallback: gather to host + pickle
        from ..framework.io import save
        save(state_dict, _pickle_path(path))
        return
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.abspath(path), _flatten_state(state_dict),
               force=True)


def load_state_dict(path: str, target_state_dict=None, shardings=None):
    """Load; if `target_state_dict` given, restore INTO its tensors keeping
    their current shardings (cross-mesh reshard happens here: save under
    mesh A, restore under mesh B — orbax reads global arrays and lays
    them out per the requested sharding)."""
    if os.path.exists(_pickle_path(path)):
        # pickle-format checkpoint (written by the no-orbax fallback)
        from ..framework.io import load
        state = load(_pickle_path(path))
        if target_state_dict is not None:
            for k, v in state.items():
                if k in target_state_dict:
                    target_state_dict[k].set_value(v)
            return target_state_dict
        return state
    try:
        import orbax.checkpoint as ocp
    except ImportError:
        raise ImportError(
            f"checkpoint at {path!r} is an orbax sharded checkpoint but "
            f"orbax.checkpoint is not importable in this environment")
    ckptr = ocp.PyTreeCheckpointer()
    if target_state_dict is not None:
        targets = {
            k: jax.ShapeDtypeStruct(
                tuple(v.shape), np.dtype(v.dtype),
                sharding=v.data.sharding if hasattr(v.data, "sharding")
                else None)
            for k, v in target_state_dict.items()
            if isinstance(v, Tensor)}
        restored = ckptr.restore(
            os.path.abspath(path),
            restore_args=jax.tree_util.tree_map(
                lambda s: ocp.ArrayRestoreArgs(
                    sharding=s.sharding, global_shape=s.shape,
                    dtype=s.dtype), targets))
        for k, v in restored.items():
            if k in target_state_dict:
                target_state_dict[k]._data = v
        return target_state_dict
    return {k: Tensor(v) for k, v in ckptr.restore(
        os.path.abspath(path)).items()}


class PPParallelAdaptor:
    """ref: fleet/utils/pp_parallel_adaptor.py — remap checkpoints between
    pipeline layouts. The reference's PipelineLayer saves per-stage state
    dicts whose ``<layer_key>.<i>.*`` indices are STAGE-LOCAL; converting
    src_pp -> dst_pp renumbers through the global layer index assuming the
    contiguous balanced partition (the reference's default 'uniform' seg
    method; np.array_split semantics). Non-layer keys (embeddings, heads)
    ride on stage 0, matching the reference's shared-weight placement."""

    @staticmethod
    def _bounds(n_layers: int, pp: int) -> List[int]:
        sizes = [len(c) for c in np.array_split(np.arange(n_layers), pp)]
        bounds = [0]
        for s in sizes:
            bounds.append(bounds[-1] + s)
        return bounds

    @classmethod
    def to_global(cls, stage_dicts: List[Dict[str, Any]],
                  layer_key: str = "layers") -> Dict[str, Any]:
        """Merge per-stage state dicts (stage-local layer indices) into
        one global-view dict."""
        pat = re.compile(rf"^{re.escape(layer_key)}\.(\d+)\.(.*)$")
        counts = [len({int(m.group(1)) for k in sd
                       if (m := pat.match(k)) is not None})
                  for sd in stage_dicts]
        bounds = [0]
        for c in counts:
            bounds.append(bounds[-1] + c)
        out: Dict[str, Any] = {}
        for stage, sd in enumerate(stage_dicts):
            for k, v in sd.items():
                m = pat.match(k)
                if m is None:
                    out.setdefault(k, v)
                    continue
                g = bounds[stage] + int(m.group(1))
                out[f"{layer_key}.{g}.{m.group(2)}"] = v
        return out

    @classmethod
    def convert(cls, state_dict: Union[Dict[str, Any],
                                       List[Dict[str, Any]]],
                src_pp: int = 1, dst_pp: int = 1,
                layer_key: str = "layers"):
        """Remap ``state_dict`` saved under ``src_pp`` pipeline stages to
        ``dst_pp`` stages. A list input is per-stage dicts (stage-local
        indices); a single dict is the global view (src_pp must be 1).
        Returns a list of ``dst_pp`` per-stage dicts, or the global dict
        when ``dst_pp == 1``."""
        if isinstance(state_dict, list):
            if len(state_dict) != src_pp:
                raise ValueError(
                    f"PPParallelAdaptor.convert: got {len(state_dict)} "
                    f"stage dicts but src_pp={src_pp}")
            global_sd = cls.to_global(state_dict, layer_key)
        else:
            if src_pp != 1:
                raise ValueError(
                    "PPParallelAdaptor.convert: a single state dict is "
                    "the global view; pass the per-stage dicts as a list "
                    "when src_pp > 1")
            global_sd = dict(state_dict)
        if dst_pp == 1:
            return global_sd
        pat = re.compile(rf"^{re.escape(layer_key)}\.(\d+)\.(.*)$")
        layer_ids = {int(m.group(1)) for k in global_sd
                     if (m := pat.match(k)) is not None}
        n_layers = (max(layer_ids) + 1) if layer_ids else 0
        bounds = cls._bounds(n_layers, dst_pp)
        stages: List[Dict[str, Any]] = [dict() for _ in range(dst_pp)]
        for k, v in global_sd.items():
            m = pat.match(k)
            if m is None:
                stages[0][k] = v
                continue
            g = int(m.group(1))
            stage = int(np.searchsorted(bounds, g, side="right") - 1)
            local = g - bounds[stage]
            stages[stage][f"{layer_key}.{local}.{m.group(2)}"] = v
        return stages
