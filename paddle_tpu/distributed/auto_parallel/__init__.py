"""Semi-auto parallel (ref: /root/reference/python/paddle/distributed/
auto_parallel/ + C++ core paddle/fluid/distributed/auto_parallel/
dist_attr.h:51 TensorDistAttr{process_mesh, dims_mapping}).

The reference's pipeline — Completion propagates dims_mapping over ops
(completion.py), Partitioner rewrites per-rank programs, Resharder inserts
comm ops (reshard.py) — IS GSPMD (see PAPERS.md): here DistAttr maps to a
jax NamedSharding, propagation/partitioning/resharding are done by XLA's
SPMD partitioner, and `reshard` is a device_put/with_sharding_constraint."""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...framework.tensor import Tensor
from ...parallel import mesh as mesh_mod

__all__ = ["ProcessMesh", "TensorDistAttr", "shard_tensor", "dtensor_from_fn",
           "reshard", "shard_op", "Engine", "Strategy", "get_mesh",
           "Shard", "Replicate", "Partial"]


class Shard:
    """Placement: shard tensor dim `dim` over the mesh axis it is paired
    with (ref: new-style placements in later paddle; equivalent to
    dims_mapping entries)."""

    def __init__(self, dim):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate:
    def __repr__(self):
        return "Replicate()"


class Partial:
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type


class ProcessMesh:
    """ref: auto_parallel/process_mesh.py. Wraps a jax Mesh over the chosen
    device ids."""

    def __init__(self, mesh, dim_names=None, process_ids=None):
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.process_ids = arr.reshape(-1).tolist()
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self.dim_names = list(dim_names)
        devices = np.asarray(jax.devices())
        dev_grid = devices[np.asarray(self.process_ids) % len(devices)]
        self._jax_mesh = Mesh(dev_grid.reshape(arr.shape),
                              tuple(self.dim_names))

    @property
    def mesh(self):
        return np.asarray(self.process_ids).reshape(self.shape)

    @property
    def jax_mesh(self):
        return self._jax_mesh

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            self.process_ids == other.process_ids and \
            self.shape == other.shape

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


class TensorDistAttr:
    """ref: dist_attr.h:51 — {process_mesh, dims_mapping}; dims_mapping[i]
    is the mesh dim tensor-dim i is sharded over (-1 = replicated)."""

    def __init__(self, process_mesh=None, dims_mapping=None):
        self.process_mesh = process_mesh
        self.dims_mapping = dims_mapping or []

    def to_partition_spec(self) -> PartitionSpec:
        names = []
        for m in self.dims_mapping:
            if m is None or m == -1:
                names.append(None)
            else:
                names.append(self.process_mesh.dim_names[m])
        return PartitionSpec(*names)

    def __repr__(self):
        return (f"TensorDistAttr(mesh={self.process_mesh}, "
                f"dims_mapping={self.dims_mapping})")


def _placements_to_spec(mesh: ProcessMesh, placements) -> PartitionSpec:
    ndim = max((p.dim for p in placements if isinstance(p, Shard)),
               default=-1) + 1
    spec = {}
    for axis_idx, p in enumerate(placements):
        if isinstance(p, Shard):
            spec[p.dim] = mesh.dim_names[axis_idx]
    max_dim = max(spec.keys(), default=-1)
    return PartitionSpec(*[spec.get(i) for i in range(max_dim + 1)])


def shard_tensor(x, process_mesh=None, placements=None, dims_mapping=None,
                 dist_attr=None, stop_gradient=None):
    """Place a Tensor on a mesh (ref: auto_parallel/api shard_tensor)."""
    if not isinstance(x, Tensor):
        x = Tensor(np.asarray(x))
    if dist_attr is not None:
        process_mesh = dist_attr.process_mesh
        spec = dist_attr.to_partition_spec()
    elif placements is not None:
        spec = _placements_to_spec(process_mesh, placements)
    elif dims_mapping is not None:
        spec = TensorDistAttr(process_mesh, dims_mapping).to_partition_spec()
    else:
        spec = PartitionSpec()
    jmesh = process_mesh.jax_mesh if process_mesh is not None \
        else mesh_mod.get_mesh()
    x._data = jax.device_put(x.data, NamedSharding(jmesh, spec))
    x._dist_attr = TensorDistAttr(process_mesh, dims_mapping)
    x.is_distributed = True
    return x


def dtensor_from_fn(fn, process_mesh, placements, *args, **kwargs):
    t = fn(*args, **kwargs)
    return shard_tensor(t, process_mesh, placements)


def reshard(x, process_mesh=None, placements=None, dist_attr=None):
    """Move a tensor to a new sharding — the reference inserts comm ops via
    Resharder (reshard.py, 3k LoC); here it is one resharding device_put
    (XLA generates the collective)."""
    return shard_tensor(x, process_mesh, placements, dist_attr=dist_attr)


def _to_spec(process_mesh, s) -> Optional[PartitionSpec]:
    """Accept a PartitionSpec, a placements list, a TensorDistAttr, or a
    dims_mapping list."""
    if s is None:
        return None
    if isinstance(s, PartitionSpec):
        return s
    if isinstance(s, TensorDistAttr):
        return s.to_partition_spec()
    if isinstance(s, (list, tuple)):
        if any(isinstance(p, (Shard, Replicate, Partial)) for p in s):
            return _placements_to_spec(process_mesh, s)
        return TensorDistAttr(process_mesh, list(s)).to_partition_spec()
    raise TypeError(f"shard_op: cannot interpret sharding {s!r}")


def shard_op(op_fn, process_mesh=None, in_shardings=None,
             out_shardings=None):
    """ref: auto_parallel shard_op — annotate an op with input/output
    dist attrs. GSPMD-native: each annotation becomes a sharding
    constraint (lax.with_sharding_constraint under trace, a placing
    device_put eagerly); XLA's partitioner inserts the collectives the
    reference's Resharder would."""
    jmesh = process_mesh.jax_mesh if process_mesh is not None else None

    def constrain(v, s):
        spec = _to_spec(process_mesh, s)
        if spec is None:
            return v
        mesh = jmesh if jmesh is not None else mesh_mod.get_mesh()
        sharding = NamedSharding(mesh, spec)
        arr = v.data if isinstance(v, Tensor) else v
        if isinstance(arr, jax.core.Tracer):
            out = jax.lax.with_sharding_constraint(arr, sharding)
        else:
            out = jax.device_put(arr, sharding)
        if isinstance(v, Tensor):
            v._data = out
            return v
        return out

    def apply_shardings(vals, shardings):
        if shardings is None:
            return vals
        if not isinstance(shardings, (list, tuple)):
            shardings = [shardings]
        return tuple(
            constrain(v, shardings[i]) if i < len(shardings) else v
            for i, v in enumerate(vals))

    def wrapper(*args, **kwargs):
        args = apply_shardings(args, in_shardings)
        out = op_fn(*args, **kwargs)
        if out_shardings is None:
            return out
        if isinstance(out, (tuple, list)):
            res = apply_shardings(out, out_shardings)
            return type(out)(res) if isinstance(out, list) else res
        return apply_shardings((out,), out_shardings)[0]

    return wrapper


def get_mesh():
    return mesh_mod.get_mesh()


class Strategy:
    """ref: auto_parallel/strategy.py."""

    def __init__(self, config=None):
        from ..fleet.strategy import _Config
        self.amp = _Config(enable=False, dtype="bfloat16", level="O1")
        self.recompute = _Config(enable=False, checkpoints=None)
        self.sharding = _Config(enable=False, stage=1, degree=1)
        self.gradient_merge = _Config(enable=False, k_steps=1, avg=True)
        self.pipeline = _Config(enable=False, schedule_mode="1F1B",
                                micro_batch_size=1, accumulate_steps=1)
        self.fused_passes = _Config(enable=False, fused_passes_list=[])


class _PipelinedSequential:
    """Engine pipeline path: runs a homogeneous block list as a compiled
    spmd pipeline over the mesh's 'pp' axis (ref: engine.py _parallel
    applying the pipeline pass + fleet PipelineLayer segmentation).

    Wraps the ORIGINAL model object — parameters stay owned by the real
    sublayers (so TrainStep/optimizer see them unchanged); forward stacks
    the block params [n_stages, layers_per_stage, ...], micro-batches the
    input, and routes through parallel.pipeline.spmd_pipeline, which
    lowers to a collective-permute ring. Differentiation flows through
    the stacking, so backward/update remain the standard path."""

    def __init__(self, model, micro_batch_size: int):
        self._model = model
        subs = getattr(model, "_sub_layers", None)
        self._blocks = list(subs.values()) if subs else []
        if not self._blocks:
            raise ValueError(
                "Engine pipeline strategy needs a Sequential-style model "
                "(a flat list of structurally identical sublayers)")
        sig0 = [(n, tuple(p.shape), str(p.dtype))
                for n, p in self._blocks[0].named_parameters()]
        for b in self._blocks[1:]:
            sig = [(n, tuple(p.shape), str(p.dtype))
                   for n, p in b.named_parameters()]
            if sig != sig0:
                raise ValueError(
                    "Engine pipeline strategy needs structurally "
                    f"identical stages; got {sig0} vs {sig}")
        self.micro_batch_size = int(micro_batch_size)

    def named_parameters(self, *a, **k):
        return self._model.named_parameters(*a, **k)

    def parameters(self, *a, **k):
        return self._model.parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._model.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._model.set_state_dict(*a, **k)

    def __call__(self, x, *rest):
        import jax.numpy as jnp
        from ...parallel.pipeline import spmd_pipeline
        mesh = mesh_mod.get_mesh()
        n_stages = mesh.shape.get("pp", 1)
        blocks = self._blocks
        L = len(blocks)
        if L % max(n_stages, 1) != 0:
            raise ValueError(
                f"pipeline: {L} blocks not divisible by pp={n_stages}")
        per = L // max(n_stages, 1)
        names = [n for n, _ in blocks[0].named_parameters()]
        stacked = {}
        for name in names:
            leaves = [dict(b.named_parameters())[name].data
                      for b in blocks]
            arr = jnp.stack(leaves)  # [L, ...]
            stacked[name] = arr.reshape((n_stages, per) + arr.shape[1:])
        b0 = blocks[0]
        p0 = dict(b0.named_parameters())

        def one_block(pdict, xa):
            saved = {n: p._data for n, p in p0.items()}
            for n, p in p0.items():
                p._data = pdict[n]
            try:
                out = b0(Tensor(xa, stop_gradient=True))
                return out.data if isinstance(out, Tensor) else out
            finally:
                for n, p in p0.items():
                    p._data = saved[n]

        def stage_fn(chunk, xa):
            out, _ = jax.lax.scan(
                lambda c, sl: (one_block(sl, c), None), xa, chunk)
            return out

        xa = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        B = xa.shape[0]
        mb = self.micro_batch_size
        if B % mb != 0:
            raise ValueError(
                f"pipeline: batch {B} not divisible by micro_batch_size "
                f"{mb}")
        x_micro = xa.reshape((B // mb, mb) + xa.shape[1:])
        out = spmd_pipeline(stage_fn, stacked, x_micro, axis="pp")
        return Tensor(out.reshape((B,) + out.shape[2:]))


class Engine:
    """ref: auto_parallel/engine.py:55 — fit/evaluate/predict over an
    annotated model. _build/_plan/_parallel (engine.py:563,722,750) collapse
    into: trace once under jit with parameter NamedShardings; XLA completes
    and partitions. Strategy knobs are APPLIED in fit(): amp
    (auto_cast/decorate), gradient_merge (k-step device-side grad
    accumulation), sharding (ZeRO placement of optimizer states/params),
    pipeline (spmd_pipeline over the mesh's pp axis), recompute."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics
        self.strategy = strategy or Strategy()
        self._train_step = None

    def _loss_fn(self, layer, *batch):
        *inputs, label = batch if len(batch) > 1 else (batch[0], None)
        amp_cfg = self.strategy.amp
        if amp_cfg["enable"]:
            from ... import amp as amp_mod
            with amp_mod.auto_cast(enable=True, dtype=amp_cfg["dtype"],
                                   level=str(amp_cfg["level"]).upper()):
                out = layer(*inputs)
        else:
            out = layer(*inputs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        if self.loss is not None and label is not None:
            return self.loss(out, label)
        return out

    def fit(self, train_data, train_sample_split=None, batch_size=1,
            epochs=1, steps_per_epoch=None, log_freq=10, save_dir=None,
            save_freq=1, valid_data=None, valid_sample_split=None,
            valid_freq=1, valid_steps=None, collate_fn=None, callbacks=None,
            verbose=2, num_workers=0):
        from ...io import DataLoader
        from ...parallel.train_step import TrainStep
        strat = self.strategy
        if strat.recompute["enable"]:
            if hasattr(self.model, "config"):
                self.model.config.recompute = True
        model_for_step = self.model
        mesh = mesh_mod.get_mesh()
        if strat.pipeline["enable"] and dict(mesh.shape).get("pp", 1) > 1:
            model_for_step = _PipelinedSequential(
                self.model, strat.pipeline["micro_batch_size"])
        if strat.amp["enable"] and str(strat.amp["level"]).upper() == "O2":
            # O2: params live in the amp dtype (fp32 path via masters)
            from ...amp import decorate
            decorate(models=self.model, optimizers=self.optimizer,
                     level="O2", dtype=strat.amp["dtype"])
        shard_axis = None
        if strat.sharding["enable"]:
            from ..fleet.meta_parallel.sharding import (shard_accumulators,
                                                        shard_parameters)
            shard_axis = "sharding" \
                if mesh_mod.mesh_axis_size("sharding") > 1 else "dp"
            if int(strat.sharding["stage"]) >= 3:
                shard_parameters(self.model, axis=shard_axis)
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=True)
        k_steps = int(strat.gradient_merge["k_steps"]) \
            if strat.gradient_merge["enable"] else 1
        step_fn = TrainStep(model_for_step, self.optimizer,
                            loss_fn=self._loss_fn,
                            grad_accum_steps=k_steps,
                            grad_accum_avg=bool(
                                strat.gradient_merge["avg"]))
        if shard_axis is not None:
            # states were created by TrainStep above; place them sharded
            # (ZeRO-1/2 semantics — XLA partitions the update)
            shard_accumulators(self.optimizer, axis=shard_axis)
        self._train_step = step_fn
        history = {"loss": []}
        it = 0
        for epoch in range(epochs):
            for batch in loader:
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                loss = step_fn(*batch)
                history["loss"].append(float(loss.numpy()))
                it += 1
                if verbose and it % log_freq == 0:
                    print(f"[auto_parallel] epoch {epoch} step {it} "
                          f"loss {history['loss'][-1]:.4f}")
                if steps_per_epoch and it >= steps_per_epoch:
                    break
        step_fn.sync_to_layer()
        return history

    def evaluate(self, valid_data, valid_sample_split=None, batch_size=1,
                 steps=None, log_freq=10, collate_fn=None, callbacks=None,
                 verbose=2, num_workers=0):
        from ...io import DataLoader
        from ...framework.autograd import no_grad
        loader = valid_data if isinstance(valid_data, DataLoader) else \
            DataLoader(valid_data, batch_size=batch_size)
        losses = []
        with no_grad():
            for batch in loader:
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                loss = self._loss_fn(self.model, *batch)
                losses.append(float(loss.numpy()))
        return {"loss": float(np.mean(losses)) if losses else 0.0}

    def predict(self, test_data, test_sample_split=None, batch_size=1,
                steps=None, collate_fn=None, callbacks=None, verbose=2,
                num_workers=0):
        from ...io import DataLoader
        from ...framework.autograd import no_grad
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size)
        outs = []
        with no_grad():
            for batch in loader:
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                out = self.model(*batch)
                outs.append(out.numpy() if isinstance(out, Tensor)
                            else out[0].numpy())
        return outs

    def save(self, path, training=True):
        from ...framework.io import save
        save(self.model.state_dict(), path + ".pdparams")
        if training and self.optimizer is not None:
            save(self.optimizer.state_dict(), path + ".pdopt")

    def load(self, path, strict=True, load_optimizer=True):
        from ...framework.io import load
        import os
        self.model.set_state_dict(load(path + ".pdparams"))
        if load_optimizer and self.optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self.optimizer.set_state_dict(load(path + ".pdopt"))
