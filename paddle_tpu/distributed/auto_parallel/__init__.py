"""Semi-auto parallel (ref: /root/reference/python/paddle/distributed/
auto_parallel/ + C++ core paddle/fluid/distributed/auto_parallel/
dist_attr.h:51 TensorDistAttr{process_mesh, dims_mapping}).

The reference's pipeline — Completion propagates dims_mapping over ops
(completion.py), Partitioner rewrites per-rank programs, Resharder inserts
comm ops (reshard.py) — IS GSPMD (see PAPERS.md): here DistAttr maps to a
jax NamedSharding, propagation/partitioning/resharding are done by XLA's
SPMD partitioner, and `reshard` is a device_put/with_sharding_constraint."""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...framework.tensor import Tensor
from ...parallel import mesh as mesh_mod

__all__ = ["ProcessMesh", "TensorDistAttr", "shard_tensor", "dtensor_from_fn",
           "reshard", "shard_op", "Engine", "Strategy", "get_mesh",
           "Shard", "Replicate", "Partial"]


class Shard:
    """Placement: shard tensor dim `dim` over the mesh axis it is paired
    with (ref: new-style placements in later paddle; equivalent to
    dims_mapping entries)."""

    def __init__(self, dim):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate:
    def __repr__(self):
        return "Replicate()"


class Partial:
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type


class ProcessMesh:
    """ref: auto_parallel/process_mesh.py. Wraps a jax Mesh over the chosen
    device ids."""

    def __init__(self, mesh, dim_names=None, process_ids=None):
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.process_ids = arr.reshape(-1).tolist()
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self.dim_names = list(dim_names)
        devices = np.asarray(jax.devices())
        dev_grid = devices[np.asarray(self.process_ids) % len(devices)]
        self._jax_mesh = Mesh(dev_grid.reshape(arr.shape),
                              tuple(self.dim_names))

    @property
    def mesh(self):
        return np.asarray(self.process_ids).reshape(self.shape)

    @property
    def jax_mesh(self):
        return self._jax_mesh

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            self.process_ids == other.process_ids and \
            self.shape == other.shape

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


class TensorDistAttr:
    """ref: dist_attr.h:51 — {process_mesh, dims_mapping}; dims_mapping[i]
    is the mesh dim tensor-dim i is sharded over (-1 = replicated)."""

    def __init__(self, process_mesh=None, dims_mapping=None):
        self.process_mesh = process_mesh
        self.dims_mapping = dims_mapping or []

    def to_partition_spec(self) -> PartitionSpec:
        names = []
        for m in self.dims_mapping:
            if m is None or m == -1:
                names.append(None)
            else:
                names.append(self.process_mesh.dim_names[m])
        return PartitionSpec(*names)

    def __repr__(self):
        return (f"TensorDistAttr(mesh={self.process_mesh}, "
                f"dims_mapping={self.dims_mapping})")


def _placements_to_spec(mesh: ProcessMesh, placements) -> PartitionSpec:
    ndim = max((p.dim for p in placements if isinstance(p, Shard)),
               default=-1) + 1
    spec = {}
    for axis_idx, p in enumerate(placements):
        if isinstance(p, Shard):
            spec[p.dim] = mesh.dim_names[axis_idx]
    max_dim = max(spec.keys(), default=-1)
    return PartitionSpec(*[spec.get(i) for i in range(max_dim + 1)])


def shard_tensor(x, process_mesh=None, placements=None, dims_mapping=None,
                 dist_attr=None, stop_gradient=None):
    """Place a Tensor on a mesh (ref: auto_parallel/api shard_tensor)."""
    if not isinstance(x, Tensor):
        x = Tensor(np.asarray(x))
    if dist_attr is not None:
        process_mesh = dist_attr.process_mesh
        spec = dist_attr.to_partition_spec()
    elif placements is not None:
        spec = _placements_to_spec(process_mesh, placements)
    elif dims_mapping is not None:
        spec = TensorDistAttr(process_mesh, dims_mapping).to_partition_spec()
    else:
        spec = PartitionSpec()
    jmesh = process_mesh.jax_mesh if process_mesh is not None \
        else mesh_mod.get_mesh()
    x._data = jax.device_put(x.data, NamedSharding(jmesh, spec))
    x._dist_attr = TensorDistAttr(process_mesh, dims_mapping)
    x.is_distributed = True
    return x


def dtensor_from_fn(fn, process_mesh, placements, *args, **kwargs):
    t = fn(*args, **kwargs)
    return shard_tensor(t, process_mesh, placements)


def reshard(x, process_mesh=None, placements=None, dist_attr=None):
    """Move a tensor to a new sharding — the reference inserts comm ops via
    Resharder (reshard.py, 3k LoC); here it is one resharding device_put
    (XLA generates the collective)."""
    return shard_tensor(x, process_mesh, placements, dist_attr=dist_attr)


def shard_op(op_fn, process_mesh=None, in_shardings=None, out_shardings=None):
    def wrapper(*args, **kwargs):
        return op_fn(*args, **kwargs)
    return wrapper


def get_mesh():
    return mesh_mod.get_mesh()


class Strategy:
    """ref: auto_parallel/strategy.py."""

    def __init__(self, config=None):
        from ..fleet.strategy import _Config
        self.amp = _Config(enable=False, dtype="bfloat16", level="O1")
        self.recompute = _Config(enable=False, checkpoints=None)
        self.sharding = _Config(enable=False, stage=1, degree=1)
        self.gradient_merge = _Config(enable=False, k_steps=1, avg=True)
        self.pipeline = _Config(enable=False, schedule_mode="1F1B",
                                micro_batch_size=1, accumulate_steps=1)
        self.fused_passes = _Config(enable=False, fused_passes_list=[])


class Engine:
    """ref: auto_parallel/engine.py:55 — fit/evaluate/predict over an
    annotated model. _build/_plan/_parallel (engine.py:563,722,750) collapse
    into: trace once under jit with parameter NamedShardings; XLA completes
    and partitions."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics
        self.strategy = strategy or Strategy()
        self._train_step = None

    def _loss_fn(self, layer, *batch):
        *inputs, label = batch if len(batch) > 1 else (batch[0], None)
        out = layer(*inputs)
        if isinstance(out, (tuple, list)):
            out = out[0]
        if self.loss is not None and label is not None:
            return self.loss(out, label)
        return out

    def fit(self, train_data, train_sample_split=None, batch_size=1,
            epochs=1, steps_per_epoch=None, log_freq=10, save_dir=None,
            save_freq=1, valid_data=None, valid_sample_split=None,
            valid_freq=1, valid_steps=None, collate_fn=None, callbacks=None,
            verbose=2, num_workers=0):
        from ...io import DataLoader
        from ...parallel.train_step import TrainStep
        if self.strategy.recompute["enable"]:
            if hasattr(self.model, "config"):
                self.model.config.recompute = True
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=True)
        step_fn = TrainStep(self.model, self.optimizer,
                            loss_fn=self._loss_fn)
        self._train_step = step_fn
        history = {"loss": []}
        it = 0
        for epoch in range(epochs):
            for batch in loader:
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                loss = step_fn(*batch)
                history["loss"].append(float(loss.numpy()))
                it += 1
                if verbose and it % log_freq == 0:
                    print(f"[auto_parallel] epoch {epoch} step {it} "
                          f"loss {history['loss'][-1]:.4f}")
                if steps_per_epoch and it >= steps_per_epoch:
                    break
        step_fn.sync_to_layer()
        return history

    def evaluate(self, valid_data, valid_sample_split=None, batch_size=1,
                 steps=None, log_freq=10, collate_fn=None, callbacks=None,
                 verbose=2, num_workers=0):
        from ...io import DataLoader
        from ...framework.autograd import no_grad
        loader = valid_data if isinstance(valid_data, DataLoader) else \
            DataLoader(valid_data, batch_size=batch_size)
        losses = []
        with no_grad():
            for batch in loader:
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                loss = self._loss_fn(self.model, *batch)
                losses.append(float(loss.numpy()))
        return {"loss": float(np.mean(losses)) if losses else 0.0}

    def predict(self, test_data, test_sample_split=None, batch_size=1,
                steps=None, collate_fn=None, callbacks=None, verbose=2,
                num_workers=0):
        from ...io import DataLoader
        from ...framework.autograd import no_grad
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size)
        outs = []
        with no_grad():
            for batch in loader:
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                out = self.model(*batch)
                outs.append(out.numpy() if isinstance(out, Tensor)
                            else out[0].numpy())
        return outs

    def save(self, path, training=True):
        from ...framework.io import save
        save(self.model.state_dict(), path + ".pdparams")
        if training and self.optimizer is not None:
            save(self.optimizer.state_dict(), path + ".pdopt")

    def load(self, path, strict=True, load_optimizer=True):
        from ...framework.io import load
        import os
        self.model.set_state_dict(load(path + ".pdparams"))
        if load_optimizer and self.optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self.optimizer.set_state_dict(load(path + ".pdopt"))
