"""Activation recomputation (ref: /root/reference/python/paddle/distributed/
fleet/recompute/recompute.py — RecomputeFunction:69, recompute():332,
recompute_sequential:456).

TPU-native: jax.checkpoint (rematerialization) on the captured pure
function — XLA re-emits the forward in the backward pass; no RNG state
save/restore dance is needed because dropout keys are explicit inputs."""
from __future__ import annotations

from typing import Any

import jax

from ...framework import autograd, random as _random
from ...framework.op import apply, unwrap
from ...framework.tensor import Tensor
from ...nn.layer.layers import Layer


def recompute(function, *args, use_reentrant=True, preserve_rng_state=True,
              **kwargs):
    layer = function if isinstance(function, Layer) else None
    params = list(layer.parameters()) if layer is not None else []
    n_args_total = len(args)
    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    n_t = len(tensor_idx)
    key = _random.next_key()

    def pure(*arrays):
        arg_arrays = arrays[:n_t]
        param_arrays = arrays[n_t:n_t + len(params)]
        saved = [p._data for p in params]
        for p, a in zip(params, param_arrays):
            p._data = a
        try:
            call_args = list(args)
            for i, a in zip(tensor_idx, arg_arrays):
                call_args[i] = Tensor(a, stop_gradient=True)
            with autograd.no_grad(), _random.key_scope(key):
                out = function(*call_args, **kwargs)
        finally:
            for p, a in zip(params, saved):
                p._data = a
        if isinstance(out, (tuple, list)):
            return tuple(unwrap(t) for t in out)
        return unwrap(out)

    impl = jax.checkpoint(pure)
    tensor_args = tuple(args[i] for i in tensor_idx) + tuple(params)
    return apply(impl, tensor_args, op_name="recompute")


def recompute_sequential(ctx, functions, *args, **kwargs):
    """ref: recompute.py:456 — chunk a Sequential into recompute segments."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    if isinstance(functions, Layer):
        functions = list(functions.children())
    n = len(functions)
    per = (n + segments - 1) // segments
    out = args[0] if len(args) == 1 else args

    class _Seg(Layer):
        def __init__(self, layers):
            super().__init__()
            from ...nn.layer.container import LayerList
            self.seg = LayerList(layers)

        def forward(self, x):
            for l in self.seg:
                x = l(x)
            return x

    for s in range(0, n, per):
        seg = _Seg(functions[s:s + per])
        out = recompute(seg, out, **kwargs)
    return out


class LegacyRecomputeFunction:
    pass
