"""Deep Gradient Compression (ref: /root/reference/python/paddle/
distributed/fleet/meta_optimizers/dgc_optimizer.py + paddle/fluid/
operators/dgc_op.h — top-k gradient sparsification with momentum
correction and residual accumulation, Lin et al. 2017).

On TPU the communication saving doesn't apply (XLA collectives move dense
tensors), but the ALGORITHM is preserved: momentum correction, residual
accumulation, and top-k masking with the reference's ramp-up schedule —
so training curves match the reference's DGC runs."""
from __future__ import annotations

import jax.numpy as jnp

from ....optimizer.optimizer import Momentum


class DGCMomentum(Momentum):
    _accum_names = ["u", "v"]  # momentum correction + residual

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 rampup_begin_step=0, rampup_step=1,
                 sparsity=(0.999,), grad_clip=None, name=None):
        super().__init__(learning_rate, momentum, parameters,
                         grad_clip=grad_clip, name=name)
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = max(int(rampup_step), 1)
        self._sparsity = tuple(sparsity)

    def _extra_cache_key(self):
        # sparsity is a trace-time constant: retrace when the ramp moves
        return (self._current_sparsity(),)

    def _current_sparsity(self):
        s = self._step_count - self._rampup_begin
        if s < 0:
            return 0.0
        idx = min(int(s * len(self._sparsity) / self._rampup_step),
                  len(self._sparsity) - 1)
        return float(self._sparsity[idx])

    def _update(self, p, g, state, lr, step, param_lr=1.0, wd=0.0):
        g32 = g.astype(jnp.float32)
        sparsity = self._current_sparsity()
        u = self._momentum * state["u"] + g32
        v = state["v"] + u
        if sparsity <= 0.0:
            new_p = p - (lr * param_lr) * v.astype(p.dtype)
            return new_p, {"u": u, "v": jnp.zeros_like(v)}
        k = max(int(v.size * (1.0 - sparsity)), 1)
        flat = jnp.abs(v).ravel()
        thr = jnp.sort(flat)[-k]
        mask = (jnp.abs(v) >= thr).astype(jnp.float32)
        transmitted = v * mask
        new_p = p - (lr * param_lr) * transmitted.astype(p.dtype)
        # clear transmitted entries from both accumulators (dgc_op.h)
        keep = 1.0 - mask
        return new_p, {"u": u * keep, "v": v * keep}


class DGCOptimizer:
    """Meta-optimizer shell (ref dgc_optimizer.py)."""

    def __init__(self, optimizer, strategy=None):
        self._user_opt = optimizer
        self._cfg = getattr(strategy, "dgc_configs", None) or {}

    def target_optimizer(self):
        opt = self._user_opt
        if isinstance(opt, DGCMomentum):
            return opt
        if not isinstance(opt, Momentum):
            return opt
        dgc = DGCMomentum(
            learning_rate=opt._lr, momentum=opt._momentum,
            parameters=opt._parameter_list,
            rampup_begin_step=self._cfg.get("rampup_begin_step", 0),
            rampup_step=self._cfg.get("rampup_step", 1),
            sparsity=self._cfg.get("sparsity", (0.999,)))
        dgc._grad_clip = opt._grad_clip
        return dgc
