"""LARS (ref: /root/reference/python/paddle/distributed/fleet/
meta_optimizers/lars_optimizer.py — swaps Momentum for lars_momentum,
paddle/phi/kernels/gpu/lars_momentum_kernel.cu for the rule)."""
from __future__ import annotations

import jax.numpy as jnp

from ....optimizer.optimizer import Momentum


class LarsMomentum(Momentum):
    """Layer-wise Adaptive Rate Scaling momentum:
    local_lr = lr * coeff * ||w|| / (||g|| + wd * ||w|| + eps)."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, exclude_from_weight_decay=None,
                 epsilon=1e-9, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, momentum, parameters,
                         grad_clip=grad_clip,
                         multi_precision=multi_precision, name=name)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._exclude = exclude_from_weight_decay or []
        self._eps = epsilon

    def _wd_mode(self):
        return "internal"  # the rule consumes weight decay itself

    def _wd_for_param(self, p):
        name = getattr(p, "name", "") or ""
        if any(tag in name for tag in self._exclude):
            return 0.0
        return self._lars_wd

    def _update(self, p, g, state, lr, step, param_lr=1.0, wd=0.0):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        p_norm = jnp.sqrt((p32 * p32).sum())
        g_norm = jnp.sqrt((g32 * g32).sum())
        trust = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            self._lars_coeff * p_norm / (g_norm + wd * p_norm + self._eps),
            1.0)
        local_lr = lr * param_lr * trust
        v = self._momentum * state["velocity"] + local_lr * (g32 + wd * p32)
        new_p = (p32 - v).astype(p.dtype)
        return new_p, {"velocity": v}


class LarsOptimizer:
    """Meta-optimizer shell (ref lars_optimizer.py): converts a user
    Momentum into LarsMomentum, inheriting its hyperparameters."""

    def __init__(self, optimizer, strategy=None):
        self._user_opt = optimizer
        self._cfg = getattr(strategy, "lars_configs", None) or {}

    def target_optimizer(self):
        opt = self._user_opt
        if isinstance(opt, LarsMomentum):
            return opt
        if not isinstance(opt, Momentum):
            return opt  # reference also falls through for non-Momentum
        lars = LarsMomentum(
            learning_rate=opt._lr, momentum=opt._momentum,
            parameters=opt._parameter_list,
            lars_coeff=self._cfg.get("lars_coeff", 0.001),
            lars_weight_decay=self._cfg.get("lars_weight_decay", 0.0005),
            exclude_from_weight_decay=self._cfg.get(
                "exclude_from_weight_decay", None),
            epsilon=self._cfg.get("epsilon", 1e-9))
        lars._grad_clip = opt._grad_clip
        return lars
