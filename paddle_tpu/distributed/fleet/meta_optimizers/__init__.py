"""Fleet meta-optimizers (ref: /root/reference/python/paddle/distributed/
fleet/meta_optimizers/ — strategy-pattern optimizer rewrites composed via
DistributedStrategy flags: gradient_merge_optimizer.py, lars_optimizer.py,
dgc_optimizer.py, localsgd_optimizer.py).

The reference rewrites static-graph programs; here each meta-optimizer is
a wrapper (or optimizer subclass) applied by fleet.distributed_optimizer
when the matching strategy flag is on — the compiled step stays one XLA
program."""
from .gradient_merge import GradientMergeOptimizer
from .lars import LarsMomentum, LarsOptimizer
from .dgc import DGCMomentum, DGCOptimizer
from .localsgd import LocalSGDOptimizer

__all__ = ["GradientMergeOptimizer", "LarsMomentum", "LarsOptimizer",
           "DGCMomentum", "DGCOptimizer", "LocalSGDOptimizer"]


def apply_meta_optimizers(optimizer, strategy):
    """Compose wrappers per strategy flags (the reference's
    _choose_meta_optimizer ordering: dgc/lars replace the rule, then
    gradient-merge and localsgd wrap the schedule)."""
    if strategy is None:
        return optimizer
    if getattr(strategy, "lars", False):
        optimizer = LarsOptimizer(optimizer, strategy).target_optimizer()
    if getattr(strategy, "dgc", False):
        optimizer = DGCOptimizer(optimizer, strategy).target_optimizer()
    if getattr(strategy, "gradient_merge", False):
        cfg = strategy.gradient_merge_configs
        optimizer = GradientMergeOptimizer(
            optimizer, k_steps=cfg.get("k_steps", 1),
            avg=cfg.get("avg", True))
    if getattr(strategy, "localsgd", False):
        cfg = getattr(strategy, "localsgd_configs", {})
        optimizer = LocalSGDOptimizer(
            optimizer, k_steps=cfg.get("k_steps", 1) if cfg else 1)
    return optimizer
