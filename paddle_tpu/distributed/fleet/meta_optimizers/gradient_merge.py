"""Gradient merge (ref: /root/reference/python/paddle/distributed/fleet/
meta_optimizers/gradient_merge_optimizer.py — accumulate grads for k
steps, apply once).

TPU-native: the tape already accumulates into param.grad across
backward() calls, so merging = deferring step()/clear_grad() to the k-th
call (and scaling by 1/k for avg) — no extra buffers, no graph rewrite."""
from __future__ import annotations


class GradientMergeOptimizer:
    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self._inner_opt = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg
        self._count = 0

    def __getattr__(self, item):
        return getattr(self.__dict__["_inner_opt"], item)

    def _is_boundary(self):
        return self._count % self.k_steps == 0

    def step(self):
        self._count += 1
        if not self._is_boundary():
            return  # keep accumulating into p.grad
        if self.avg and self.k_steps > 1:
            for p in self._inner_opt._parameter_list_flat():
                if p.grad is not None:
                    p.grad.set_value(p.grad * (1.0 / self.k_steps))
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        if self._is_boundary():
            self._inner_opt.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
