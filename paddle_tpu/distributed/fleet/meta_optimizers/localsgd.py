"""Local SGD (ref: /root/reference/python/paddle/distributed/fleet/
meta_optimizers/localsgd_optimizer.py — workers step locally, parameters
are averaged across the data-parallel group every k steps).

Single-controller GSPMD keeps parameters globally consistent, so the
averaging is a real collective only under multi-process launch
(jax.process_count() > 1 with per-process param copies); otherwise the
wrapper preserves the schedule/API and the average is the identity."""
from __future__ import annotations


class LocalSGDOptimizer:
    def __init__(self, inner_optimizer, k_steps=1, begin_step=1):
        self._inner_opt = inner_optimizer
        self.k_steps = int(k_steps)
        self._begin = int(begin_step)
        self._count = 0

    def __getattr__(self, item):
        return getattr(self.__dict__["_inner_opt"], item)

    def step(self):
        self._inner_opt.step()
        self._count += 1
        if self._count >= self._begin and self._count % self.k_steps == 0:
            self._average_params()

    def _average_params(self):
        import jax
        if jax.process_count() <= 1:
            return  # params already globally consistent under GSPMD
        from ...communication import all_reduce
        n = jax.process_count()
        for p in self._inner_opt._parameter_list_flat():
            all_reduce(p)
            p.set_value(p / n)

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
