"""paddle.distributed.fleet (ref: /root/reference/python/paddle/distributed/
fleet/__init__.py)."""
from . import meta_parallel  # noqa: F401
from .fleet import (HybridParallelOptimizer, PaddleCloudRoleMaker,  # noqa: F401
                    UserDefinedRoleMaker, barrier_worker, distributed_model,
                    distributed_optimizer, init, is_first_worker,
                    is_initialized, worker_index, worker_num)
from .strategy import DistributedStrategy  # noqa: F401
from .topology import (CommunicateTopology, HybridCommunicateGroup,  # noqa: F401
                       get_hybrid_communicate_group)
from .meta_parallel.sharding import (group_sharded_parallel,  # noqa: F401
                                     save_group_sharded_model)

# submodule aliases matching the reference layout
from . import fleet as _fleet_mod  # noqa: F401
from .layers import mpu  # noqa: F401
from . import utils  # noqa: F401
