"""fleet facade (ref: /root/reference/python/paddle/distributed/fleet/
fleet.py — init:168, _init_hybrid_parallel_env:385, distributed_model via
fleet/model.py:30, distributed_optimizer via
meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:238)."""
from __future__ import annotations

from typing import Optional

from .. import env as dist_env
from .strategy import DistributedStrategy
from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       get_hybrid_communicate_group,
                       set_hybrid_communicate_group)


class _FleetState:
    def __init__(self):
        self.initialized = False
        self.strategy: Optional[DistributedStrategy] = None
        self.hcg: Optional[HybridCommunicateGroup] = None


_fleet = _FleetState()


def init(role_maker=None, is_collective=False, strategy=None, log_level="INFO"):
    """fleet.init (ref: fleet/fleet.py:168). Builds the 4-D (plus sep)
    topology and the global device mesh."""
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    names = ["data", "pipe", "sharding", "sep", "model"]
    import jax
    n_dev = len(jax.devices())
    degrees = [hc["dp_degree"], hc["pp_degree"], hc["sharding_degree"],
               hc.get("sep_degree", 1), hc["mp_degree"]]
    specified = 1
    for d in degrees:
        specified *= max(d, 1)
    if hc["dp_degree"] <= 0:
        degrees[0] = max(n_dev // (specified // max(hc["dp_degree"], 1)), 1) \
            if specified else n_dev
        # recompute: dp fills the remainder
        rest = degrees[1] * degrees[2] * degrees[3] * degrees[4]
        degrees[0] = max(n_dev // rest, 1)

    topo = CommunicateTopology(names, degrees)
    hcg = HybridCommunicateGroup(topo, global_rank=dist_env.get_rank()
                                 if dist_env.get_rank() < topo.world_size
                                 else 0)
    set_hybrid_communicate_group(hcg)
    _fleet.initialized = True
    _fleet.strategy = strategy
    _fleet.hcg = hcg
    dist_env.mark_initialized()

    # model-parallel RNG streams (ref: mpu/random.py)
    from .layers.mpu import random as mpu_random
    seed = strategy.tensor_parallel_configs.get("tensor_init_seed", -1)
    mpu_random.model_parallel_random_seed(
        None if seed in (-1, None) else seed)
    return None


def is_initialized():
    return _fleet.initialized


def get_hybrid_communicate_group_():
    return _fleet.hcg


def worker_index():
    return dist_env.get_rank()


def worker_num():
    return dist_env.get_world_size()


def is_first_worker():
    return dist_env.get_rank() == 0


def barrier_worker():
    pass


def distributed_model(model):
    """ref: fleet/model.py:30 — wrap per topology."""
    hcg = _fleet.hcg or get_hybrid_communicate_group()
    from .meta_parallel.meta_parallel_base import (ShardingParallel,
                                                   TensorParallel)
    from .meta_parallel.pipeline_parallel import (
        PipelineParallel, PipelineParallelWithInterleave)
    from .meta_parallel.pp_layers import PipelineLayer

    strategy = _fleet.strategy
    mode = hcg.get_parallel_mode()
    if mode == "pipeline_parallel" or isinstance(model, PipelineLayer):
        if isinstance(model, PipelineLayer):
            return PipelineParallel(model, hcg, strategy)
        return PipelineParallel(_WrapAsPipeline(model), hcg, strategy)
    if mode == "tensor_parallel":
        return TensorParallel(model, hcg, strategy)
    if mode == "sharding_parallel":
        return ShardingParallel(model, hcg, strategy)
    from ..parallel import DataParallel
    return DataParallel(model)


class _WrapAsPipeline:
    def __init__(self, model):
        self._model = model

    def __call__(self, *a, **kw):
        return self._model(*a, **kw)

    def __getattr__(self, item):
        return getattr(self.__dict__["_model"], item)


class HybridParallelOptimizer:
    """ref: hybrid_parallel_optimizer.py:238 — wraps the user optimizer; in
    the reference it fuses DP-group allreduce of grads and widens grad-clip
    to all axes. Under GSPMD gradients are global values already, and
    ClipGradByGlobalNorm sees full tensors, so the wrapper is thin; sharding
    stage-1 state placement is applied when enabled."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        if strategy is not None and strategy.hybrid_configs[
                "sharding_degree"] > 1:
            from .meta_parallel.sharding import DygraphShardingOptimizer
            DygraphShardingOptimizer(optimizer, hcg)

    def __getattr__(self, item):
        return getattr(self.__dict__["_inner_opt"], item)

    def step(self):
        self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner_opt.minimize(loss, startup_program, parameters,
                                        no_grad_set)

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad


def distributed_optimizer(optimizer, strategy=None):
    strategy = strategy or _fleet.strategy
    from .meta_optimizers import apply_meta_optimizers
    optimizer = apply_meta_optimizers(optimizer, strategy)
    return HybridParallelOptimizer(optimizer, _fleet.hcg, strategy)


class UserDefinedRoleMaker:
    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        self._kwargs = kwargs


class PaddleCloudRoleMaker(UserDefinedRoleMaker):
    pass
