"""MetaParallelBase + TP/Sharding wrappers (ref: /root/reference/python/
paddle/distributed/fleet/meta_parallel/meta_parallel_base.py,
tensor_parallel.py:27, sharding_parallel.py:22)."""
from __future__ import annotations

from ....nn.layer.layers import Layer


class MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers_holder = layers
        self._hcg = hcg
        self._strategy = strategy
        self._prepare_for_model()

    def _prepare_for_model(self):
        pass

    def forward(self, *args, **kwargs):
        return self._layers_holder(*args, **kwargs)

    # delegate layer protocol to the wrapped model
    def parameters(self, include_sublayers=True):
        return self._layers_holder.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers_holder.named_parameters(prefix, include_sublayers)

    def state_dict(self, *a, **kw):
        return self._layers_holder.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers_holder.set_state_dict(*a, **kw)

    def train(self):
        self._layers_holder.train()
        return self

    def eval(self):
        self._layers_holder.eval()
        return self


class TensorParallel(MetaParallelBase):
    """In the GSPMD world, parameter placement was done by the mpu layers at
    construction; initial-state broadcast (hybrid_parallel_util.py:199) is
    unnecessary because a global array IS one logical value."""
    pass


class ShardingParallel(MetaParallelBase):
    pass
