from ..layers.mpu.mp_layers import (ColumnParallelLinear,  # noqa: F401
                                    ParallelCrossEntropy, RowParallelLinear,
                                    VocabParallelEmbedding)
from ..layers.mpu.random import get_rng_state_tracker  # noqa: F401
from .meta_parallel_base import (MetaParallelBase, ShardingParallel,  # noqa: F401
                                 TensorParallel)
from .pipeline_parallel import (PipelineParallel,  # noqa: F401
                                PipelineParallelWithInterleave)
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .sharding import (DygraphShardingOptimizer, GroupShardedOptimizerStage2,  # noqa: F401
                       GroupShardedStage2, GroupShardedStage3,
                       group_sharded_parallel, save_group_sharded_model)
