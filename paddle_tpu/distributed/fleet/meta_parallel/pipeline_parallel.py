"""PipelineParallel wrapper (ref: /root/reference/python/paddle/distributed/
fleet/meta_parallel/pipeline_parallel.py — 1F1B schedule :174-192,
interleave :551; p2p meta negotiation pp_utils/p2p_communication.py:84).

Single-controller semantics: train_batch splits the batch into
micro-batches, runs forward/backward per micro-batch with gradient
accumulation and steps the optimizer — numerically identical to the
reference's 1F1B (the loss-equivalence contract its tests assert,
hybrid_parallel_pp_transformer.py). Device-level pipelining across the
'pp' mesh axis comes from the stacked-stage SPMD schedule in
paddle_tpu/parallel/pipeline.py, which the flagship models drive under
jit; there is no NCCL p2p to schedule by hand on TPU — activations move
via ppermute inside the compiled program."""
from __future__ import annotations

from typing import Optional

from ....framework.tensor import Tensor
from .meta_parallel_base import MetaParallelBase
from .pp_layers import PipelineLayer


class PipelineParallel(MetaParallelBase):
    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pconf = strategy.pipeline_configs if strategy is not None else {}
        self.micro_batch_size = pconf.get("micro_batch_size", 1) if \
            hasattr(pconf, "get") else 1
        self.accumulate_steps = pconf.get("accumulate_steps", 1) if \
            hasattr(pconf, "get") else 1
        self.total_loss = None

    def _split_micro(self, data):
        if isinstance(data, (tuple, list)):
            xs = data
        else:
            xs = (data,)
        n = self.accumulate_steps
        micros = []
        for i in range(n):
            parts = []
            for x in xs:
                if isinstance(x, Tensor):
                    bs = x.shape[0]
                    mb = bs // n
                    parts.append(x[i * mb:(i + 1) * mb])
                else:
                    parts.append(x)
            micros.append(tuple(parts))
        return micros

    def forward_backward_pipeline(self, data, scaler=None):
        """Micro-batched forward/backward with grad accumulation — the
        single-controller equivalent of the 1F1B loop (ref:
        pipeline_parallel.py:174)."""
        micros = self._split_micro(data)
        total = None
        # Accumulate the loss on-device; a float()/numpy() inside this loop
        # would host-sync per micro-batch and serialize device work
        # (flagged in round-1 review).
        for inputs in micros:
            x, label = inputs if len(inputs) == 2 else (inputs[0], None)
            out = self._layers.forward(x)
            loss = self._layers.loss(out, label) if label is not None else out
            scaled = loss * (1.0 / self.accumulate_steps)
            if scaler is not None:
                scaled = scaler.scale(scaled)
            scaled.backward()
            ldata = loss.detach().data.astype("float32")
            total = ldata if total is None else total + ldata
        self.total_loss = Tensor(total / len(micros))
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is None:
            optimizer.step()
        else:
            scaler.step(optimizer)
            scaler.update()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        micros = self._split_micro(data)
        total = None
        from ....framework.autograd import no_grad
        with no_grad():
            for inputs in micros:
                x, label = inputs if len(inputs) == 2 else (inputs[0], None)
                out = self._layers.forward(x)
                loss = self._layers.loss(out, label) if compute_loss else out
                ldata = loss.detach().data.astype("float32")
                total = ldata if total is None else total + ldata
        return Tensor(total / len(micros))

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)


class PipelineParallelWithInterleave(PipelineParallel):
    """Virtual-stage interleave (ref: pipeline_parallel.py:551). The
    single-controller grad-accum schedule here is identical; the compiled
    interleaved ring schedule lives in parallel/pipeline.py
    (spmd_pipeline(n_virtual=v) — chunk j of stage s hosts logical stage
    j*n+s, with per-stage remat for the 1F1B memory footprint), which the
    flagship SPMD trainer drives via LlamaSpmdTrainer(n_virtual=...)."""
    pass
