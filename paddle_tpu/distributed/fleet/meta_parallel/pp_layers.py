"""PipelineLayer (ref: /root/reference/python/paddle/distributed/fleet/
meta_parallel/parallel_layers/pp_layers.py — LayerDesc:56,
SharedLayerDesc:76, PipelineLayer:240 with seg_method partitioning).

Single-controller twist: every stage's layers exist in this process; the
stage partition drives (a) the per-stage execution used by
PipelineParallel's microbatch schedule and (b) the stacked-stage SPMD
pipeline (parallel/pipeline.py) when stages are uniform."""
from __future__ import annotations

import re
from typing import Any, Callable, List, Optional

import numpy as np

from ....nn.layer.layers import Layer
from ....nn.layer.container import LayerList
from ..topology import get_hybrid_communicate_group


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """Tied layers (e.g. embedding shared with the LM head,
    ref: pp_layers.py:76). In single-controller SPMD the same Parameter
    object is simply reused — no broadcast needed."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        hcg = get_hybrid_communicate_group()
        if num_stages is None:
            num_stages = hcg.get_pipe_parallel_world_size() if hcg else 1
        self._num_stages = num_stages
        self._stage_id = hcg.get_stage_id() if hcg else 0
        self._recompute_interval = recompute_interval
        self._descs = list(layers)

        # build all layers (shared descs reuse one instance per key)
        shared_instances = {}
        built: List[Any] = []
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in shared_instances:
                    shared_instances[d.layer_name] = (d.build_layer(), d)
                built.append(shared_instances[d.layer_name])
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer) or callable(d):
                built.append(d)
            else:
                raise TypeError(f"bad pipeline entry {d!r}")
        self._built = built
        self.shared_layers = {k: v[0] for k, v in shared_instances.items()}

        # register as sublayers for parameters()
        self.run_function = LayerList(
            [l if isinstance(l, Layer) else _FnLayer(l)
             for l in self._unwrap_built()])

        # stage partition
        self.segment_parts = self._segment(seg_method)

    def _unwrap_built(self):
        out = []
        for b in self._built:
            if isinstance(b, tuple):  # shared
                out.append(b[0])
            else:
                out.append(b)
        return out

    def _segment(self, seg_method):
        n = len(self._built)
        if seg_method.startswith("layer:"):
            cls_name = seg_method.split(":")[1]
            block_idx = [i for i, l in enumerate(self._unwrap_built())
                         if type(l).__name__ == cls_name]
            # layers before first block go to stage 0, after last to last
            per = len(block_idx) // self._num_stages
            rem = len(block_idx) % self._num_stages
            parts = [0]
            cursor = 0
            for s in range(self._num_stages):
                take = per + (1 if s < rem else 0)
                cursor += take
                end = block_idx[cursor - 1] + 1 if cursor > 0 else 0
                parts.append(n if s == self._num_stages - 1 else end)
            return parts
        # uniform
        per = n // self._num_stages
        rem = n % self._num_stages
        parts = [0]
        for s in range(self._num_stages):
            parts.append(parts[-1] + per + (1 if s < rem else 0))
        return parts

    def get_stage_from_index(self, layer_idx):
        for s in range(self._num_stages):
            if self.segment_parts[s] <= layer_idx < self.segment_parts[s + 1]:
                return s
        return self._num_stages - 1

    def stage_layers(self, stage_id):
        lo, hi = self.segment_parts[stage_id], self.segment_parts[stage_id + 1]
        return self._unwrap_built()[lo:hi]

    def forward_stage(self, x, stage_id):
        from ....parallel import mesh as mesh_mod
        for item, layer in zip(self._built[self.segment_parts[stage_id]:
                                           self.segment_parts[stage_id + 1]],
                               self.stage_layers(stage_id)):
            if isinstance(item, tuple):  # shared layer with custom forward
                inst, desc = item
                if desc.forward_func is not None:
                    x = desc.forward_func(inst, x)
                    continue
            x = layer(x) if not isinstance(layer, _FnLayer) else layer(x)
        return x

    def forward(self, x):
        for s in range(self._num_stages):
            x = self.forward_stage(x, s)
        return x

    def loss(self, output, label):
        if self._loss_fn is None:
            return output
        return self._loss_fn(output, label)


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args, **kwargs):
        return self._fn(*args, **kwargs)
