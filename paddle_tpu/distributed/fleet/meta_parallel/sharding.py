"""Sharded data parallel — ZeRO stages 1/2/3 (ref: /root/reference/python/
paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/
dygraph_sharding_optimizer.py:29 and meta_parallel/sharding/
group_sharded_stage2.py, group_sharded_stage3.py:59).

GSPMD design: "sharding optimizer states" = placing the accumulator arrays
with a NamedSharding over the 'sharding' mesh axis; "sharding parameters"
(stage 3) = placing param arrays sharded — XLA all-gathers them at use and
reduce-scatters gradients, which is exactly the stage-3 dataflow the
reference implements with manual broadcast/reduce hooks."""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec

from ....framework.tensor import Parameter
from ....parallel import mesh as mesh_mod


def _shardable_dim(shape, n):
    for dim, s in enumerate(shape):
        if s % n == 0 and s >= n:
            return dim
    return None


def _shard_spec(shape, axis="sharding"):
    n = mesh_mod.mesh_axis_size(axis)
    if n <= 1:
        return None
    dim = _shardable_dim(shape, n)
    if dim is None:
        return None
    spec = [None] * len(shape)
    spec[dim] = axis
    return PartitionSpec(*spec)


def shard_accumulators(optimizer, axis="sharding"):
    """Place every optimizer accumulator sharded over `axis` (ZeRO-1)."""
    for pname, state in optimizer._accumulators.items():
        for k, v in state.items():
            spec = _shard_spec(v.shape, axis)
            if spec is not None:
                state[k] = mesh_mod.shard_tensor_data(v, spec)
    for k, v in optimizer._master_weights.items():
        spec = _shard_spec(v.shape, axis)
        if spec is not None:
            optimizer._master_weights[k] = mesh_mod.shard_tensor_data(v, spec)
    return optimizer


def shard_parameters(layer, axis="sharding"):
    """ZeRO-3: place parameter storage sharded over `axis`."""
    for p in layer.parameters():
        spec = _shard_spec(tuple(p.shape), axis)
        if spec is not None and p._dist_attr is None:
            p._data = mesh_mod.shard_tensor_data(p._data, spec)
            p._dist_attr = spec
    return layer


class DygraphShardingOptimizer:
    """Stage-1 wrapper (ref: dygraph_sharding_optimizer.py:29): optimizer
    states sharded over the sharding axis; step() delegates to the inner
    optimizer whose jitted update runs distributed under GSPMD.

    offload=True pins optimizer states (and fp32 master weights) in HOST
    memory (ref: group_sharded_stage3.py:84-96): each step streams one
    parameter's states H2D, updates on the accelerator, and streams the
    new states D2H — peak accelerator memory for optimizer state is the
    largest single parameter, not the sum."""

    def __init__(self, optimizer, hcg=None, offload=False, **kwargs):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._offload = bool(offload)
        orig_init = optimizer._init_state

        if self._offload:
            if mesh_mod.get_mesh().size > 1:
                raise NotImplementedError(
                    "offload=True with a multi-device mesh is not "
                    "supported yet: host-pinned sharded state needs the "
                    "TPU memory-kind API end to end. Use offload on "
                    "single-device ranks, or sharding without offload "
                    "(states are already partitioned over the sharding "
                    "axis).")
            self._host = jax.devices("cpu")[0]

            def offload_init(p):
                st = orig_init(p)
                return {k: jax.device_put(v, self._host)
                        for k, v in st.items()}
            optimizer._init_state = offload_init
            # Route the INNER optimizer's own step() through the
            # streamed path too: once states live on the host device,
            # the stock fused step would feed CPU-committed states +
            # TPU params into one jit ("incompatible devices"). A user
            # holding the original optimizer object must still work.
            optimizer.step = self._offload_step
        else:
            def sharded_init(p):
                st = orig_init(p)
                for k, v in st.items():
                    spec = _shard_spec(v.shape)
                    if spec is not None:
                        st[k] = mesh_mod.shard_tensor_data(v, spec)
                return st
            optimizer._init_state = sharded_init

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        if self._offload:
            return self._offload_step()
        self._inner_opt.step()

    def _offload_step(self):
        """Per-parameter streamed update with host-resident states.
        Clip/lr/wd semantics come from the inner optimizer's own
        _prepare_step/_param_meta — no duplicated update plumbing."""
        from ....framework import autograd
        opt = self._inner_opt
        with autograd.no_grad():
            prepared = opt._prepare_step()
            if prepared is None:
                return
            params_grads, lr, step = prepared
            compute_dev = params_grads[0][0].data.devices().pop()

            for p, g in params_grads:
                st, master, meta = opt._param_meta(p)
                if master is not None and \
                        self._host not in master.devices():
                    master = jax.device_put(master, self._host)
                p_arr = master if master is not None else p.data
                key = ("offload", tuple(p_arr.shape), str(p_arr.dtype),
                       meta, opt._extra_cache_key())
                fn = opt._jit_cache.get(key)
                if fn is None:
                    fn = jax.jit(opt._make_fused([meta]))
                    opt._jit_cache[key] = fn
                # H2D stream: this parameter's states only
                st_dev = {k: jax.device_put(v, compute_dev)
                          for k, v in st.items()}
                p_dev = jax.device_put(p_arr, compute_dev)
                new_ps, new_sts = fn([p_dev], [g.data], [st_dev], lr, step)
                new_p, new_st = new_ps[0], new_sts[0]
                if master is not None:
                    opt._master_weights[p.name] = jax.device_put(
                        new_p, self._host)
                    p._data = new_p.astype(p.dtype)
                else:
                    p._data = new_p
                # D2H: states go back to host memory
                opt._accumulators[p.name] = {
                    k: jax.device_put(v, self._host)
                    for k, v in new_st.items()}

    def minimize(self, *a, **kw):
        return self._inner_opt.minimize(*a, **kw)


class GroupShardedOptimizerStage2(DygraphShardingOptimizer):
    """Stage 2 (ref: group_sharded_optimizer_stage2.py): states + grads
    sharded. Gradients in this runtime are transient vjp outputs that XLA
    already reduce-scatters when the consumer (the update) is sharded."""

    def __init__(self, params, optim, group=None, offload=False, **kw):
        super().__init__(optim, offload=offload)
        self._params = params


class GroupShardedStage2:
    """Model wrapper for stage 2 (ref: group_sharded_stage2.py)."""

    def __init__(self, layer, sharding_optimizer, group=None,
                 sync_buffers=False, buffer_max_size=2**23, **kw):
        self._layer = layer
        self._opt = sharding_optimizer

    def __call__(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self.__dict__["_layer"], item)


class GroupShardedStage3:
    """Stage 3 (ref: group_sharded_stage3.py:59,1006): parameters sharded;
    all-gather-on-use and reduce-scatter-of-grads are inserted by GSPMD."""

    def __init__(self, layer, optimizer=None, group=None, sync_buffers=False,
                 device="tpu", segment_size=2**20, pertrain_sync_models=True,
                 offload=False, **kw):
        self._layer = shard_parameters(layer)
        if offload and optimizer is not None:
            # host-resident states, streamed per-param (see stage-1 wrapper)
            optimizer = DygraphShardingOptimizer(optimizer, offload=True)
            self._opt = optimizer
        else:
            self._opt = optimizer
            if optimizer is not None:
                shard_accumulators(optimizer)

    def __call__(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self.__dict__["_layer"], item)

    def get_all_parameters(self):
        """Re-gather full parameters (ref: stage3 convert2cpu/get_all_parameters)."""
        for p in self._layer.parameters():
            p._data = mesh_mod.shard_tensor_data(p._data, PartitionSpec())
            p._dist_attr = None
        return self._layer.parameters()


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False,
                           buffer_max_size=2**23, segment_size=2**20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """ref: python/paddle/distributed/sharding/group_sharded.py."""
    if level == "os":
        opt = DygraphShardingOptimizer(optimizer, offload=offload)
        return model, opt, scaler
    if level == "os_g":
        opt = GroupShardedOptimizerStage2(model.parameters(), optimizer,
                                          offload=offload)
        wrapped = GroupShardedStage2(model, opt)
        return wrapped, opt, scaler
    if level == "p_g_os":
        wrapped = GroupShardedStage3(model, optimizer, offload=offload)
        stage3_opt = wrapped._opt if offload else optimizer
        return wrapped, stage3_opt, scaler
    raise ValueError(f"unknown group_sharded level {level}")


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from ....framework.io import save
    layer = getattr(model, "_layer", model)
    os.makedirs(output, exist_ok=True)
    save(layer.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
