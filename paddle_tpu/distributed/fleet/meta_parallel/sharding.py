"""Sharded data parallel — ZeRO stages 1/2/3 (ref: /root/reference/python/
paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/
dygraph_sharding_optimizer.py:29 and meta_parallel/sharding/
group_sharded_stage2.py, group_sharded_stage3.py:59).

GSPMD design: "sharding optimizer states" = placing the accumulator arrays
with a NamedSharding over the 'sharding' mesh axis; "sharding parameters"
(stage 3) = placing param arrays sharded — XLA all-gathers them at use and
reduce-scatters gradients, which is exactly the stage-3 dataflow the
reference implements with manual broadcast/reduce hooks."""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec

from ....framework.tensor import Parameter
from ....parallel import mesh as mesh_mod


def _shardable_dim(shape, n):
    for dim, s in enumerate(shape):
        if s % n == 0 and s >= n:
            return dim
    return None


def _shard_spec(shape, axis="sharding"):
    n = mesh_mod.mesh_axis_size(axis)
    if n <= 1:
        return None
    dim = _shardable_dim(shape, n)
    if dim is None:
        return None
    spec = [None] * len(shape)
    spec[dim] = axis
    return PartitionSpec(*spec)


def shard_accumulators(optimizer, axis="sharding"):
    """Place every optimizer accumulator sharded over `axis` (ZeRO-1)."""
    for pname, state in optimizer._accumulators.items():
        for k, v in state.items():
            spec = _shard_spec(v.shape, axis)
            if spec is not None:
                state[k] = mesh_mod.shard_tensor_data(v, spec)
    for k, v in optimizer._master_weights.items():
        spec = _shard_spec(v.shape, axis)
        if spec is not None:
            optimizer._master_weights[k] = mesh_mod.shard_tensor_data(v, spec)
    return optimizer


def shard_parameters(layer, axis="sharding"):
    """ZeRO-3: place parameter storage sharded over `axis`."""
    for p in layer.parameters():
        spec = _shard_spec(tuple(p.shape), axis)
        if spec is not None and p._dist_attr is None:
            p._data = mesh_mod.shard_tensor_data(p._data, spec)
            p._dist_attr = spec
    return layer


class DygraphShardingOptimizer:
    """Stage-1 wrapper (ref: dygraph_sharding_optimizer.py:29): optimizer
    states sharded over the sharding axis; step() delegates to the inner
    optimizer whose jitted update runs distributed under GSPMD."""

    def __init__(self, optimizer, hcg=None, **kwargs):
        self._inner_opt = optimizer
        self._hcg = hcg
        orig_init = optimizer._init_state

        def sharded_init(p):
            st = orig_init(p)
            for k, v in st.items():
                spec = _shard_spec(v.shape)
                if spec is not None:
                    st[k] = mesh_mod.shard_tensor_data(v, spec)
            return st
        optimizer._init_state = sharded_init

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def minimize(self, *a, **kw):
        return self._inner_opt.minimize(*a, **kw)


class GroupShardedOptimizerStage2(DygraphShardingOptimizer):
    """Stage 2 (ref: group_sharded_optimizer_stage2.py): states + grads
    sharded. Gradients in this runtime are transient vjp outputs that XLA
    already reduce-scatters when the consumer (the update) is sharded."""

    def __init__(self, params, optim, group=None, offload=False, **kw):
        super().__init__(optim)
        self._params = params


class GroupShardedStage2:
    """Model wrapper for stage 2 (ref: group_sharded_stage2.py)."""

    def __init__(self, layer, sharding_optimizer, group=None,
                 sync_buffers=False, buffer_max_size=2**23, **kw):
        self._layer = layer
        self._opt = sharding_optimizer

    def __call__(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self.__dict__["_layer"], item)


class GroupShardedStage3:
    """Stage 3 (ref: group_sharded_stage3.py:59,1006): parameters sharded;
    all-gather-on-use and reduce-scatter-of-grads are inserted by GSPMD."""

    def __init__(self, layer, optimizer=None, group=None, sync_buffers=False,
                 device="tpu", segment_size=2**20, pertrain_sync_models=True,
                 offload=False, **kw):
        self._layer = shard_parameters(layer)
        self._opt = optimizer
        if optimizer is not None:
            shard_accumulators(optimizer)

    def __call__(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self.__dict__["_layer"], item)

    def get_all_parameters(self):
        """Re-gather full parameters (ref: stage3 convert2cpu/get_all_parameters)."""
        for p in self._layer.parameters():
            p._data = mesh_mod.shard_tensor_data(p._data, PartitionSpec())
            p._dist_attr = None
        return self._layer.parameters()


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False,
                           buffer_max_size=2**23, segment_size=2**20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """ref: python/paddle/distributed/sharding/group_sharded.py."""
    if level == "os":
        opt = DygraphShardingOptimizer(optimizer)
        return model, opt, scaler
    if level == "os_g":
        opt = GroupShardedOptimizerStage2(model.parameters(), optimizer)
        wrapped = GroupShardedStage2(model, opt)
        return wrapped, opt, scaler
    if level == "p_g_os":
        wrapped = GroupShardedStage3(model, optimizer)
        return wrapped, optimizer, scaler
    raise ValueError(f"unknown group_sharded level {level}")


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from ....framework.io import save
    layer = getattr(model, "_layer", model)
    os.makedirs(output, exist_ok=True)
    save(layer.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
