"""Hybrid-parallel topology (ref: /root/reference/python/paddle/distributed/
fleet/base/topology.py:54 CommunicateTopology, :140 HybridCommunicateGroup).

The reference builds one NCCL communicator per axis slice; here the topology
IS the global jax Mesh (parallel/mesh.py) and each axis "communicator" is a
Group naming a mesh axis. Rank arithmetic matches the reference so samplers,
checkpoint sharding and per-rank debugging stay compatible."""
from __future__ import annotations

import collections
from functools import reduce
from typing import Dict, List

import numpy as np

from ...parallel import mesh as mesh_mod
from ..communication.group import Group, axis_group

_HYBRID_PARALLEL_GROUP = None


# reference order [data, pipe, sharding, sep?, model]; mesh.AXIS_ORDER maps
# 'data'->'dp', 'pipe'->'pp', 'model'->'mp'
_AXIS_TO_MESH = {"data": "dp", "pipe": "pp", "sharding": "sharding",
                 "sep": "sep", "model": "mp"}


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding",
                                           "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = collections.namedtuple(
            "Coordinate", self._parallel_names)
        self.world_size = int(np.prod(self._dims))
        ranges = [range(d) for d in self._dims]
        all_coords = [self.coordinate(*c) for c in
                      __import__("itertools").product(*ranges)]
        self._coord2rank = {c: i for i, c in enumerate(all_coords)}
        self._rank2coord = {i: c for c, i in self._coord2rank.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def get_rank(self, **args):
        return self._coord2rank[self.coordinate(**args)]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return [r for r, c in self._rank2coord.items() if c[axis] == index]

    def get_comm_list(self, axis_name):
        """All rank lists along `axis_name` (one per orthogonal coordinate)."""
        axis = self._parallel_names.index(axis_name)
        other = [i for i in range(len(self._dims)) if i != axis]
        lists = []
        import itertools
        for combo in itertools.product(*[range(self._dims[i]) for i in other]):
            ranks = []
            for k in range(self._dims[axis]):
                coord = [0] * len(self._dims)
                for i, v in zip(other, combo):
                    coord[i] = v
                coord[axis] = k
                ranks.append(self._coord2rank[self.coordinate(*coord)])
            lists.append(ranks)
        return lists

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)
        tf = coord._replace(**kwargs)._asdict()
        return self.get_rank(**tf)


class HybridCommunicateGroup:
    def __init__(self, topology: CommunicateTopology, global_rank=0):
        self._topo = topology
        self.global_rank = int(global_rank)
        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._mp_degree = topology.get_dim("model")
        self._sep_degree = topology.get_dim("sep") if \
            "sep" in topology.get_hybrid_group_names() else 1

        coord = topology.get_coord(self.global_rank)
        self._dp_rank = coord.data
        self._pp_rank = coord.pipe
        self._sharding_rank = coord.sharding
        self._mp_rank = coord.model
        self._sep_rank = getattr(coord, "sep", 0)

        # build the one global mesh
        mesh_mod.build_mesh(dp=self._dp_degree, pp=self._pp_degree,
                            sharding=self._sharding_degree,
                            sep=self._sep_degree, mp=self._mp_degree)

        def _grp(name):
            mesh_axis = _AXIS_TO_MESH[name]
            lists = topology.get_comm_list(name)
            mine = next((l for l in lists if self.global_rank in l), lists[0])
            return axis_group(mesh_axis, mine)

        self._dp_group = _grp("data")
        self._pp_group = _grp("pipe")
        self._sharding_group = _grp("sharding")
        self._mp_group = _grp("model")
        self._sep_group = _grp("sep") if self._sep_degree > 1 or \
            "sep" in topology.get_hybrid_group_names() else None
        self._check_group = Group(list(range(topology.world_size)), 0,
                                  axis=None, name="check")

    # -- reference API surface (topology.py:156-400) ------------------------
    def get_parallel_mode(self):
        if self._mp_degree == 1 and self._pp_degree == 1 and \
                self._sharding_degree == 1:
            return "data_parallel"
        if self._sharding_degree > 1 and self._mp_degree == 1 and \
                self._pp_degree == 1 and self._dp_degree == 1:
            return "sharding_parallel"
        if self._mp_degree > 1 and self._pp_degree == 1:
            return "tensor_parallel"
        if self._pp_degree > 1:
            return "pipeline_parallel"
        return "data_parallel"

    def get_global_rank(self):
        return self.global_rank

    def get_world_size(self):
        return self._topo.world_size

    # data parallel
    def get_data_parallel_rank(self):
        return self._dp_rank

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._mp_rank

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    # pipeline parallel
    def get_stage_id(self):
        return self._pp_rank

    def get_pipe_parallel_rank(self):
        return self._pp_rank

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_p2p_groups(self):
        return None

    def is_first_stage(self):
        return self._pp_rank == 0

    def is_last_stage(self):
        return self._pp_rank == self._pp_degree - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._sharding_rank

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return self._sharding_group.ranks[0]

    # sep
    def get_sep_parallel_rank(self):
        return self._sep_rank

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._sep_group

    def get_check_parallel_group(self, *a):
        return self._check_group

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank,
                                              pipe=stage_id, **kwargs)


def set_hybrid_communicate_group(hcg):
    global _HYBRID_PARALLEL_GROUP
    _HYBRID_PARALLEL_GROUP = hcg
    from .. import env
    env.set_hcg(hcg)


def get_hybrid_communicate_group():
    return _HYBRID_PARALLEL_GROUP
