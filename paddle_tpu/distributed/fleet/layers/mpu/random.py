"""RNG state tracker for hybrid parallel (ref: /root/reference/python/paddle/
distributed/fleet/layers/mpu/random.py — RNGStatesTracker with
local_seed/global_seed). In the GSPMD global view dropout masks are global
arrays, so 'local' vs 'global' seeds reduce to distinct named key streams."""
from __future__ import annotations

import contextlib

import jax

from .....framework import random as _random

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.states_[name] = jax.random.PRNGKey(seed)

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        orig = _random.get_rng_state()
        _random.set_rng_state(self.states_[name])
        try:
            yield
        finally:
            self.states_[name] = _random.get_rng_state()
            _random.set_rng_state(orig)


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import random as pyrandom
    seed = seed if seed is not None else pyrandom.randint(0, 2**31 - 1)
    from ...topology import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    mp_rank = hcg.get_model_parallel_rank() if hcg else 0
    global_seed = seed
    local_seed = seed + 1024 + mp_rank
    _RNG_STATE_TRACKER.reset()
    _RNG_STATE_TRACKER.add(MODEL_PARALLEL_RNG, local_seed)
    _random.seed(global_seed)


def determinate_seed(rng_name):
    return 0
