"""Tensor-parallel layers (ref: /root/reference/python/paddle/distributed/
fleet/layers/mpu/mp_layers.py — VocabParallelEmbedding:35,
ColumnParallelLinear:173, RowParallelLinear:343, ParallelCrossEntropy:524).

TPU-native design (GSPMD global view): each layer holds the FULL logical
weight placed on the global mesh with a NamedSharding over the 'mp' axis;
forward is the plain math plus sharding constraints, and XLA's SPMD
partitioner inserts the identity/allreduce/allgather collectives the
reference implements by hand in mp_ops.py + c_* CUDA ops. Per-rank local
shapes are available via .local_shape for checkpoint interop.
"""
from __future__ import annotations

import numpy as np
from jax.sharding import PartitionSpec

from .....framework.tensor import Parameter
from .....nn import functional as F
from .....nn import initializer as I
from .....nn.layer.layers import Layer
from .....parallel import mesh as mesh_mod
from ...topology import get_hybrid_communicate_group

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _mp_size():
    hcg = get_hybrid_communicate_group()
    if hcg is not None:
        return hcg.get_model_parallel_world_size()
    return mesh_mod.mesh_axis_size("mp")


def _place(param: Parameter, *spec):
    param._data = mesh_mod.shard_tensor_data(param._data,
                                             PartitionSpec(*spec))
    param._dist_attr = PartitionSpec(*spec)
    param.is_distributed = True
    return param


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over 'mp'
    (ref: mp_layers.py:35; C++ op c_embedding_op.cc)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.world_size = _mp_size()
        assert num_embeddings % self.world_size == 0, \
            "vocab size must divide mp degree"
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        _place(self.weight, "mp", None)

    @property
    def local_shape(self):
        return [self._num_embeddings // self.world_size, self._embedding_dim]

    def forward(self, x):
        out = F.embedding(x, self.weight)
        from .....framework.op import apply
        return apply(lambda a: mesh_mod.constraint(a), (out,),
                     op_name="c_identity")


class ColumnParallelLinear(Layer):
    """Linear with out_features sharded over 'mp' (ref: mp_layers.py:173).
    gather_output=False leaves activations sharded for a following
    RowParallelLinear (Megatron pairing)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.world_size = _mp_size()
        assert out_features % self.world_size == 0
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        _place(self.weight, None, "mp")
        self.bias = None
        if has_bias or has_bias is None:
            self.bias = self.create_parameter(
                [out_features], attr=None, is_bias=True)
            _place(self.bias, "mp")

    @property
    def local_shape(self):
        return [self._in_features, self._out_features // self.world_size]

    def forward(self, x):
        from .....framework.op import apply
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return apply(lambda a: mesh_mod.constraint(a), (out,),
                         op_name="c_concat")
        nd = out.ndim
        spec = [None] * (nd - 1) + ["mp"]
        return apply(lambda a: mesh_mod.constraint(a, *spec), (out,),
                     op_name="c_identity")


class RowParallelLinear(Layer):
    """Linear with in_features sharded over 'mp'; output needs an allreduce
    which GSPMD inserts from the contraction over a sharded dim
    (ref: mp_layers.py:343)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.world_size = _mp_size()
        assert in_features % self.world_size == 0
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        _place(self.weight, "mp", None)
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter(
                [out_features], attr=None, is_bias=True)
            _place(self.bias)

    @property
    def local_shape(self):
        return [self._in_features // self.world_size, self._out_features]

    def forward(self, x):
        from .....framework.op import apply
        if self.input_is_parallel:
            nd = x.ndim
            spec = [None] * (nd - 1) + ["mp"]
            x = apply(lambda a: mesh_mod.constraint(a, *spec), (x,),
                      op_name="c_identity")
        out = F.linear(x, self.weight, self.bias)
        return apply(lambda a: mesh_mod.constraint(a), (out,),
                     op_name="mp_allreduce_sum")


class ParallelCrossEntropy(Layer):
    """Softmax CE over vocab-sharded logits (ref: mp_layers.py:524; CUDA
    kernel c_softmax_with_cross_entropy_op.cu). The log-sum-exp reduction
    over the sharded vocab dim becomes an XLA allreduce under GSPMD."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        loss = F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
        from .....ops.manipulation import unsqueeze
        return unsqueeze(loss, -1)
