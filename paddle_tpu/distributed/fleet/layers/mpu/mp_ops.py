"""TP communication primitives (ref: /root/reference/python/paddle/
distributed/fleet/layers/mpu/mp_ops.py — _c_identity:26, _c_concat:90,
_c_split:152, _mp_allreduce:218, _c_lookup_table:297,
_c_softmax_with_cross_entropy:374, _parallel_linear:512, split:664).

Under GSPMD these are sharding-constraint annotations (forward no-op /
backward allreduce pairs fall out of the partitioner); the functions keep
the reference signatures so fleet code ports unchanged."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from .....framework.op import apply, unwrap, wrap
from .....framework.tensor import Tensor
from .....nn import functional as F
from .....parallel import mesh as mesh_mod


def _c_identity(tensor, group=None, skip_c_identity_dynamic=False):
    """fwd identity / bwd allreduce — GSPMD derives this from replicated
    output of an mp-sharded consumer."""
    return apply(lambda a: a, (tensor,), op_name="c_identity")


def _mp_allreduce(tensor, op=None, group=None, use_calc_stream=True,
                  use_model_parallel=True):
    """fwd allreduce / bwd identity: constrain to replicated."""
    return apply(lambda a: mesh_mod.constraint(a), (tensor,),
                 op_name="mp_allreduce_sum")


def _c_concat(tensor, group=None):
    """gather mp-sharded last dim -> replicated full tensor."""
    return apply(lambda a: mesh_mod.constraint(a), (tensor,),
                 op_name="c_concat")


def _c_split(tensor, group=None):
    """split last dim over mp: constrain last dim sharded."""
    nd = tensor.ndim
    spec = [None] * (nd - 1) + ["mp"]
    return apply(lambda a: mesh_mod.constraint(a, *spec), (tensor,),
                 op_name="c_split")


def _c_lookup_table(table, index, start_index=0, name=None):
    return F.embedding(index, table)


def _c_softmax_with_cross_entropy(logits, label, group=None,
                                  return_softmax=False,
                                  ignore_index=-100):
    loss = F.cross_entropy(logits, label, reduction="none",
                           ignore_index=ignore_index)
    from .....ops.manipulation import unsqueeze
    loss = unsqueeze(loss, -1)
    if return_softmax:
        return loss, F.softmax(logits)
    return loss


def _parallel_linear(x, num_rows, num_cols, axis, param_attr, bias_attr,
                     gather_out, inner_rank, nranks, split_tensor, name,
                     group=None):
    from .mp_layers import ColumnParallelLinear, RowParallelLinear
    if axis == 0:
        layer = RowParallelLinear(num_rows, num_cols, param_attr,
                                  bias_attr is not False,
                                  input_is_parallel=split_tensor)
    else:
        layer = ColumnParallelLinear(num_rows, num_cols, param_attr,
                                     bias_attr is not False,
                                     gather_output=gather_out)
    return layer(x)


def _parallel_embedding(x, per_part_embeddings, origin_size, param_attr,
                        inner_rank, num_partitions, name, group=None):
    from .mp_layers import VocabParallelEmbedding
    layer = VocabParallelEmbedding(origin_size[0], origin_size[1], param_attr)
    return layer(x)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Auto-split API (ref: mp_ops.py:664)."""
    if operation == "linear":
        return _parallel_linear(x, size[0], size[1], axis, weight_attr,
                                bias_attr, gather_out, 0, num_partitions,
                                axis == 0, name)
    if operation == "embedding":
        return _parallel_embedding(x, size[0] // num_partitions, size,
                                   weight_attr, 0, num_partitions, name)
    raise ValueError(f"unsupported operation {operation}")
