from . import mp_layers, mp_ops, random  # noqa: F401
from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,  # noqa: F401
                        RowParallelLinear, VocabParallelEmbedding)
from .random import RNGStatesTracker, get_rng_state_tracker  # noqa: F401
