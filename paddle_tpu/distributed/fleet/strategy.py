"""DistributedStrategy (ref: /root/reference/python/paddle/distributed/fleet/
base/distributed_strategy.py wrapping paddle/fluid/framework/
distributed_strategy.proto:26-194,324). Plain-python mirror of the proto
messages actually consumed on TPU."""
from __future__ import annotations


class _Config(dict):
    """dict with attribute access, mirroring proto message fields."""

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError:
            raise AttributeError(k)

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    def __init__(self):
        # hybrid degrees (proto HybridConfig, distributed_strategy.proto:324)
        self.hybrid_configs = _Config(
            dp_degree=1, mp_degree=1, pp_degree=1, sharding_degree=1,
            sep_degree=1,
            mp_configs=_Config(sync_param=False, sync_grad=False,
                               sync_moment=False),
            pp_configs=_Config(delay_scale_loss=False,
                               dp_comm_overlap=False,
                               enable_timer=False),
        )
        # AMPConfig (proto :26)
        self.amp = False
        self.amp_configs = _Config(
            init_loss_scaling=32768.0, incr_every_n_steps=1000,
            decr_every_n_nan_or_inf=2, incr_ratio=2.0, decr_ratio=0.8,
            use_dynamic_loss_scaling=True, custom_white_list=[],
            custom_black_list=[], use_pure_fp16=False, use_fp16_guard=True,
            use_bf16=True)
        # RecomputeConfig
        self.recompute = False
        self.recompute_configs = _Config(checkpoints=[],
                                         enable_offload=False,
                                         checkpoint_shape=[])
        # ShardingConfig
        self.sharding = False
        self.sharding_configs = _Config(
            sharding_degree=8, stage=1, mp_degree=1, segment_broadcast_MB=32,
            accumulate_steps=1, offload=False)
        # PipelineConfig
        self.pipeline = False
        self.pipeline_configs = _Config(accumulate_steps=1,
                                        micro_batch_size=1,
                                        schedule_mode="1F1B")
        self.gradient_merge = False
        self.gradient_merge_configs = _Config(k_steps=1, avg=True)
        self.tensor_parallel = False
        self.tensor_parallel_configs = _Config(tensor_parallel_degree=1,
                                               tensor_init_seed=-1)
        self.lamb = False
        self.lars = False
        self.lars_configs = _Config(lars_coeff=0.001,
                                    lars_weight_decay=0.0005,
                                    epsilon=1e-9,
                                    exclude_from_weight_decay=[])
        self.dgc = False
        self.dgc_configs = _Config(rampup_begin_step=0, rampup_step=1,
                                   sparsity=[0.999])
        self.localsgd = False
        self.localsgd_configs = _Config(k_steps=1, begin_step=1)
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.find_unused_parameters = False
        self.heter_ccl_mode = False
        self.without_graph_optimization = False
        self.gradient_scale_configs = _Config(scale_strategy="avg")

    def __repr__(self):
        fields = {k: v for k, v in self.__dict__.items()}
        return f"DistributedStrategy({fields})"
