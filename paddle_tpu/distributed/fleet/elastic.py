"""Elastic training manager (ref: /root/reference/python/paddle/distributed/
fleet/elastic/manager.py:124 ElasticManager — etcd membership watch +
relaunch; collective.py:61).

On TPU pods, membership is the pod slice itself: failures surface as
jax.distributed heartbeat loss and the platform restarts the slice. This
manager provides the reference's API over a file/TCP-store heartbeat so
single/multi-host CPU+TPU runs can detect scale events and trigger a
relaunch callback; checkpoint/resume supplies the state continuity."""
from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Callable, Optional


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, args=None, etcd_client=None, heartbeat_dir=None,
                 np=None, host=None, interval=3):
        self.np = np or int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self.host = host or os.environ.get("POD_IP", "127.0.0.1")
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.dir = heartbeat_dir or os.environ.get(
            "PADDLE_ELASTIC_DIR", "/tmp/paddle_tpu_elastic")
        self.interval = interval
        self.enable = self.np > 1 or os.environ.get(
            "PADDLE_ELASTIC_ENABLE") == "1"
        self._stop = threading.Event()
        self._thread = None
        self.on_scale: Optional[Callable] = None
        self.elastic_level = int(os.environ.get(
            "PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", "1"))

    def _beat_path(self, rank):
        return os.path.join(self.dir, f"rank_{rank}.beat")

    def start(self):
        if not self.enable:
            return
        os.makedirs(self.dir, exist_ok=True)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            with open(self._beat_path(self.rank), "w") as f:
                json.dump({"ts": time.time(), "host": self.host}, f)
            self._stop.wait(self.interval)

    def watch(self):
        """Return current membership status (the reference polls etcd)."""
        if not self.enable:
            return ElasticStatus.COMPLETED
        now = time.time()
        alive = 0
        for r in range(self.np):
            p = self._beat_path(r)
            if os.path.exists(p):
                try:
                    with open(p) as f:
                        beat = json.load(f)
                    if now - beat["ts"] < 6 * self.interval:
                        alive += 1
                except (json.JSONDecodeError, OSError):
                    pass
        if alive < self.np:
            if self.on_scale:
                self.on_scale(alive, self.np)
            return ElasticStatus.RESTART
        return ElasticStatus.HOLD

    def exit(self, completed=True):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=1)
        try:
            os.remove(self._beat_path(self.rank))
        except OSError:
            pass
        return ElasticStatus.COMPLETED if completed else ElasticStatus.ERROR


def scale_np(np_new):
    """ref: distributed/elastic.py:21-43 — request a new world size."""
    os.environ["PADDLE_ELASTIC_NP"] = str(np_new)
    return np_new
