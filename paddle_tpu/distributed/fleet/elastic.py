"""Elastic training manager (ref: /root/reference/python/paddle/distributed/
fleet/elastic/manager.py:124 ElasticManager — etcd membership watch +
relaunch; collective.py:61).

On TPU pods, membership is the pod slice itself: failures surface as
jax.distributed heartbeat loss and the platform restarts the slice. This
manager provides the reference's API over a file/TCP-store heartbeat so
single/multi-host CPU+TPU runs can detect scale events and trigger a
relaunch callback; checkpoint/resume supplies the state continuity."""
from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Callable, Optional


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, args=None, etcd_client=None, heartbeat_dir=None,
                 np=None, host=None, interval=3):
        self.np = np or int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self.host = host or os.environ.get("POD_IP", "127.0.0.1")
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.dir = heartbeat_dir or os.environ.get(
            "PADDLE_ELASTIC_DIR", "/tmp/paddle_tpu_elastic")
        self.interval = interval
        self.enable = self.np > 1 or os.environ.get(
            "PADDLE_ELASTIC_ENABLE") == "1"
        self._stop = threading.Event()
        self._thread = None
        self.on_scale: Optional[Callable] = None
        self.elastic_level = int(os.environ.get(
            "PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", "1"))

    def _beat_path(self, rank):
        return os.path.join(self.dir, f"rank_{rank}.beat")

    def start(self):
        if not self.enable:
            return
        os.makedirs(self.dir, exist_ok=True)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            with open(self._beat_path(self.rank), "w") as f:
                json.dump({"ts": time.time(), "host": self.host}, f)
            self._stop.wait(self.interval)

    def watch(self):
        """Return current membership status (the reference polls etcd)."""
        if not self.enable:
            return ElasticStatus.COMPLETED
        now = time.time()
        alive = 0
        for r in range(self.np):
            p = self._beat_path(r)
            if os.path.exists(p):
                try:
                    with open(p) as f:
                        beat = json.load(f)
                    if now - beat["ts"] < 6 * self.interval:
                        alive += 1
                except (json.JSONDecodeError, OSError):
                    pass
        if alive < self.np:
            if self.on_scale:
                self.on_scale(alive, self.np)
            return ElasticStatus.RESTART
        return ElasticStatus.HOLD

    def exit(self, completed=True):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=1)
        try:
            os.remove(self._beat_path(self.rank))
        except OSError:
            pass
        return ElasticStatus.COMPLETED if completed else ElasticStatus.ERROR


def scale_np(np_new):
    """ref: distributed/elastic.py:21-43 — request a new world size."""
    os.environ["PADDLE_ELASTIC_NP"] = str(np_new)
    return np_new


class ElasticSupervisor:
    """The relaunch half of the reference ElasticManager (manager.py:124
    watch loop + :220 relaunch): spawns the worker processes, watches
    process liveness + heartbeat files, and relaunches the whole pod when
    membership drops (the reference also restarts every trainer — state
    continuity comes from checkpoint/resume).

    Used by the launcher under --elastic_level >= 1 and directly by the
    elastic e2e test."""

    def __init__(self, cmds, envs=None, heartbeat_dir=None, interval=0.5,
                 max_restarts=3, heartbeat_timeout=None, log=print):
        self.cmds = list(cmds)
        self.envs = list(envs) if envs is not None \
            else [dict(os.environ)] * len(self.cmds)
        # per-supervisor unique default: a shared dir would let two jobs
        # on one host delete/misread each other's heartbeats
        self.dir = heartbeat_dir or os.environ.get("PADDLE_ELASTIC_DIR") \
            or f"/tmp/paddle_tpu_elastic_{os.getpid()}"
        for env in self.envs:
            env.setdefault("PADDLE_ELASTIC_DIR", self.dir)
        self.interval = interval
        self.max_restarts = max_restarts
        # hang detection: a rank that HAS written heartbeats (workers
        # opt in by running ElasticManager.start()) and then goes silent
        # longer than this is treated as dead even though its process is
        # alive (deadlocked collective). None -> 20x poll interval.
        self.heartbeat_timeout = heartbeat_timeout or 20 * interval
        self.restarts = 0
        self._procs = []
        self._log = log

    def _spawn(self):
        import subprocess
        os.makedirs(self.dir, exist_ok=True)
        # stale beats from the previous incarnation must not mask a death
        for name in os.listdir(self.dir):
            if name.endswith(".beat"):
                try:
                    os.remove(os.path.join(self.dir, name))
                except OSError:
                    pass
        self._procs = [subprocess.Popen(cmd, env=env)
                       for cmd, env in zip(self.cmds, self.envs)]

    def _kill_all(self):
        for p in self._procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 5
        for p in self._procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except Exception:
                p.kill()

    def _stale_ranks(self):
        """Ranks whose process is still RUNNING but whose heartbeat went
        silent for longer than heartbeat_timeout — alive-but-hung
        workers. Ranks that already exited (cleanly or not) are the
        exit-code path's business, not a hang."""
        import json
        stale = []
        now = time.time()
        for rank, proc in enumerate(self._procs):
            if proc.poll() is not None:
                continue  # exited: not hung
            path = os.path.join(self.dir, f"rank_{rank}.beat")
            if not os.path.exists(path):
                continue  # this worker never opted into heartbeats
            try:
                with open(path) as f:
                    beat = json.load(f)
                if now - beat["ts"] > self.heartbeat_timeout:
                    stale.append(rank)
            except (json.JSONDecodeError, OSError, KeyError):
                pass  # mid-write; next poll decides
        return stale

    def run(self) -> int:
        """Supervise until every worker exits 0 (returns 0) or
        max_restarts is exhausted (returns the first failed worker's
        exit code, or 1 when giving up on a hang)."""
        self._spawn()
        while True:
            time.sleep(self.interval)
            codes = [p.poll() for p in self._procs]
            if all(c == 0 for c in codes):
                return 0
            dead = [i for i, c in enumerate(codes)
                    if c is not None and c != 0]
            hung = [] if dead else self._stale_ranks()
            if dead or hung:
                if self.restarts >= self.max_restarts:
                    self._kill_all()
                    self._log(f"ELASTIC giving up after "
                              f"{self.restarts} restarts "
                              f"(dead={dead}, hung={hung})")
                    return codes[dead[0]] if dead else 1
                self.restarts += 1
                self._log(f"ELASTIC worker(s) dead={dead} hung={hung} "
                          f"(codes={codes}); relaunch #{self.restarts}")
                self._kill_all()
                self._spawn()
