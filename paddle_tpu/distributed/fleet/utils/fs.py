"""Filesystem abstraction (ref: /root/reference/python/paddle/
distributed/fleet/utils/fs.py — LocalFS + HDFSClient over hadoop CLI).
LocalFS is fully implemented; HDFS needs a hadoop deployment and raises
with instructions."""
from __future__ import annotations

import os
import shutil

__all__ = ["LocalFS", "HDFSClient", "FSFileExistsError",
           "FSFileNotExistsError"]


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class LocalFS:
    """ref fs.py LocalFS."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            if os.path.isdir(os.path.join(fs_path, name)):
                dirs.append(name)
            else:
                files.append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        with open(fs_path, "a"):
            pass

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if self.is_exist(dst_path):
            if not overwrite:
                raise FSFileExistsError(dst_path)
            self.delete(dst_path)
        shutil.move(src_path, dst_path)

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        else:
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)


class HDFSClient:
    """ref fs.py HDFSClient — drives the hadoop CLI, which is not part
    of a TPU image."""

    def __init__(self, hadoop_home=None, configs=None, *a, **kw):
        raise NotImplementedError(
            "HDFSClient needs a hadoop deployment (the reference shells "
            "out to $HADOOP_HOME/bin/hadoop). TPU jobs read GCS/local "
            "storage — use LocalFS or gcsfs-style tooling.")
