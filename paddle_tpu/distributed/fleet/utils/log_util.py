"""ref: /root/reference/python/paddle/distributed/fleet/utils/
log_util.py — the fleet logger."""
from __future__ import annotations

import logging

__all__ = ["logger", "set_log_level", "layer_to_str"]

logger = logging.getLogger("paddle_tpu.distributed.fleet")
if not logger.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    logger.addHandler(_h)
logger.setLevel(logging.INFO)


def set_log_level(level):
    if isinstance(level, str):
        level = getattr(logging, level.upper())
    logger.setLevel(level)


def layer_to_str(base, *args, **kwargs):
    name = base + "("
    name += ", ".join(str(a) for a in args)
    if kwargs:
        if args:
            name += ", "
        name += ", ".join(f"{k}={v}" for k, v in kwargs.items())
    return name + ")"
