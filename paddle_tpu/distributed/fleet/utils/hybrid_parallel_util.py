"""Hybrid-parallel gradient/parameter sync helpers (ref: /root/reference/
python/paddle/distributed/fleet/utils/hybrid_parallel_util.py —
fused_allreduce_gradients:227, broadcast_mp_parameters:199,
broadcast_dp_parameters:207, sharding_reduce_gradients:258).

GSPMD note: inside the jitted SPMD step these syncs are XLA collectives
inserted automatically; these helpers exist for the EAGER hybrid path
(dygraph DP over jax.distributed / multi-controller), where gradients
live per-process."""
from __future__ import annotations

from ....framework import autograd

__all__ = ["fused_allreduce_gradients", "broadcast_mp_parameters",
           "broadcast_dp_parameters", "sharding_reduce_gradients",
           "broadcast_sharding_parameters"]


def _group_size(hcg, kind):
    if hcg is None:
        from ... import get_world_size
        return get_world_size()
    getter = {"dp": "get_data_parallel_world_size",
              "mp": "get_model_parallel_world_size",
              "sharding": "get_sharding_parallel_world_size"}[kind]
    try:
        return getattr(hcg, getter)()
    except AttributeError:
        return 1


def _mean_reduce(parameter_list, n):
    from ... import all_reduce
    if n <= 1:
        return
    with autograd.no_grad():
        for p in parameter_list:
            mg = getattr(p, "main_grad", None)
            g = mg if mg is not None else p.grad  # bool(Tensor) raises
            if g is None:
                continue
            all_reduce(g)
            g.set_value(g * (1.0 / n))


def fused_allreduce_gradients(parameter_list, hcg=None):
    """ref hybrid_parallel_util.py:227 — mean-allreduce every grad over
    the data-parallel group."""
    _mean_reduce(parameter_list, _group_size(hcg, "dp"))


def sharding_reduce_gradients(parameter_list, hcg=None):
    """ref :258 — mean-reduce over the SHARDING group (the rank keeps
    its shard's slice; under GSPMD the slice-keeping is the optimizer
    state's PartitionSpec)."""
    _mean_reduce(parameter_list, _group_size(hcg, "sharding"))


def _broadcast_params(model, src_rank=0):
    from ... import broadcast
    with autograd.no_grad():
        for p in model.parameters():
            broadcast(p, src_rank)


def broadcast_mp_parameters(model, hcg=None):
    """ref :199 — rank-0 weights win across the model-parallel group."""
    if _group_size(hcg, "mp") > 1:
        _broadcast_params(model)


def broadcast_dp_parameters(model, hcg=None):
    """ref :207."""
    if _group_size(hcg, "dp") > 1:
        _broadcast_params(model)


def broadcast_sharding_parameters(model, hcg=None):
    if _group_size(hcg, "sharding") > 1:
        _broadcast_params(model)
