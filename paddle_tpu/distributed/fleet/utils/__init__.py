"""paddle.distributed.fleet.utils (ref: /root/reference/python/paddle/
distributed/fleet/utils/__init__.py)."""
from .. import recompute as _recompute_mod  # noqa: F401
from ..recompute import recompute, recompute_sequential  # noqa: F401
from . import fs  # noqa: F401
from . import hybrid_parallel_util  # noqa: F401
from . import log_util  # noqa: F401
from . import mix_precision_utils  # noqa: F401
from .fs import HDFSClient, LocalFS  # noqa: F401
from .log_util import logger, set_log_level  # noqa: F401

__all__ = ["recompute", "recompute_sequential", "LocalFS", "HDFSClient",
           "logger", "set_log_level", "fs", "hybrid_parallel_util",
           "log_util", "mix_precision_utils"]
