"""fp32 main-grad accumulation for hybrid-parallel bf16 training
(ref: /root/reference/python/paddle/distributed/fleet/utils/
mix_precision_utils.py:30-45 MixPrecisionLayer / MixPrecisionOptimizer).

The reference registers per-parameter grad hooks that accumulate the
bf16 gradients into an fp32 `main_grad` buffer, and the wrapped
optimizer updates from main_grad with fp32 master weights. Identical
mechanism here over the tape's _accumulate_grad hook point."""
from __future__ import annotations

import jax.numpy as jnp

from ....framework.tensor import Tensor
from ....nn.layer.layers import Layer

__all__ = ["MixPrecisionLayer", "MixPrecisionOptimizer"]


class MixPrecisionLayer(Layer):
    """Wraps a layer whose params run in bf16/fp16: every backward
    accumulates the gradient into fp32 `param.main_grad` (the hook
    returns the grad unchanged, so `.grad` semantics stay intact)."""

    def __init__(self, layers, dtype="bfloat16"):
        super().__init__()
        self._layers = layers
        self._dtype = dtype
        import numpy as np
        for p in layers.parameters():
            if np.issubdtype(np.dtype(str(p.data.dtype)), np.floating) \
                    and str(p.data.dtype) != dtype:
                p._data = p.data.astype(dtype)
            p.main_grad = None

            def _acc(grad, param=p):
                g32 = grad.data.astype(jnp.float32)
                if param.main_grad is None:
                    param.main_grad = Tensor(g32)
                else:
                    param.main_grad._data = param.main_grad.data + g32
                return grad
            p.register_hook(_acc)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, sd, *a, **kw):
        return self._layers.set_state_dict(sd, *a, **kw)


class MixPrecisionOptimizer:
    """Updates from fp32 main_grad with fp32 master weights (the
    reference swaps param.grad for param.main_grad before the inner
    step)."""

    def __init__(self, optimizer):
        self._inner_opt = optimizer
        self._inner_opt._multi_precision = True

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        params = self._inner_opt._parameter_list_flat()
        saved = []
        for p in params:
            if p.main_grad is not None:
                saved.append((p, p._grad))
                p._grad = p.main_grad
        try:
            self._inner_opt.step()
        finally:
            for p, g in saved:
                p._grad = g

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad(set_to_zero)
        for p in self._inner_opt._parameter_list_flat():
            p.main_grad = None

    clear_gradients = clear_grad
