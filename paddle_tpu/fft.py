"""paddle.fft — FFT family over jnp.fft (ref: /root/reference/python/
paddle/fft.py; the reference's fft_c2c/fft_r2c/fft_c2r kernels in
paddle/phi/kernels/gpu live behind these same public names).

XLA lowers these to its native FFT HLO; on TPU that runs on the VPU.
"""
from __future__ import annotations

import jax.numpy as jnp

from .framework.op import apply

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2",
           "ifft2", "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _make(op_name, jnp_fn, differentiable=True):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply(
            lambda a: jnp_fn(a, n=n, axis=axis, norm=norm), (x,),
            differentiable=differentiable, op_name=op_name)
    op.__name__ = op_name
    return op


def _make_nd(op_name, jnp_fn, default_axes=None):
    def op(x, s=None, axes=default_axes, norm="backward", name=None):
        return apply(
            lambda a: jnp_fn(a, s=s, axes=axes, norm=norm), (x,),
            op_name=op_name)
    op.__name__ = op_name
    return op


fft = _make("fft", jnp.fft.fft)
ifft = _make("ifft", jnp.fft.ifft)
rfft = _make("rfft", jnp.fft.rfft)
irfft = _make("irfft", jnp.fft.irfft)
hfft = _make("hfft", jnp.fft.hfft)
ihfft = _make("ihfft", jnp.fft.ihfft)

fftn = _make_nd("fftn", jnp.fft.fftn)
ifftn = _make_nd("ifftn", jnp.fft.ifftn)
rfftn = _make_nd("rfftn", jnp.fft.rfftn)
irfftn = _make_nd("irfftn", jnp.fft.irfftn)

fft2 = _make_nd("fft2", jnp.fft.fft2, default_axes=(-2, -1))
ifft2 = _make_nd("ifft2", jnp.fft.ifft2, default_axes=(-2, -1))
rfft2 = _make_nd("rfft2", jnp.fft.rfft2, default_axes=(-2, -1))
irfft2 = _make_nd("irfft2", jnp.fft.irfft2, default_axes=(-2, -1))


def fftfreq(n, d=1.0, dtype=None, name=None):
    return apply(lambda: jnp.fft.fftfreq(n, d).astype(dtype or "float32"),
                 (), differentiable=False, op_name="fftfreq")


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return apply(lambda: jnp.fft.rfftfreq(n, d).astype(dtype or "float32"),
                 (), differentiable=False, op_name="rfftfreq")


def fftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.fftshift(a, axes=axes), (x,),
                 op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply(lambda a: jnp.fft.ifftshift(a, axes=axes), (x,),
                 op_name="ifftshift")
