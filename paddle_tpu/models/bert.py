"""BERT (config #2: static-graph pure-DP benchmark; ref model family from
PaddleNLP running on the reference runtime). Built from paddle_tpu.nn
TransformerEncoder so the encoder math exercises the framework's own
attention path."""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import nn
from ..nn import functional as F


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def tiny(vocab=1000, hidden=64, layers=2, heads=4, inter=128, seq=64):
        return BertConfig(vocab_size=vocab, hidden_size=hidden,
                          num_hidden_layers=layers, num_attention_heads=heads,
                          intermediate_size=inter,
                          max_position_embeddings=seq)


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size,
                                            config.hidden_size)
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size,
                                                  config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        import paddle_tpu as paddle
        seq = input_ids.shape[1]
        pos = paddle.arange(seq, dtype="int64")
        from ..ops.manipulation import unsqueeze
        pos = unsqueeze(pos, 0)
        emb = self.word_embeddings(input_ids) \
            + self.position_embeddings(pos)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertPooler(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.dense = nn.Linear(config.hidden_size, config.hidden_size)
        self.activation = nn.Tanh()

    def forward(self, hidden):
        return self.activation(self.dense(hidden[:, 0]))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        enc_layer = nn.TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size,
            dropout=config.hidden_dropout_prob, activation="gelu",
            attn_dropout=config.attention_probs_dropout_prob,
            layer_norm_eps=config.layer_norm_eps)
        self.encoder = nn.TransformerEncoder(enc_layer,
                                             config.num_hidden_layers)
        self.pooler = BertPooler(config)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        if attention_mask is not None:
            from ..ops.manipulation import unsqueeze, cast
            from ..ops.math import scale
            # [B, L] 1/0 -> additive [B, 1, 1, L]
            m = unsqueeze(cast(attention_mask, "float32"), [1, 2])
            attention_mask = scale(m - 1.0, 1e4)
        x = self.encoder(x, attention_mask)
        pooled = self.pooler(x)
        return x, pooled


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels), logits
        return logits


class BertLMHead(nn.Layer):
    def __init__(self, config: BertConfig, embedding_weights=None):
        super().__init__()
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.activation = nn.GELU()
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       config.layer_norm_eps)
        self.decoder = nn.Linear(config.hidden_size, config.vocab_size)
        if embedding_weights is not None:
            # tied: decoder weight is the transpose of the embedding
            self._tied = embedding_weights
        else:
            self._tied = None

    def forward(self, x):
        x = self.layer_norm(self.activation(self.transform(x)))
        if self._tied is not None:
            from ..ops.linalg import matmul
            return matmul(x, self._tied, transpose_y=True) + self.decoder.bias
        return self.decoder(x)


class BertForPretraining(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.cls = BertLMHead(config,
                              self.bert.embeddings.word_embeddings.weight)
        self.nsp = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_label=None):
        seq_out, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        pred = self.cls(seq_out)
        nsp_logits = self.nsp(pooled)
        if masked_lm_labels is not None:
            from ..ops.manipulation import reshape
            mlm_loss = F.cross_entropy(
                reshape(pred, [-1, pred.shape[-1]]),
                reshape(masked_lm_labels, [-1]), ignore_index=-100)
            loss = mlm_loss
            if next_sentence_label is not None:
                loss = loss + F.cross_entropy(
                    nsp_logits, next_sentence_label)
            return loss, pred
        return pred, nsp_logits
