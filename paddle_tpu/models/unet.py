"""Diffusion UNet (config #5: Stable-Diffusion-style conv/groupnorm path —
the reference serves this through PaddleMIX on the phi conv/group_norm
kernels, ref: /root/reference/paddle/phi/kernels/gpu/group_norm_kernel.cu).
A compact SD-style UNet: timestep embedding, ResBlocks with GroupNorm+SiLU,
self-attention at low resolutions, skip connections."""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List

import numpy as np

from .. import nn
from ..nn import functional as F
from ..framework.tensor import Tensor


@dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    base_channels: int = 128
    channel_mult: tuple = (1, 2, 4)
    num_res_blocks: int = 2
    attention_resolutions: tuple = (2, 4)
    num_heads: int = 4
    groups: int = 32

    @staticmethod
    def tiny():
        return UNetConfig(in_channels=3, out_channels=3, base_channels=32,
                          channel_mult=(1, 2), num_res_blocks=1,
                          attention_resolutions=(2,), num_heads=2, groups=8)


def timestep_embedding(t, dim, max_period=10000):
    import paddle_tpu as paddle
    from ..ops.manipulation import concat, cast
    from ..ops.math import cos, exp, sin
    from ..ops.manipulation import unsqueeze
    half = dim // 2
    freqs = paddle.to_tensor(
        np.exp(-math.log(max_period) * np.arange(half, dtype=np.float32)
               / half))
    args = unsqueeze(cast(t, "float32"), -1) * unsqueeze(freqs, 0)
    return concat([cos(args), sin(args)], axis=-1)


class ResBlock(nn.Layer):
    def __init__(self, in_ch, out_ch, time_ch, groups):
        super().__init__()
        self.norm1 = nn.GroupNorm(min(groups, in_ch), in_ch)
        self.conv1 = nn.Conv2D(in_ch, out_ch, 3, padding=1)
        self.time_emb = nn.Linear(time_ch, out_ch)
        self.norm2 = nn.GroupNorm(min(groups, out_ch), out_ch)
        self.conv2 = nn.Conv2D(out_ch, out_ch, 3, padding=1)
        self.skip = nn.Conv2D(in_ch, out_ch, 1) if in_ch != out_ch else None

    def forward(self, x, temb):
        from ..ops.manipulation import unsqueeze
        h = self.conv1(F.silu(self.norm1(x)))
        h = h + unsqueeze(self.time_emb(F.silu(temb)), [2, 3])
        h = self.conv2(F.silu(self.norm2(h)))
        skip = self.skip(x) if self.skip is not None else x
        return h + skip


class AttnBlock(nn.Layer):
    def __init__(self, channels, num_heads, groups):
        super().__init__()
        self.norm = nn.GroupNorm(min(groups, channels), channels)
        self.qkv = nn.Conv2D(channels, channels * 3, 1)
        self.proj = nn.Conv2D(channels, channels, 1)
        self.num_heads = num_heads
        self.channels = channels

    def forward(self, x):
        from ..ops.manipulation import reshape, split, transpose
        b, c, h, w = x.shape
        qkv = self.qkv(self.norm(x))
        q, k, v = split(qkv, 3, axis=1)
        hd = c // self.num_heads

        def to_blhd(t):
            t = reshape(t, [b, self.num_heads, hd, h * w])
            return transpose(t, [0, 3, 1, 2])  # [B, L, H, D]
        out = F.scaled_dot_product_attention(to_blhd(q), to_blhd(k),
                                             to_blhd(v))
        out = transpose(out, [0, 2, 3, 1])  # [B, H, D, L]
        out = reshape(out, [b, c, h, w])
        return x + self.proj(out)


class Downsample(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        self.op = nn.Conv2D(ch, ch, 3, stride=2, padding=1)

    def forward(self, x, temb=None):
        return self.op(x)


class Upsample(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        self.conv = nn.Conv2D(ch, ch, 3, padding=1)

    def forward(self, x, temb=None):
        x = F.interpolate(x, scale_factor=2, mode="nearest")
        return self.conv(x)


class UNetModel(nn.Layer):
    def __init__(self, config: UNetConfig = None):
        super().__init__()
        config = config or UNetConfig()
        self.config = config
        ch = config.base_channels
        time_ch = ch * 4
        self.time_mlp1 = nn.Linear(ch, time_ch)
        self.time_mlp2 = nn.Linear(time_ch, time_ch)
        self.conv_in = nn.Conv2D(config.in_channels, ch, 3, padding=1)

        self.down_blocks = nn.LayerList()
        self.down_attns = nn.LayerList()
        self.downsamples = nn.LayerList()
        chans = [ch]
        cur = ch
        for level, mult in enumerate(config.channel_mult):
            out_ch = ch * mult
            for _ in range(config.num_res_blocks):
                self.down_blocks.append(ResBlock(cur, out_ch, time_ch,
                                                 config.groups))
                use_attn = (2 ** level) in config.attention_resolutions
                self.down_attns.append(
                    AttnBlock(out_ch, config.num_heads, config.groups)
                    if use_attn else nn.Identity())
                cur = out_ch
                chans.append(cur)
            if level < len(config.channel_mult) - 1:
                self.downsamples.append(Downsample(cur))
                chans.append(cur)
            else:
                self.downsamples.append(nn.Identity())

        self.mid_block1 = ResBlock(cur, cur, time_ch, config.groups)
        self.mid_attn = AttnBlock(cur, config.num_heads, config.groups)
        self.mid_block2 = ResBlock(cur, cur, time_ch, config.groups)

        self.up_blocks = nn.LayerList()
        self.up_attns = nn.LayerList()
        self.upsamples = nn.LayerList()
        for level, mult in reversed(list(enumerate(config.channel_mult))):
            out_ch = ch * mult
            for _ in range(config.num_res_blocks + 1):
                skip_ch = chans.pop()
                self.up_blocks.append(ResBlock(cur + skip_ch, out_ch,
                                               time_ch, config.groups))
                use_attn = (2 ** level) in config.attention_resolutions
                self.up_attns.append(
                    AttnBlock(out_ch, config.num_heads, config.groups)
                    if use_attn else nn.Identity())
                cur = out_ch
            if level > 0:
                self.upsamples.append(Upsample(cur))
            else:
                self.upsamples.append(nn.Identity())

        self.norm_out = nn.GroupNorm(min(config.groups, cur), cur)
        self.conv_out = nn.Conv2D(cur, config.out_channels, 3, padding=1)

    def forward(self, x, timesteps):
        from ..ops.manipulation import concat
        temb = timestep_embedding(timesteps, self.config.base_channels)
        temb = self.time_mlp2(F.silu(self.time_mlp1(temb)))

        h = self.conv_in(x)
        skips = [h]
        bi = 0
        n_levels = len(self.config.channel_mult)
        for level in range(n_levels):
            for _ in range(self.config.num_res_blocks):
                h = self.down_blocks[bi](h, temb)
                h = self.down_attns[bi](h)
                skips.append(h)
                bi += 1
            if level < n_levels - 1:
                h = self.downsamples[level](h)
                skips.append(h)

        h = self.mid_block1(h, temb)
        h = self.mid_attn(h)
        h = self.mid_block2(h, temb)

        bi = 0
        for idx, level in enumerate(reversed(range(n_levels))):
            for _ in range(self.config.num_res_blocks + 1):
                h = concat([h, skips.pop()], axis=1)
                h = self.up_blocks[bi](h, temb)
                h = self.up_attns[bi](h)
                bi += 1
            if level > 0:
                h = self.upsamples[idx](h)

        return self.conv_out(F.silu(self.norm_out(h)))
