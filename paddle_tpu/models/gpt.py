"""GPT-2/3 family (config #4: 13B-class with recompute + AMP O2; the
reference's auto_parallel tests are built on this model,
ref: /root/reference/test/auto_parallel/auto_parallel_gpt_model.py)."""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import nn
from ..nn import functional as F
from ..framework.tensor import Tensor


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: int = 4096
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-5
    recompute: bool = False

    @staticmethod
    def gpt3_13b():
        return GPTConfig(hidden_size=5120, num_hidden_layers=40,
                         num_attention_heads=40, intermediate_size=20480,
                         max_position_embeddings=2048)

    @staticmethod
    def tiny(vocab=512, hidden=64, layers=2, heads=4, inter=128, seq=64):
        return GPTConfig(vocab_size=vocab, hidden_size=hidden,
                         num_hidden_layers=layers, num_attention_heads=heads,
                         intermediate_size=inter,
                         max_position_embeddings=seq)


def _mp_active():
    from ..distributed.fleet.topology import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    return hcg is not None and hcg.get_model_parallel_world_size() > 1


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        H = config.hidden_size
        self.ln_1 = nn.LayerNorm(H, config.layer_norm_eps)
        self.ln_2 = nn.LayerNorm(H, config.layer_norm_eps)
        if _mp_active():
            from ..distributed.fleet.meta_parallel import (
                ColumnParallelLinear, RowParallelLinear)
            self.qkv = ColumnParallelLinear(H, 3 * H, gather_output=False)
            self.proj = RowParallelLinear(H, H, input_is_parallel=True)
            self.fc_in = ColumnParallelLinear(H, config.intermediate_size,
                                              gather_output=False)
            self.fc_out = RowParallelLinear(config.intermediate_size, H,
                                            input_is_parallel=True)
        else:
            self.qkv = nn.Linear(H, 3 * H)
            self.proj = nn.Linear(H, H)
            self.fc_in = nn.Linear(H, config.intermediate_size)
            self.fc_out = nn.Linear(config.intermediate_size, H)
        self.n_head = config.num_attention_heads
        self.head_dim = H // config.num_attention_heads
        self.attn_drop = nn.Dropout(config.attention_probs_dropout_prob)
        self.resid_drop = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x, cache=None):
        from ..ops.manipulation import concat, reshape, split
        b, l = x.shape[0], x.shape[1]
        h = self.ln_1(x)
        qkv = self.qkv(h)
        q, k, v = split(qkv, 3, axis=-1)
        q = reshape(q, [b, l, self.n_head, self.head_dim])
        k = reshape(k, [b, l, self.n_head, self.head_dim])
        v = reshape(v, [b, l, self.n_head, self.head_dim])
        new_cache = None
        if cache is not None:
            k = concat([cache[0], k], axis=1)
            v = concat([cache[1], v], axis=1)
            new_cache = (k, v)
        attn = F.scaled_dot_product_attention(
            q, k, v, is_causal=l > 1,
            dropout_p=self.attn_drop.p if self.training else 0.0)
        attn = reshape(attn, [b, l, self.n_head * self.head_dim])
        x = x + self.resid_drop(self.proj(attn))
        h = self.ln_2(x)
        h = self.fc_out(F.gelu(self.fc_in(h), approximate=True))
        x = x + self.resid_drop(h)
        if cache is not None:
            return x, new_cache
        return x


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        if _mp_active():
            from ..distributed.fleet.meta_parallel import (
                VocabParallelEmbedding)
            self.wte = VocabParallelEmbedding(config.vocab_size,
                                              config.hidden_size)
        else:
            self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings,
                                config.hidden_size)
        self.drop = nn.Dropout(config.hidden_dropout_prob)
        self.h = nn.LayerList([GPTBlock(config)
                               for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)

    def forward(self, input_ids, caches=None, pos_offset=0):
        import paddle_tpu as paddle
        from ..ops.manipulation import unsqueeze
        l = input_ids.shape[1]
        pos = unsqueeze(paddle.arange(pos_offset, pos_offset + l,
                                      dtype="int64"), 0)
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        new_caches = [] if caches is not None else None
        for i, block in enumerate(self.h):
            if caches is not None:
                x, c = block(x, caches[i])
                new_caches.append(c)
            elif self.config.recompute and self.training:
                from ..distributed.fleet.recompute import recompute
                x = recompute(block, x)
            else:
                x = block(x)
        x = self.ln_f(x)
        if caches is not None:
            return x, new_caches
        return x


class GPTForCausalLM(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)

    def forward(self, input_ids, labels=None):
        h = self.gpt(input_ids)
        from ..ops.linalg import matmul
        logits = matmul(h, self.gpt.wte.weight, transpose_y=True)
        if labels is not None:
            from ..ops.manipulation import reshape
            loss = F.cross_entropy(
                reshape(logits[:, :-1], [-1, self.config.vocab_size]),
                reshape(labels[:, 1:], [-1]))
            return loss, logits
        return logits
