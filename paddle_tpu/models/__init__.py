"""Model zoo: the benchmark families from BASELINE.md."""
from .llama import LlamaConfig, LlamaForCausalLM  # noqa: F401
from .bert import BertConfig, BertForPretraining, BertForSequenceClassification, BertModel  # noqa: F401
from .gpt import GPTConfig, GPTForCausalLM, GPTModel  # noqa: F401
from .unet import UNetConfig, UNetModel  # noqa: F401
