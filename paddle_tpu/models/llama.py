"""Llama family (flagship model).

API mirrors PaddleNLP-style usage on the reference runtime (the reference
repo itself ships kernels for this model class: fused_multi_transformer
ref: /root/reference/paddle/fluid/operators/fused/fused_multi_transformer_op.cu.h:138,420
— rotary embedding + cache-KV decoder attention). Architecture: RMSNorm,
rotary position embeddings, GQA attention, SwiGLU MLP.

Tensor parallelism: when a hybrid mesh with mp>1 is active, q/k/v/gate/up
projections are ColumnParallel and o/down are RowParallel (Megatron
pairing) — full logical weights, GSPMD inserts collectives. The jit-compiled
SPMD trainer for pods lives in models/llama_spmd.py."""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from ..framework.tensor import Tensor
from .. import nn
from ..nn import functional as F


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    recompute: bool = False
    dtype: str = "float32"

    @staticmethod
    def llama2_7b():
        return LlamaConfig()

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, kv_heads=2, inter=128,
             seq=128):
        return LlamaConfig(vocab_size=vocab, hidden_size=hidden,
                           num_hidden_layers=layers,
                           num_attention_heads=heads,
                           num_key_value_heads=kv_heads,
                           intermediate_size=inter,
                           max_position_embeddings=seq)


def _mp_active():
    from ..distributed.fleet.topology import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    return hcg is not None and hcg.get_model_parallel_world_size() > 1


def _linear(in_f, out_f, col=True, gather=False, has_bias=False):
    if _mp_active():
        from ..distributed.fleet.meta_parallel import (ColumnParallelLinear,
                                                       RowParallelLinear)
        if col:
            return ColumnParallelLinear(in_f, out_f, has_bias=has_bias,
                                        gather_output=gather)
        return RowParallelLinear(in_f, out_f, has_bias=has_bias,
                                 input_is_parallel=True)
    return nn.Linear(in_f, out_f, bias_attr=False if not has_bias else None)


def rotate_half(x):
    from ..ops.manipulation import concat, split
    a, b = split(x, 2, axis=-1)
    from ..ops.math import neg
    return concat([neg(b), a], axis=-1)


def apply_rotary_pos_emb(q, k, cos, sin):
    # q,k: [B, L, H, D]; cos/sin: [L, D] broadcast over batch+heads
    q_out = q * cos + rotate_half(q) * sin
    k_out = k * cos + rotate_half(k) * sin
    return q_out, k_out


class LlamaRotaryEmbedding(nn.Layer):
    def __init__(self, dim, max_pos=4096, theta=10000.0):
        super().__init__()
        self.dim = dim
        self.theta = theta
        inv = 1.0 / (theta ** (np.arange(0, dim, 2, dtype=np.float32) / dim))
        t = np.arange(max_pos, dtype=np.float32)
        freqs = np.outer(t, inv)
        emb = np.concatenate([freqs, freqs], axis=-1)
        self.register_buffer("cos_cached", Tensor(np.cos(emb)),
                             persistable=False)
        self.register_buffer("sin_cached", Tensor(np.sin(emb)),
                             persistable=False)

    def forward(self, seq_len, offset=0):
        cos = self.cos_cached[offset:offset + seq_len]
        sin = self.sin_cached[offset:offset + seq_len]
        # [L, D] -> [1, L, 1, D]
        from ..ops.manipulation import unsqueeze
        return unsqueeze(cos, [0, 2]), unsqueeze(sin, [0, 2])


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.hidden_size = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        kv_out = self.num_kv_heads * self.head_dim
        self.q_proj = _linear(self.hidden_size, self.hidden_size, col=True)
        self.k_proj = _linear(self.hidden_size, kv_out, col=True)
        self.v_proj = _linear(self.hidden_size, kv_out, col=True)
        self.o_proj = _linear(self.hidden_size, self.hidden_size, col=False)
        self.rotary = LlamaRotaryEmbedding(
            self.head_dim, config.max_position_embeddings, config.rope_theta)

    def forward(self, x, attn_mask=None, cache=None):
        from ..ops.manipulation import concat, reshape
        b, l = x.shape[0], x.shape[1]
        q = reshape(self.q_proj(x), [b, l, self.num_heads, self.head_dim])
        k = reshape(self.k_proj(x), [b, l, self.num_kv_heads, self.head_dim])
        v = reshape(self.v_proj(x), [b, l, self.num_kv_heads, self.head_dim])

        offset = cache[0].shape[1] if cache is not None else 0
        cos, sin = self.rotary(l, offset)
        q, k = apply_rotary_pos_emb(q, k, cos, sin)

        new_cache = None
        if cache is not None:
            k = concat([cache[0], k], axis=1)
            v = concat([cache[1], v], axis=1)
            new_cache = (k, v)

        # GQA: repeat kv heads
        if self.num_kv_heads != self.num_heads:
            rep = self.num_heads // self.num_kv_heads
            from ..ops.manipulation import repeat_interleave
            k = repeat_interleave(k, rep, axis=2)
            v = repeat_interleave(v, rep, axis=2)

        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            is_causal=(attn_mask is None and l > 1))
        out = reshape(out, [b, l, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        if cache is not None:
            return out, new_cache
        return out


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.gate_proj = _linear(config.hidden_size,
                                 config.intermediate_size, col=True)
        self.up_proj = _linear(config.hidden_size, config.intermediate_size,
                               col=True)
        self.down_proj = _linear(config.intermediate_size,
                                 config.hidden_size, col=False)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          config.rms_norm_eps)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   config.rms_norm_eps)

    def forward(self, x, attn_mask=None, cache=None):
        residual = x
        h = self.input_layernorm(x)
        if cache is not None:
            h, new_cache = self.self_attn(h, attn_mask, cache)
        else:
            h = self.self_attn(h, attn_mask)
            new_cache = None
        x = residual + h
        residual = x
        h = self.post_attention_layernorm(x)
        x = residual + self.mlp(h)
        if cache is not None:
            return x, new_cache
        return x


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        if _mp_active():
            from ..distributed.fleet.meta_parallel import (
                VocabParallelEmbedding)
            self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                       config.hidden_size)
        else:
            self.embed_tokens = nn.Embedding(config.vocab_size,
                                             config.hidden_size)
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None, caches=None):
        x = self.embed_tokens(input_ids)
        new_caches = [] if caches is not None else None
        for i, layer in enumerate(self.layers):
            if caches is not None:
                x, c = layer(x, attn_mask, caches[i])
                new_caches.append(c)
            else:
                if self.config.recompute and self.training:
                    from ..distributed.fleet.recompute import recompute
                    x = recompute(layer, x, attn_mask)
                else:
                    x = layer(x, attn_mask)
        x = self.norm(x)
        if caches is not None:
            return x, new_caches
        return x


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        self.lm_head = _linear(config.hidden_size, config.vocab_size,
                               col=True, gather=True)
        if config.tie_word_embeddings and not _mp_active():
            self.lm_head.weight = self.llama.embed_tokens.weight

    def forward(self, input_ids, labels=None, attn_mask=None):
        h = self.llama(input_ids, attn_mask)
        logits = self.lm_head(h)
        if labels is not None:
            from ..ops.manipulation import reshape
            loss = F.cross_entropy(
                reshape(logits[:, :-1], [-1, self.config.vocab_size]),
                reshape(labels[:, 1:], [-1]))
            return loss, logits
        return logits

    @classmethod
    def from_config(cls, config):
        return cls(config)

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0):
        """Greedy/sampled decode with per-layer KV cache (the reference's
        fused_multi_transformer cache-KV path, fused_multi_transformer_op.cu.h:835)."""
        from ..framework.autograd import no_grad
        from ..ops.manipulation import concat
        from ..ops.search import argmax
        import paddle_tpu as paddle
        with no_grad():
            caches = [( paddle.zeros([input_ids.shape[0], 0,
                                      self.config.num_key_value_heads,
                                      self.config.hidden_size
                                      // self.config.num_attention_heads]),
                        paddle.zeros([input_ids.shape[0], 0,
                                      self.config.num_key_value_heads,
                                      self.config.hidden_size
                                      // self.config.num_attention_heads]))
                      for _ in range(self.config.num_hidden_layers)]
            h, caches = self.llama(input_ids, None, caches)
            logits = self.lm_head(h[:, -1:])
            out = input_ids
            for _ in range(max_new_tokens):
                if temperature > 0:
                    from ..ops.creation import multinomial
                    from ..nn.functional import softmax
                    probs = softmax(logits[:, -1] / temperature, axis=-1)
                    nxt = multinomial(probs, 1)
                else:
                    nxt = argmax(logits[:, -1], axis=-1, keepdim=True)
                out = concat([out, nxt], axis=1)
                h, caches = self.llama(nxt, None, caches)
                logits = self.lm_head(h)
            return out
