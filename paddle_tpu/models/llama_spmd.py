"""Flagship SPMD Llama trainer — the pod-scale performance path.

The reference trains this model class through Fleet hybrid parallel:
per-rank processes, NCCL groups per axis, 1F1B p2p, ZeRO state partitioning
(SURVEY.md §2.4). Here the whole hybrid step is ONE jitted program over the
global mesh:

- dp:        batch dim sharded over 'dp'
- mp (TP):   Megatron column/row sharding on qkv/o and gate/up/down + vocab
             — GSPMD inserts the allreduces
- pp:        decoder stack split into stages, stacked on a 'pp'-sharded
             leading dim, scheduled by the shard_map ppermute pipeline
             (parallel/pipeline.py); backward = AD through the schedule
- sep (SP):  activations and K/V stay sequence-sharded end to end; attention
             is blockwise ring attention with K/V ppermuted around the sep
             ring (parallel/ring_attention.py) — no full K/V gather
- ZeRO:      AdamW moments + fp32 master weights sharded over 'sharding'
- bf16 compute, fp32 master accumulate; per-block jax.checkpoint (remat)

The dygraph/user-facing Llama lives in models/llama.py; this trainer is the
analog of the reference's fused static path (fused_multi_transformer +
distributed_strategy), built TPU-first.
"""
from __future__ import annotations

import functools
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import mesh as mesh_mod
from ..parallel.pipeline import spmd_pipeline
from .llama import LlamaConfig


def _place(a, *spec):
    return mesh_mod.shard_tensor_data(a, P(*spec))


def _on_tpu():
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def _zero_spec(shape, base_spec, axis="sharding"):
    """Add 'sharding' to the first free, divisible dim of base_spec."""
    n = mesh_mod.mesh_axis_size(axis)
    spec = list(base_spec) + [None] * (len(shape) - len(base_spec))
    if n <= 1:
        return P(*spec)
    for i, (dim, s) in enumerate(zip(shape, spec)):
        if s is None and dim % n == 0 and dim >= n:
            spec[i] = axis
            break
    return P(*spec)


class LlamaSpmdTrainer:
    def __init__(self, config: LlamaConfig, lr=3e-4, weight_decay=0.1,
                 beta1=0.9, beta2=0.95, eps=1e-8, remat=True,
                 n_micro=None, seed=0, compute_dtype=jnp.bfloat16,
                 from_state_dict=None, remat_policy="full",
                 n_virtual=1, remat_stage=False,
                 moments_dtype=jnp.float32, ce_remat=True,
                 scan_unroll=1):
        self.config = config
        self.lr = lr
        self.wd = weight_decay
        self.b1, self.b2, self.eps = beta1, beta2, eps
        self.remat = remat
        # 'full': recompute everything in backward (min memory);
        # 'save_dots': keep tagged matmul outputs so backward recompute is
        # mostly elementwise — except the dense attention path (sep>1/CPU),
        # whose O(T^2) QK^T/softmax is rematerialized either way
        # (the reference's recompute granularity knob, RecomputeConfig);
        # 'save_attn': keep only q/k/v/attn_out (what the flash backward
        # reads) and recompute the MLP — the long-context point between
        # 'full' and 'save_dots' where the ffn_gate/ffn_up buffers
        # (2.7x hidden per token) dominate the saved bytes
        if remat_policy not in ("full", "save_dots", "save_attn"):
            raise ValueError(f"remat_policy must be 'full', 'save_dots' "
                             f"or 'save_attn', got {remat_policy!r}")
        self.remat_policy = remat_policy
        self.compute_dtype = compute_dtype
        # AdamW moment storage dtype. fp32 is the default (exact parity
        # with the reference's Adam); bf16 halves optimizer-state HBM
        # (the update math still runs in fp32 — only m/v storage is
        # compressed, master weights stay fp32). The memory-efficient
        # analog of the reference's multi_precision knob.
        self.moments_dtype = moments_dtype
        # ce_remat=True recomputes each CE chunk's logits in backward
        # (min memory); False saves the bf16 chunk logits instead —
        # one head-matmul less recompute when HBM allows
        self.ce_remat = ce_remat
        # unroll factor for the scan over a stage's layers: >1 removes
        # the XLA while-loop (its double-buffered carries and per-layer
        # weight dynamic-slices) at the cost of compile time — worth it
        # for shallow stages
        self.scan_unroll = int(scan_unroll)
        mesh = mesh_mod.get_mesh()
        self.pp = mesh.shape.get("pp", 1)
        self.n_micro = n_micro or max(2 * self.pp, 1)
        # interleaved virtual stages (ref PipelineParallelWithInterleave,
        # pipeline_parallel.py:551): each stage owns n_virtual
        # non-adjacent chunks
        self.n_virtual = int(n_virtual)
        self.remat_stage = remat_stage
        L = config.num_hidden_layers
        n_chunks = self.pp * self.n_virtual
        assert L % n_chunks == 0, \
            "layers must divide pp_degree * n_virtual"
        self.layers_per_stage = L // n_chunks
        # Optional single-chip pallas path: fused rmsnorm+residual and
        # fused AdamW (one HBM pass each). OPT-IN via
        # FLAGS_tpu_fused_block=pallas: measured on v5e, XLA's own fusion
        # of the jnp path is faster in the full training graph (a pallas
        # custom call is a fusion barrier), so the default stays 'xla'.
        # Multi-chip GSPMD always uses jnp — pallas_call doesn't
        # partition under GSPMD without a fully-manual shard_map region.
        from ..flags import get_flag
        self._pallas_fused = (
            _on_tpu() and mesh.size == 1
            and get_flag("FLAGS_tpu_fused_block", "xla") == "pallas")
        self.head_dim = config.hidden_size // config.num_attention_heads
        self._stepno = 0
        self.params = self._init_params(seed)
        self.opt_state = self._init_opt_state()
        self._step_fn = None

    # -- parameters ---------------------------------------------------------
    def _param_specs(self):
        c = self.config
        H = c.hidden_size
        KV = c.num_key_value_heads * self.head_dim
        F = c.intermediate_size
        # block leaves all carry leading dims [pp, layers_per_stage, ...]
        blk = {
            "wq": ((H, H), (None, "mp")),
            "wk": ((H, KV), (None, "mp")),
            "wv": ((H, KV), (None, "mp")),
            "wo": ((H, H), ("mp", None)),
            "wg": ((H, F), (None, "mp")),
            "wu": ((H, F), (None, "mp")),
            "wd": ((F, H), ("mp", None)),
            "ln1": ((H,), (None,)),
            "ln2": ((H,), (None,)),
        }
        return blk

    def _init_params(self, seed):
        c = self.config
        key = jax.random.PRNGKey(seed)
        dt = self.compute_dtype
        H, V = c.hidden_size, c.vocab_size
        keys = jax.random.split(key, 4 + len(self._param_specs()))
        std = 0.02

        def init(k, shape, spec, scale=std, ones=False, rearrange=None):
            if ones:
                # add 0 to escape jnp's constant cache: donated buffers must
                # be unique
                a = jnp.ones(shape, dt) + jnp.zeros((), dt)
            else:
                a = (scale * jax.random.normal(k, shape)).astype(dt)
            if rearrange is not None:
                a = rearrange(a)
            return _place(a, *spec)

        params = {
            "embed": init(keys[0], (V, H), ("mp", None)),
            "norm": init(keys[1], (H,), (None,), ones=True),
            "head": init(keys[2], (H, V), (None, "mp")),
        }
        blocks = {}
        blk_specs = self._param_specs()
        staged = self.n_virtual > 1 and self.pp > 1
        for i, (name, (shape, spec)) in enumerate(blk_specs.items()):
            # leading dim = logical chunks (pp * n_virtual), pp-sharded;
            # with interleave the chunks are rearranged ONCE here into the
            # staged [pp, v, ...] layout (per-step rearrangement would
            # shuffle weights across pp shards every step)
            full_shape = (self.pp * self.n_virtual,
                          self.layers_per_stage) + shape
            full_spec = (("pp", None, None) if staged else
                         ("pp", None)) + spec
            ones = name.startswith("ln")
            from ..parallel.pipeline import interleave_stage_params
            blocks[name] = init(
                keys[3 + i], full_shape, full_spec, scale=std, ones=ones,
                rearrange=(functools.partial(
                    interleave_stage_params, n_stages=self.pp,
                    n_virtual=self.n_virtual) if staged else None))
        params["blocks"] = blocks
        return params

    def _init_opt_state(self):
        def init_state(a):
            shape = a.shape
            base = a.sharding.spec if isinstance(a.sharding,
                                                 NamedSharding) else ()
            spec = _zero_spec(shape, tuple(base))
            mdt = self.moments_dtype
            def zeros():
                # fresh buffer per accumulator (escape the constant cache)
                return jnp.zeros(shape, mdt) + jnp.zeros((), mdt)
            return {
                "m": mesh_mod.shard_tensor_data(zeros(), spec),
                "v": mesh_mod.shard_tensor_data(zeros(), spec),
                "master": mesh_mod.shard_tensor_data(
                    a.astype(jnp.float32) + jnp.zeros((), jnp.float32),
                    spec),
            }
        return jax.tree_util.tree_map(init_state, self.params,
                                      is_leaf=lambda x: hasattr(x, "shape"))

    # -- model math ---------------------------------------------------------
    def _rope(self, T, offset=0):
        d = self.head_dim
        inv = 1.0 / (self.config.rope_theta **
                     (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
        # offset may be traced (axis_index under sequence parallelism)
        t = jnp.arange(T, dtype=jnp.float32) + offset
        freqs = jnp.outer(t, inv)
        emb = jnp.concatenate([freqs, freqs], axis=-1)
        return jnp.cos(emb), jnp.sin(emb)

    def _block(self, bp, x):
        """One decoder block. x: [B, T, H] (dp on B, sep on T).

        Runs in two sharding regimes: plain GSPMD (pp==1), where T is the
        global sequence and 'sep' sharding is a constraint; or inside the
        pipeline's shard_map where 'sep' is a MANUAL axis (jax cannot nest
        new manual axes), T is the per-shard chunk, and rope/attention use
        global positions via axis_index('sep')."""
        c = self.config
        nh = c.num_attention_heads
        nkv = c.num_key_value_heads
        hd = self.head_dim
        dt = x.dtype
        B, T, H = x.shape
        sep_manual = (mesh_mod.mesh_axis_size("sep") > 1
                      and mesh_mod.inside_spmd_region("sep"))

        # under a manual 'sep' the T dim is structurally local;
        # mesh_mod.constraint drops manual-axis entries automatically
        cstr = mesh_mod.constraint

        def rms(h, w):
            h32 = h.astype(jnp.float32)
            out = h32 * jax.lax.rsqrt(
                jnp.mean(h32 * h32, axis=-1, keepdims=True)
                + c.rms_norm_eps)
            return (out * w.astype(jnp.float32)).astype(dt)

        from jax.ad_checkpoint import checkpoint_name

        if self._pallas_fused:
            from ..ops.pallas.fused_norm import fused_rms_norm
            h = fused_rms_norm(x, bp["ln1"], c.rms_norm_eps)
        else:
            h = rms(x, bp["ln1"])
        q = checkpoint_name((h @ bp["wq"]), "q").reshape(B, T, nh, hd)
        k = checkpoint_name((h @ bp["wk"]), "k").reshape(B, T, nkv, hd)
        v = checkpoint_name((h @ bp["wv"]), "v").reshape(B, T, nkv, hd)
        offset = jax.lax.axis_index("sep") * T if sep_manual else 0
        cos, sin = self._rope(T, offset)
        cos = cos[None, :, None, :].astype(dt)
        sin = sin[None, :, None, :].astype(dt)

        def rot(u):
            u1, u2 = jnp.split(u, 2, axis=-1)
            return jnp.concatenate([-u2, u1], axis=-1)

        q = q * cos + rot(q) * sin
        k = k * cos + rot(k) * sin

        scale = 1.0 / math.sqrt(hd)
        sep_n = mesh_mod.mesh_axis_size("sep")
        from ..flags import get_flag
        use_flash = (_on_tpu() and hd % 64 == 0 and T % 128 == 0
                     and sep_n == 1
                     and bool(get_flag("FLAGS_tpu_flash_attention", True)))
        if sep_n > 1:
            # sequence parallel: q/k/v all stay sep-sharded on T; ring
            # attention circulates K/V blocks over the sep axis — per-step
            # score memory O((T/sep)^2), never a full K/V gather
            from ..parallel.ring_attention import ring_attention
            q = cstr(q, "dp", "sep", "mp", None)
            k = cstr(k, "dp", "sep", "mp", None)
            v = cstr(v, "dp", "sep", "mp", None)
            attn = ring_attention(q, k, v, causal=True, sm_scale=scale)
        elif use_flash:
            from ..ops.pallas.flash_attention import flash_attention_blhd
            if nkv != nh:
                # the tuned kernel wants equal head counts
                rep = nh // nkv
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            attn = flash_attention_blhd(q, k, v, causal=True,
                                        sm_scale=scale)
        elif nkv != nh:
            # grouped-query attention without materializing repeated K/V:
            # fold the group dim into the score einsum (g = nh // nkv)
            g = nh // nkv
            qg = q.reshape(B, T, nkv, g, hd)
            scores = jnp.einsum("bqngd,bknd->bngqk", qg, k,
                                preferred_element_type=jnp.float32) * scale
            mask = jnp.tril(jnp.ones((T, T), bool))
            scores = jnp.where(mask, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(dt)
            attn = jnp.einsum("bngqk,bknd->bqngd", probs, v)
            attn = attn.reshape(B, T, nh, hd)
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                                preferred_element_type=jnp.float32) * scale
            mask = jnp.tril(jnp.ones((T, T), bool))
            scores = jnp.where(mask, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(dt)
            attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        attn = checkpoint_name(attn.reshape(B, T, nh * hd), "attn_out")
        if self._pallas_fused:
            # fused residual-add + rmsnorm: one HBM pass (the reference's
            # fused_layernorm_residual_dropout_bias pattern)
            from ..ops.pallas.fused_norm import fused_rms_norm_residual
            h, x = fused_rms_norm_residual(attn @ bp["wo"], x, bp["ln2"],
                                           c.rms_norm_eps)
        else:
            x = x + attn @ bp["wo"]
            h = rms(x, bp["ln2"])
        gate = jax.nn.silu(checkpoint_name(h @ bp["wg"], "ffn_gate"))
        up = checkpoint_name(h @ bp["wu"], "ffn_up")
        x = x + (gate * up) @ bp["wd"]
        return cstr(x, "dp", "sep", None)

    def _stage_fn(self, stage_params, x):
        """Run this stage's layers_per_stage blocks (scan + remat)."""
        block = self._block
        # remat_stage checkpoints the whole stage in the pipeline; nesting
        # per-block checkpoints under it would recompute blocks twice in
        # backward for no extra memory win. With pp==1 no pipeline (and no
        # stage-level checkpoint) runs, so block remat must stay on.
        stage_remat_active = self.remat_stage and self.pp > 1
        if self.remat and not stage_remat_active:
            if self.remat_policy == "save_dots":
                pol = jax.checkpoint_policies.save_only_these_names(
                    "q", "k", "v", "attn_out", "ffn_gate", "ffn_up")
                block = jax.checkpoint(block, policy=pol)
            elif self.remat_policy == "save_attn":
                pol = jax.checkpoint_policies.save_only_these_names(
                    "q", "k", "v", "attn_out")
                block = jax.checkpoint(block, policy=pol)
            else:
                block = jax.checkpoint(block)

        def body(carry, bp):
            return block(bp, carry), None

        out, _ = jax.lax.scan(body, x, stage_params,
                              unroll=max(1, self.scan_unroll))
        return out

    def forward(self, params, ids):
        """ids: [B, T] -> logits [B, T, V]."""
        x = self.forward_hidden(params, ids)
        logits = x @ params["head"]
        return mesh_mod.constraint(logits, "dp", "sep", "mp")

    def forward_hidden(self, params, ids):
        """ids: [B, T] -> final-norm hidden states [B, T, H] (pre-head)."""
        x = jnp.take(params["embed"], ids, axis=0).astype(self.compute_dtype)
        x = mesh_mod.constraint(x, "dp", "sep", None)
        if self.pp > 1:
            B = x.shape[0]
            assert B % self.n_micro == 0, "batch must divide n_micro"
            mb = B // self.n_micro
            x_micro = x.reshape((self.n_micro, mb) + x.shape[1:])
            sep_n = mesh_mod.mesh_axis_size("sep")
            kw = dict(n_virtual=self.n_virtual,
                      remat_stage=self.remat_stage)
            if sep_n > 1:
                kw.update(manual_axes={"sep"},
                          x_spec=P(None, None, "sep"))
            out = spmd_pipeline(self._stage_fn, params["blocks"], x_micro,
                                params_layout="staged" if
                                self.n_virtual > 1 else "logical", **kw)
            x = out.reshape((B,) + out.shape[2:])
        else:
            stage = jax.tree_util.tree_map(
                lambda a: a.reshape((-1,) + a.shape[2:]), params["blocks"])
            x = self._stage_fn(stage, x)
        x32 = x.astype(jnp.float32)
        x32 = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, -1, keepdims=True) + self.config.rms_norm_eps)
        return (x32 * params["norm"].astype(jnp.float32)).astype(
            self.compute_dtype)

    def loss_fn(self, params, ids, labels):
        """Next-token cross entropy, computed CHUNKED over the sequence:
        each lax.scan step projects one T-chunk through the vocab head
        and reduces it to per-token CE (logsumexp - target logit) in
        fp32, under jax.checkpoint so backward recomputes the chunk
        logits instead of saving them. Peak loss memory drops from
        2 full fp32 [B, T, V] buffers (logits + log_softmax) to one
        [B, C, V] chunk — the difference between OOM and fitting a
        bigger batch at vocab 32000 on one chip. Numerics are identical
        to log_softmax + gather (same fp32 logsumexp)."""
        if mesh_mod.mesh_axis_size("sep") > 1:
            # sequence parallel: T is sep-sharded (chunking would fight
            # GSPMD over the reshape) and the per-device logit slab is
            # already T/sep small — use the plain log_softmax path
            logits = self.forward(params, ids).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
            tgt = labels[:, 1:]
            picked = jnp.take_along_axis(logp, tgt[..., None], axis=-1)
            return -picked.mean()
        x = self.forward_hidden(params, ids)          # [B, T, H]
        B, T, H = x.shape
        head = params["head"]
        # position t predicts labels[t+1]; the final position has no
        # target — give it a dummy and mask it out of the mean
        tgt = jnp.concatenate([labels[:, 1:], labels[:, :1]], axis=1)
        C = min(256, T)
        while T % C:
            C //= 2
        nC = T // C
        xs = jnp.moveaxis(x.reshape(B, nC, C, H), 1, 0)       # [nC,B,C,H]
        ts = jnp.moveaxis(tgt.reshape(B, nC, C), 1, 0)        # [nC,B,C]

        def chunk_ce(xc, tc):
            logits = (xc @ head).astype(jnp.float32)          # [B, C, V]
            logits = mesh_mod.constraint(logits, "dp", None, "mp")
            lse = jax.nn.logsumexp(logits, axis=-1)           # [B, C]
            picked = jnp.take_along_axis(
                logits, tc[..., None], axis=-1)[..., 0]
            return lse - picked                               # [B, C]

        def body(total, xc_tc):
            return total + chunk_ce(*xc_tc).sum(axis=-1), None

        if nC > 1:
            b = jax.checkpoint(body) if self.ce_remat else body
            ce_rows, _ = jax.lax.scan(b, jnp.zeros((B,), jnp.float32),
                                      (xs, ts))
            # subtract the masked final position's dummy CE
            ce_rows = ce_rows - chunk_ce(x[:, -1:], tgt[:, -1:])[:, 0]
        else:
            ce = chunk_ce(x, tgt)                             # [B, T]
            ce_rows = ce[:, :-1].sum(axis=-1)
        return ce_rows.sum() / (B * (T - 1))

    # -- optimizer ----------------------------------------------------------
    def _adamw(self, p, g, st, lr, step):
        if self._pallas_fused and self.moments_dtype == jnp.float32:
            # one fused pallas pass over p/g/m/v/master (the reference's
            # fused_adam multi-tensor kernel, fused_adam_kernel.cu)
            from ..ops.pallas.fused_adamw import fused_adamw_update
            new_p, m, v, master = fused_adamw_update(
                p, g, st["m"], st["v"], st["master"], lr, self.b1,
                self.b2, self.eps, self.wd, step)
            return new_p, {"m": m, "v": v, "master": master}
        g32 = g.astype(jnp.float32)
        m = self.b1 * st["m"].astype(jnp.float32) + (1 - self.b1) * g32
        v = (self.b2 * st["v"].astype(jnp.float32)
             + (1 - self.b2) * g32 * g32)
        mh = m / (1 - self.b1 ** step)
        vh = v / (1 - self.b2 ** step)
        upd = mh / (jnp.sqrt(vh) + self.eps) + self.wd * st["master"]
        master = st["master"] - lr * upd
        mdt = self.moments_dtype
        return master.astype(p.dtype), {"m": m.astype(mdt),
                                        "v": v.astype(mdt),
                                        "master": master}

    def _make_step(self):
        def step(params, opt_state, ids, labels, lr, stepno):
            loss, grads = jax.value_and_grad(self.loss_fn)(params, ids,
                                                           labels)
            leaves_p, tree = jax.tree_util.tree_flatten(params)
            leaves_g = jax.tree_util.tree_leaves(grads)
            leaves_s = tree.flatten_up_to(opt_state)
            new_p, new_s = [], []
            for p, g, st in zip(leaves_p, leaves_g, leaves_s):
                np_, ns = self._adamw(p, g, st, lr, stepno)
                new_p.append(np_)
                new_s.append(ns)
            return (loss, jax.tree_util.tree_unflatten(tree, new_p),
                    jax.tree_util.tree_unflatten(tree, new_s))
        return jax.jit(step, donate_argnums=(0, 1))

    def train_step(self, ids, labels=None):
        if labels is None:
            labels = ids
        if self._step_fn is None:
            self._step_fn = self._make_step()
        self._stepno += 1
        ids = _place(jnp.asarray(ids), "dp", None)
        labels = _place(jnp.asarray(labels), "dp", None)
        loss, self.params, self.opt_state = self._step_fn(
            self.params, self.opt_state, ids, labels,
            jnp.asarray(self.lr, jnp.float32),
            jnp.asarray(self._stepno, jnp.float32))
        return loss

    # -- analytics ----------------------------------------------------------
    def flops_per_token(self, seq_len=None):
        """Training FLOPs/token, strict Megatron/PaLM convention:

        - 6 * params-in-matmuls, where the vocab projection is counted
          ONCE (the logit head V*H). The input-embedding forward is a
          gather and its backward a scatter-add — no matmul FLOPs, so the
          untied embedding table contributes nothing here even though the
          hardware does real (uncounted) work for it.
        - causal attention quadratic term: QK^T and PV are 2*H*T_eff fwd
          flops each per token with T_eff = T/2 under causal masking;
          backward doubles the forward, so train = 3x fwd = 6*H*T per
          layer per token.
        - Remat recompute is NOT counted (MFU convention: model FLOPs
          only).
        """
        c = self.config
        H, F, V = c.hidden_size, c.intermediate_size, c.vocab_size
        T = seq_len or c.max_position_embeddings
        KV = c.num_key_value_heads * self.head_dim
        per_layer = 2 * H * H + 2 * H * KV + 3 * H * F
        matmul_params = c.num_hidden_layers * per_layer + V * H
        attn = 6 * c.num_hidden_layers * H * T
        return 6 * matmul_params + attn

    def param_count(self):
        return sum(int(np.prod(l.shape)) for l in
                   jax.tree_util.tree_leaves(self.params))
