"""Fused transformer layers (ref: /root/reference/python/paddle/incubate/nn/
layer/fused_transformer.py — FusedMultiTransformer:1021 with
cache_kvs/time_step decode path; CUDA impl
fused_multi_transformer_op.cu.h:138 (attention), :420 (ffn), :835
(cache-KV decode)).

The reference fuses qkv+rotary+cacheKV+attention+residual+LN into one CUDA
kernel chain; here each block is a single jnp expression chain — XLA fuses
the elementwise segments into the GEMMs, and decode-time cache append is a
dynamic_update_slice into a preallocated [B, max_len, H, D] cache (static
shapes, MXU-friendly)."""
from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.op import apply
from ...framework.tensor import Tensor
from ... import nn
from ...nn import functional as F


def _use_decode_kernel():
    from ...flags import get_flag
    try:
        on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover
        on_tpu = False
    return on_tpu and bool(get_flag("FLAGS_enable_pallas_kernels", True))


class FusedMultiHeadAttention(nn.Layer):
    """ref: fused_transformer.py FusedMultiHeadAttention — pre/post LN +
    qkv proj + attention + out proj + residual in one call."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.qkv = nn.Linear(embed_dim, 3 * embed_dim)
        self.out_proj = nn.Linear(embed_dim, embed_dim)
        self.norm = nn.LayerNorm(embed_dim, epsilon)
        self.dropout = nn.Dropout(dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        from ...ops.manipulation import reshape, split
        residual = query
        x = self.norm(query) if self.normalize_before else query
        b, l = x.shape[0], x.shape[1]
        q, k, v = split(self.qkv(x), 3, axis=-1)
        q = reshape(q, [b, l, self.num_heads, self.head_dim])
        k = reshape(k, [b, l, self.num_heads, self.head_dim])
        v = reshape(v, [b, l, self.num_heads, self.head_dim])
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask)
        out = self.out_proj(reshape(out, [b, l, self.embed_dim]))
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(nn.Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-05, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.fc1 = nn.Linear(d_model, dim_feedforward)
        self.fc2 = nn.Linear(dim_feedforward, d_model)
        self.norm = nn.LayerNorm(d_model, epsilon)
        self.dropout = nn.Dropout(dropout_rate)
        self.act = getattr(F, activation)

    def forward(self, src, cache=None):
        residual = src
        x = self.norm(src) if self.normalize_before else src
        x = self.fc2(self.dropout(self.act(self.fc1(x))))
        x = residual + self.dropout(x)
        if not self.normalize_before:
            x = self.norm(x)
        return x


class FusedTransformerEncoderLayer(nn.Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False, **kw):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate,
            attn_dropout_rate or dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(d_model, dim_feedforward, dropout_rate,
                                    activation=activation,
                                    normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedMultiTransformer(nn.Layer):
    """Decoder stack with preallocated KV caches + time_step decode
    (ref: fused_transformer.py:1021). cache_kvs: per-layer
    [2, B, H, max_len, D] like the reference; time_step selects decode
    branch (single-token append via dynamic_update_slice)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, ln_bias_attrs=None,
                 qkv_weight_attrs=None, qkv_bias_attrs=None,
                 linear_weight_attrs=None, linear_bias_attrs=None,
                 ffn_ln_scale_attrs=None, ffn_ln_bias_attrs=None,
                 ffn1_weight_attrs=None, ffn1_bias_attrs=None,
                 ffn2_weight_attrs=None, ffn2_bias_attrs=None,
                 epsilon=1e-5, num_layers=-1, nranks=1, trans_qkvw=True,
                 ring_id=-1, name=None):
        super().__init__()
        if num_layers == -1:
            num_layers = len(qkv_weight_attrs) if qkv_weight_attrs else 1
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.num_layers = num_layers
        self.normalize_before = normalize_before
        self.layers = nn.LayerList()
        for _ in range(num_layers):
            blk = nn.Layer()
            blk.ln = nn.LayerNorm(embed_dim, epsilon)
            blk.qkv = nn.Linear(embed_dim, 3 * embed_dim)
            blk.out_proj = nn.Linear(embed_dim, embed_dim)
            blk.ffn_ln = nn.LayerNorm(embed_dim, epsilon)
            blk.ffn1 = nn.Linear(embed_dim, dim_feedforward)
            blk.ffn2 = nn.Linear(dim_feedforward, embed_dim)
            self.layers.append(blk)
        self._act_name = activation
        self.activation = getattr(F, activation)

    def gen_cache(self, batch, max_len, dtype="float32"):
        import paddle_tpu as paddle
        # round the cache length up to a lane multiple: the flash-decode
        # kernel blocks the cache axis in 128-wide steps, and a max_len
        # like 200 would otherwise force an 8-wide block (16x more grid
        # steps for the same bytes)
        if max_len > 128:
            max_len = -(-max_len // 128) * 128
        return [paddle.zeros([2, batch, self.num_heads, max_len,
                              self.head_dim], dtype=dtype)
                for _ in range(self.num_layers)]

    def gen_paged_cache(self, block_size, num_blocks, max_seqs,
                        max_blocks_per_seq=None, dtype="float32",
                        prefix_cache=False):
        """Block-paged alternative to gen_cache: returns a PagedKVCache
        whose ``.views`` list rides in the same ``caches=`` argument —
        the cache layout is a protocol, not a tensor shape (see
        inference/paged_cache.py). ``prefix_cache`` turns on the
        cross-request chained-hash block index + cached-free tier."""
        from ...inference.paged_cache import PagedKVCache
        return PagedKVCache.for_model(
            self, block_size, num_blocks, max_seqs,
            max_blocks_per_seq=max_blocks_per_seq, dtype=dtype,
            prefix_cache=prefix_cache)

    def _proj(self, i, blk, name, x):
        """Linear-projection hook; the int8 subclass overrides this."""
        return getattr(blk, name)(x)

    def _ffn_block(self, i, blk, x):
        """Post-attention FFN sub-block (residual + LN wrapping included).

        Overridable seam: the attention/cache schedule in forward() is
        shared by every serving mode, so a subclass that only changes the
        FFN (e.g. inference.moe_serving.MoeServingCore's routed expert
        FFN) inherits all paged/prefix/speculative cache behavior."""
        residual = x
        h = blk.ffn_ln(x) if self.normalize_before else x
        h = self._proj(i, blk, "ffn2", self.activation(
            self._proj(i, blk, "ffn1", h)))
        x = residual + h
        if not self.normalize_before:
            x = blk.ffn_ln(x)
        return x

    def forward(self, src, attn_mask=None, caches=None, time_step=None,
                **kwargs):
        from ...ops.manipulation import reshape, split, transpose
        x = src
        b, l = x.shape[0], x.shape[1]
        new_caches = [] if caches is not None else None
        for i, blk in enumerate(self.layers):
            residual = x
            h = blk.ln(x) if self.normalize_before else x
            q, k, v = split(self._proj(i, blk, "qkv", h), 3, axis=-1)
            q = reshape(q, [b, l, self.num_heads, self.head_dim])
            k = reshape(k, [b, l, self.num_heads, self.head_dim])
            v = reshape(v, [b, l, self.num_heads, self.head_dim])
            if caches is not None and time_step is not None and \
                    getattr(caches[i], "is_paged", False):
                # paged-cache protocol (inference/paged_cache.py): the
                # per-layer view appends k/v through its block table
                # and attends over the sequence's pages — Pallas paged
                # kernel on TPU, jnp gather + the same masked-sdpa
                # codepath as the dense ragged branch on CPU (so paged
                # and dense decode stay bit-identical there). l == 1
                # is the plain decode step; l > 1 appends l tokens per
                # row from time_step on and scores each causally (the
                # speculative-decode verification step). Prompt
                # PREFILL rides the same protocol through
                # PagedKVCache.prefill_views: batch-1 chunk calls
                # whose per-layer PagedPrefillView appends the chunk
                # straight into the slot's pages and attends with a
                # multi-row masked sdpa (inference/scheduler.py
                # chunked_prefill) — no dense scratch. All paths
                # assume the block tables already cover [t, t+l).
                t = time_step.data if isinstance(time_step, Tensor) \
                    else jnp.asarray(time_step, jnp.int32)
                # per-row positions like the ragged dense path; a
                # scalar/shape-[1] time_step broadcasts across rows
                t = jnp.broadcast_to(t.reshape(-1).astype(jnp.int32),
                                     (b,))
                attn = caches[i].decode(q, k, v, t,
                                        use_kernel=_use_decode_kernel())
                new_caches.append(caches[i])
            elif caches is not None and time_step is not None:
                # decode: append k/v at time_step into the static cache.
                # time_step stays a TRACED scalar (dynamic_update_slice,
                # the decode-kernel lens, and the mask below all accept
                # traced indices) — no host sync, no per-step retrace,
                # and forward can sit under jit with a traced time_step.
                cache = caches[i]
                # python-int time_step keeps a static fast path (slice
                # instead of full-cache mask); Tensor/traced time_step
                # stays traced — no host sync, no per-step retrace
                t_static = int(time_step) if isinstance(
                    time_step, (int, np.integer)) else None
                t = time_step.data if isinstance(time_step, Tensor) \
                    else jnp.asarray(time_step, jnp.int32)
                # ragged = per-row positions, a [batch] vector
                # (continuous-batching serving, every slot at its own
                # cache offset — ref masked-mha per-batch lens,
                # fused_multi_transformer_op.cu.h:835). The reference
                # API's documented shape-[1] time_step stays a SCALAR
                # (b==1 per-row is equivalent anyway).
                ragged = t.ndim == 1 and b > 1 and t.shape[0] == b
                if not ragged:
                    t = t.reshape(())

                if ragged:
                    def upd(c, ka, va, tv):
                        def row(cs, ks, vs, tb):  # cs [2, H, S, D]
                            kc = jax.lax.dynamic_update_slice(
                                cs[0], ks, (0, tb, 0))
                            vc = jax.lax.dynamic_update_slice(
                                cs[1], vs, (0, tb, 0))
                            return jnp.stack([kc, vc])
                        return jax.vmap(row, in_axes=(1, 0, 0, 0),
                                        out_axes=1)(
                            c, jnp.moveaxis(ka, 1, 2),
                            jnp.moveaxis(va, 1, 2), tv)
                    cache = apply(upd, (cache, k, v, Tensor(t)),
                                  op_name="cache_kv")
                else:
                    def upd(c, ka, va):
                        kc = jax.lax.dynamic_update_slice(
                            c[0], jnp.moveaxis(ka, 1, 2), (0, 0, t, 0))
                        vc = jax.lax.dynamic_update_slice(
                            c[1], jnp.moveaxis(va, 1, 2), (0, 0, t, 0))
                        return jnp.stack([kc, vc])
                    cache = apply(upd, (cache, k, v), op_name="cache_kv")
                new_caches.append(cache)
                if l == 1 and _use_decode_kernel():
                    # flash-decoding over the static cache (ref
                    # fused_multi_transformer_op.cu.h:835 masked mha)
                    from ...ops.pallas.decode_attention import \
                        decode_attention

                    if ragged:
                        # t rides as an ARGUMENT: a traced closure cell
                        # would bust the per-op executable cache
                        def dec_r(c, q_, tv):
                            kc = jnp.swapaxes(c[0], 1, 2)  # [B,S,H,D]
                            vc = jnp.swapaxes(c[1], 1, 2)
                            return decode_attention(q_[:, 0], kc, vc,
                                                    tv + 1)[:, None]
                        attn = apply(dec_r, (cache, q, Tensor(t)),
                                     op_name="decode_attention")
                    else:
                        def dec(c, q_):
                            kc = jnp.swapaxes(c[0], 1, 2)
                            vc = jnp.swapaxes(c[1], 1, 2)
                            lens = jnp.zeros((q_.shape[0],), jnp.int32) \
                                + (t + 1)
                            return decode_attention(q_[:, 0], kc, vc,
                                                    lens)[:, None]
                        attn = apply(dec, (cache, q),
                                     op_name="decode_attention")
                elif t_static is not None:
                    # static t: slice just the valid prefix (much
                    # cheaper than attending over max_len when t << S)
                    ts = t_static
                    k_full = transpose(cache[0], [0, 2, 1, 3])[:, :ts + l]
                    v_full = transpose(cache[1], [0, 2, 1, 3])[:, :ts + l]
                    mask = None
                    if l > 1:
                        qpos = ts + jnp.arange(l)[:, None]
                        kpos = jnp.arange(ts + l)[None, :]
                        mask = Tensor(jnp.where(kpos <= qpos, 0.0, -1e30)
                                      .astype(jnp.float32))
                    attn = F.scaled_dot_product_attention(
                        q, k_full, v_full, attn_mask=mask)
                else:
                    # traced t: attend over the FULL static cache with a
                    # validity mask (a [:t+l] slice would need static
                    # t): query i sees cache pos <= t+i. Ragged t ([B])
                    # builds a per-row mask [B, 1, l, S].
                    S = cache.shape[3]
                    k_full = transpose(cache[0], [0, 2, 1, 3])
                    v_full = transpose(cache[1], [0, 2, 1, 3])
                    if ragged:
                        qpos = (t[:, None, None, None]
                                + jnp.arange(l)[None, None, :, None])
                        kpos = jnp.arange(S)[None, None, None, :]
                    else:
                        qpos = t + jnp.arange(l)[:, None]
                        kpos = jnp.arange(S)[None, :]
                    mask = Tensor(jnp.where(kpos <= qpos, 0.0, -1e30)
                                  .astype(jnp.float32))
                    attn = F.scaled_dot_product_attention(
                        q, k_full, v_full, attn_mask=mask)
            else:
                attn = F.scaled_dot_product_attention(
                    q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None)
                if caches is not None:
                    new_caches.append(caches[i])
            attn = self._proj(i, blk, "out_proj",
                              reshape(attn, [b, l, self.embed_dim]))
            x = residual + attn
            if not self.normalize_before:
                x = blk.ln(x)
            x = self._ffn_block(i, blk, x)
        if caches is not None:
            return x, new_caches
        return x

class FusedMultiTransformerInt8(FusedMultiTransformer):
    """Int8 weight-quantized decoder stack (ref: fused_multi_transformer
    _int8 op, /root/reference/paddle/fluid/operators/fused/
    fused_multi_transformer_int8_op.cu + attn_gemm_int8.h's cublasLt int8
    GEMMs — here the MXU int8 path via quantization.quantized_matmul).

    Construct with float weights (same signature as FusedMultiTransformer)
    then call `quantize_weights()` — per-out-channel abs-max int8 — or
    build from a trained FusedMultiTransformer with `from_float(model)`.
    Activations stay bf16/fp32 (weight-only), the dominant TPU serving
    mode. The forward schedule is inherited; only the linear projections
    (_proj) change."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._quantized = False

    def quantize_weights(self, bits=8):
        """Snapshot int8 weights and DROP the float linear weights:
        quantization freezes the weights at this point (later float-side
        mutation cannot silently desync from the int8 copies, and the
        float tensors stop double-counting in parameters()). The int8
        weights + scales are registered as persistable BUFFERS on each
        linear, so state_dict()/set_state_dict round-trip the quantized
        model (construct + quantize_weights() first, then load)."""
        import jax.numpy as _jnp
        from ...quantization.functional import quantize as _quantize
        if self._quantized:
            raise RuntimeError(
                "already quantized: the float weights were dropped at "
                "quantize time. To re-quantize at a different bit width, "
                "rebuild via FusedMultiTransformerInt8.from_float(model, "
                "bits=...) from the float model.")
        self._bits = bits
        self._int8 = []
        for blk in self.layers:
            entry = {}
            for name in ("qkv", "out_proj", "ffn1", "ffn2"):
                lin = getattr(blk, name)
                w = lin.weight.data
                # all-zero channels would give scale 0 -> NaN int8
                scale = _jnp.maximum(_jnp.max(_jnp.abs(w), axis=0), 1e-8)
                wq = _quantize(lin.weight, scale, bits=bits, axis=-1)
                wq = wq if isinstance(wq, Tensor) else Tensor(wq)
                scale_t = Tensor(scale)
                lin.weight = None  # Layer.__setattr__ drops the param
                lin.register_buffer("weight_int8", wq)
                lin.register_buffer("weight_scale", scale_t)
                # entry aliases the SAME Tensor objects as the buffers:
                # set_state_dict mutates them in place (set_value), so a
                # reloaded checkpoint reaches _proj without re-wiring
                entry[name] = (wq, scale_t, lin.bias)
            self._int8.append(entry)
        self._quantized = True
        return self

    @classmethod
    def from_float(cls, model: "FusedMultiTransformer", bits: int = 8):
        m = cls(model.embed_dim, model.num_heads,
                model.layers[0].ffn1.weight.shape[1],
                activation=model._act_name,
                num_layers=model.num_layers,
                normalize_before=model.normalize_before,
                epsilon=model.layers[0].ln._epsilon)
        # copy the float model's values into m's OWN Parameter objects
        # (jnp arrays are immutable, so sharing the array data is safe;
        # sharing the modules by reference is not — the source model
        # would see its weights dropped by quantize_weights, and later
        # source-side updates would silently desync from the int8 copies)
        for dst, srcb in zip(m.layers, model.layers):
            for name in ("ln", "qkv", "out_proj", "ffn_ln", "ffn1",
                         "ffn2"):
                dmod, smod = getattr(dst, name), getattr(srcb, name)
                for pname, p in smod._parameters.items():
                    if p is not None and \
                            dmod._parameters.get(pname) is not None:
                        dmod._parameters[pname]._data = p.data
        return m.quantize_weights(bits=bits)

    def _proj(self, i, blk, name, x):
        if not self._quantized:
            raise RuntimeError("call quantize_weights() (or from_float) "
                               "before forward")
        import jax.numpy as jnp
        from ...quantization.functional import quantized_matmul
        wq, scale, bias = self._int8[i][name]
        # dequantize with the SAME bit width used at quantize time; the
        # activation dtype (bf16 in serving) flows through unchanged
        out = quantized_matmul(x, wq, scale, bits=self._bits,
                               out_dtype=jnp.dtype(str(x.dtype)))
        return out + bias if bias is not None else out
