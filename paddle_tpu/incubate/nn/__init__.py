"""paddle.incubate.nn — fused transformer layers (ref: /root/reference/
python/paddle/incubate/nn/layer/fused_transformer.py; CUDA kernels
paddle/fluid/operators/fused/fused_multi_transformer_op.cu,
fused_attention_op.cu, fused_feedforward_op.cu).

On TPU "fused" means: written as one jnp chain so XLA fuses the elementwise
work into the GEMMs, with the flash-attention pallas kernel on the score
path. The classes keep the reference's weight-list API."""
from . import functional  # noqa: F401
from .memory_efficient_attention import (  # noqa: F401
    memory_efficient_attention)
from .layers import (FusedBiasDropoutResidualLayerNorm,  # noqa: F401
                     FusedDropout, FusedDropoutAdd, FusedEcMoe,
                     FusedLinear)
from .fused_transformer import (FusedFeedForward, FusedMultiHeadAttention,  # noqa: F401
                                FusedMultiTransformer,
                                FusedMultiTransformerInt8,
                                FusedTransformerEncoderLayer)

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedMultiTransformer",
           "FusedMultiTransformerInt8"]
