"""paddle.incubate.nn.memory_efficient_attention (ref: /root/reference/
python/paddle/incubate/nn/memory_efficient_attention.py:70 — the cutlass
memory-efficient attention binding).

On TPU the memory-efficient algorithm IS flash attention: the call
routes to the Pallas flash kernel (ops/pallas/flash_attention.py) via
nn.functional, with the reference's attn_bias type surface mapped to
mask/causal arguments."""
from __future__ import annotations

import math
from typing import Optional

from ...framework.tensor import Tensor
from ...nn import functional as F

__all__ = ["memory_efficient_attention", "LowerTriangularMask",
           "BlockDiagonalMask"]


class LowerTriangularMask:
    """ref attn_bias LowerTriangularMask — causal attention marker."""


class BlockDiagonalMask:
    """Simplified block-diagonal bias: materialize() gives the additive
    mask (the reference builds this from seqlen lists)."""

    def __init__(self, q_seqinfo, k_seqinfo=None):
        self.q_seqinfo = q_seqinfo
        self.k_seqinfo = k_seqinfo or q_seqinfo

    def materialize(self):
        import numpy as np
        qs = list(self.q_seqinfo)
        ks = list(self.k_seqinfo)
        Lq, Lk = sum(qs), sum(ks)
        mask = np.full((Lq, Lk), -1e30, np.float32)
        q0 = k0 = 0
        for lq, lk in zip(qs, ks):
            mask[q0:q0 + lq, k0:k0 + lk] = 0.0
            q0 += lq
            k0 += lk
        return Tensor(mask)


def memory_efficient_attention(query, key, value, attn_bias=None,
                               p: float = 0.0,
                               scale: Optional[float] = None,
                               training: bool = True):
    """ref memory_efficient_attention.py:70. query/key/value
    [B, L, H, D]; attn_bias: None | Tensor (additive) |
    LowerTriangularMask (causal) | BlockDiagonalMask."""
    causal = isinstance(attn_bias, LowerTriangularMask)
    mask = None
    if isinstance(attn_bias, BlockDiagonalMask):
        mask = attn_bias.materialize()
    elif isinstance(attn_bias, Tensor):
        mask = attn_bias
    dropout = p if training else 0.0
    if scale is not None:
        # sdpa scales by 1/sqrt(d) internally; fold a custom scale into q
        query = query * (scale * math.sqrt(query.shape[-1]))
    return F.scaled_dot_product_attention(
        query, key, value, attn_mask=mask, dropout_p=dropout,
        is_causal=causal)
