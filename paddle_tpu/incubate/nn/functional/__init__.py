"""paddle.incubate.nn.functional — the fused-op functional surface
(ref: /root/reference/python/paddle/incubate/nn/functional/__init__.py;
CUDA impls fused_attention_op.cu / fused_feedforward_op.cu /
fused_multi_transformer_op.cu / fused_gemm_epilogue_op.cu /
fused_ec_moe via cutlass moe_kernel.cu).

TPU design: each "fused op" is ONE jnp expression chain — XLA fuses the
elementwise pieces into the surrounding GEMMs, which is exactly what the
hand-written CUDA kernels buy on GPU. Under jit these compile to the
same fused HLO the dedicated kernels would; the Pallas variants for the
truly bandwidth-bound cases live in ops/pallas/.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ....framework.op import apply
from ....framework.tensor import Tensor
from ....framework import random as _random

__all__ = ["fused_multi_head_attention", "fused_feedforward",
           "fused_multi_transformer", "fused_matmul_bias", "fused_linear",
           "fused_bias_dropout_residual_layer_norm", "fused_ec_moe",
           "fused_dropout_add", "fused_gate_attention"]


def _dropout(a, rate, training, key, mode="upscale_in_train"):
    """Both reference modes (nn.functional dropout semantics):
    upscale_in_train — train: kept/keep, infer: identity;
    downscale_in_infer — train: kept unscaled, infer: a*(1-p)."""
    if rate == 0.0:
        return a
    keep = 1.0 - rate
    if not training:
        return a if mode == "upscale_in_train" \
            else (a * keep).astype(a.dtype)
    mask = jax.random.bernoulli(key, keep, a.shape)
    kept = a / keep if mode == "upscale_in_train" else a
    return jnp.where(mask, kept, 0.0).astype(a.dtype)


def _ln(a, scale, bias, eps):
    mu = a.mean(-1, keepdims=True)
    var = ((a - mu) ** 2).mean(-1, keepdims=True)
    out = (a - mu) / jnp.sqrt(var + eps)
    if scale is not None:
        out = out * scale
    if bias is not None:
        out = out + bias
    return out


def fused_matmul_bias(x, y, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    """ref fused_matmul_bias.py:21 (cublasLt gemm+epilogue on GPU; one
    dot with fused add here)."""
    def impl(x_, y_, *b):
        if transpose_x:
            x_ = jnp.swapaxes(x_, -1, -2)
        if transpose_y:
            y_ = jnp.swapaxes(y_, -1, -2)
        out = jnp.matmul(x_, y_)
        return out + b[0] if b else out
    args = (x, y) + ((bias,) if bias is not None else ())
    return apply(impl, args, op_name="fused_matmul_bias")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """ref fused_matmul_bias.py:72."""
    return fused_matmul_bias(x, weight, bias,
                             transpose_y=transpose_weight)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """ref fused_dropout_add.py:23 — dropout(x) + y in one pass."""
    key = _random.next_key()

    def impl(x_, y_, k):
        return _dropout(x_, p, training, k, mode) + y_
    return apply(impl, (x, y, key), op_name="fused_dropout_add")


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """ref fused_transformer.py:274 —
    layer_norm(residual + dropout(x + bias))."""
    key = _random.next_key()
    opt = [t for t in (bias, ln_scale, ln_bias) if t is not None]
    has = (bias is not None, ln_scale is not None, ln_bias is not None)

    def impl(x_, res, k, *rest):
        it = iter(rest)
        b = next(it) if has[0] else None
        s = next(it) if has[1] else None
        lb = next(it) if has[2] else None
        h = x_ + b if b is not None else x_
        h = res + _dropout(h, dropout_rate, training, k, mode)
        return _ln(h, s, lb, ln_epsilon)
    return apply(impl, (x, residual, key, *opt),
                 op_name="fused_bias_dropout_residual_layer_norm")


def fused_feedforward(x, linear1_weight, linear2_weight,
                      linear1_bias=None, linear2_bias=None,
                      ln1_scale=None, ln1_bias=None, ln2_scale=None,
                      ln2_bias=None, dropout1_rate=0.5, dropout2_rate=0.5,
                      activation="relu", ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False,
                      training=True, mode="upscale_in_train", ring_id=-1,
                      add_residual=True, name=None):
    """ref fused_transformer.py:31 — (pre/post-)LN + linear + act +
    dropout + linear + dropout + residual, the fused_feedforward_op.cu
    schedule."""
    k1, k2 = _random.next_key(), _random.next_key()
    opt = {"l1b": linear1_bias, "l2b": linear2_bias, "s1": ln1_scale,
           "b1": ln1_bias, "s2": ln2_scale, "b2": ln2_bias}
    names = [n for n, t in opt.items() if t is not None]
    tensors = [opt[n] for n in names]

    def impl(x_, w1, w2, ka, kb, *rest):
        d = dict(zip(names, rest))
        # exact-gelu default, matching nn.functional.gelu / the reference
        # (jax.nn.gelu would silently use the tanh approximation)
        act = (lambda a: jax.nn.gelu(a, approximate=False)) \
            if activation == "gelu" else \
            (getattr(jax.nn, activation, None) or getattr(jnp, activation))
        residual = x_
        h = _ln(x_, d.get("s1"), d.get("b1"), ln1_epsilon) \
            if pre_layer_norm else x_
        h = jnp.matmul(h, w1)
        if "l1b" in d:
            h = h + d["l1b"]
        h = _dropout(act(h), dropout1_rate, training, ka, mode)
        h = jnp.matmul(h, w2)
        if "l2b" in d:
            h = h + d["l2b"]
        h = _dropout(h, dropout2_rate, training, kb, mode)
        if add_residual:
            h = residual + h
        if not pre_layer_norm:
            h = _ln(h, d.get("s2"), d.get("b2"), ln2_epsilon)
        return h
    return apply(impl, (x, linear1_weight, linear2_weight, k1, k2,
                        *tensors), op_name="fused_feedforward")


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None,
        cache_kv=None, attn_mask=None, dropout_rate=0.5,
        attn_dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", ring_id=-1, add_residual=True,
        num_heads=-1, transpose_qkv_wb=False, name=None):
    """ref fused_transformer.py:464 (fused_attention_op.cu). qkv_weight:
    [3, n_heads, head_dim, embed_dim] (or [embed_dim, 3*embed_dim] with
    transpose_qkv_wb=True, then num_heads is required)."""
    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention(cache_kv=...) is not wired in "
            "the functional entry; use incubate.nn.FusedMultiTransformer "
            "(caches/time_step decode path) — silently ignoring the "
            "cache would corrupt autoregressive decode")
    k1, k2 = _random.next_key(), _random.next_key()
    opt = {"pls": pre_ln_scale, "plb": pre_ln_bias, "ls": ln_scale,
           "lb": ln_bias, "qb": qkv_bias, "ob": linear_bias,
           "mask": attn_mask}
    names = [n for n, t in opt.items() if t is not None]
    tensors = [opt[n] for n in names]

    def impl(x_, qkvw, ow, ka, kb, *rest):
        d = dict(zip(names, rest))
        B, L, E = x_.shape
        residual = x_
        h = _ln(x_, d.get("pls"), d.get("plb"), pre_ln_epsilon) \
            if pre_layer_norm else x_
        if transpose_qkv_wb:
            nh = num_heads
            qkv = jnp.matmul(h, qkvw)  # [B, L, 3E]
            if "qb" in d:
                qkv = qkv + d["qb"]
            qkv = qkv.reshape(B, L, 3, nh, E // nh)
        else:
            # qkvw [3, nh, hd, E]: project E -> (3, nh, hd)
            nh = qkvw.shape[1]
            qkv = jnp.einsum("ble,cnhe->blcnh", h, qkvw)
            if "qb" in d:
                qkv = qkv + d["qb"].reshape(3, nh, -1)[None, None]
        q, k, v = (qkv[:, :, i] for i in range(3))  # [B, L, nh, hd]
        hd = q.shape[-1]
        scores = jnp.einsum("blnh,bmnh->bnlm", q, k) / math.sqrt(hd)
        if "mask" in d:
            scores = scores + d["mask"]
        probs = jax.nn.softmax(scores, axis=-1)
        probs = _dropout(probs, attn_dropout_rate, training, ka, mode)
        ctx = jnp.einsum("bnlm,bmnh->blnh", probs, v).reshape(B, L, -1)
        out = jnp.matmul(ctx, ow)
        if "ob" in d:
            out = out + d["ob"]
        out = _dropout(out, dropout_rate, training, kb, mode)
        if add_residual:
            out = residual + out
        if not pre_layer_norm:
            out = _ln(out, d.get("ls"), d.get("lb"), ln_epsilon)
        return out
    return apply(impl, (x, qkv_weight, linear_weight, k1, k2, *tensors),
                 op_name="fused_multi_head_attention")


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights,
                            qkv_biases, linear_weights, linear_biases,
                            ffn_ln_scales, ffn_ln_biases, ffn1_weights,
                            ffn1_biases, ffn2_weights, ffn2_biases,
                            pre_layer_norm=True, epsilon=1e-5,
                            cache_kvs=None, pre_caches=None, seq_lens=None,
                            rotary_embs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            rotary_emb_dims=0, activation="gelu",
                            training=False, mode="upscale_in_train",
                            trans_qkvw=True, ring_id=-1, name=None):
    """ref fused_transformer.py:872 — the functional decoder-stack entry.
    Delegates to the FusedMultiTransformer layer math (incubate/nn/
    fused_transformer.py), wiring the per-layer weight lists in."""
    unsupported = {"seq_lens": seq_lens, "pre_caches": pre_caches,
                   "rotary_embs": rotary_embs}
    bad = [k for k, v in unsupported.items() if v is not None]
    if bad:
        raise NotImplementedError(
            f"fused_multi_transformer: {bad} are not wired in the "
            f"functional entry (the layer path has no rotary/varlen "
            f"support); silently dropping them would produce wrong "
            f"outputs. Use models.llama_spmd for rotary decoding or "
            f"ops.pallas.decode_attention for variable seq_lens.")
    if dropout_rate:
        raise NotImplementedError(
            "fused_multi_transformer functional entry supports "
            "dropout_rate=0 only (inference schedule, matching the "
            "reference's training=False default)")
    from ..fused_transformer import FusedMultiTransformer
    num_layers = len(qkv_weights)
    embed_dim = x.shape[-1]
    nh = _infer_heads(qkv_weights[0], embed_dim, trans_qkvw)
    # cache the block structure: rebuilding (and Xavier-initializing)
    # the whole stack per call would cost O(model size) per decode step;
    # every weight is overwritten below anyway (array rebinding is free)
    cache_key = (embed_dim, nh, int(ffn1_weights[0].shape[-1]),
                 activation, pre_layer_norm, float(epsilon), num_layers)
    from ....framework import autograd
    # the lock spans weight rebinding AND the forward: the cached
    # block's parameters are shared mutable state across callers
    with _FMT_LOCK, autograd.no_grad():
        blk = _FMT_CACHE.get(cache_key)
        if blk is None:
            blk = FusedMultiTransformer(
                embed_dim, num_heads=nh,
                dim_feedforward=ffn1_weights[0].shape[-1],
                activation=activation, normalize_before=pre_layer_norm,
                epsilon=epsilon, num_layers=num_layers)
            if len(_FMT_CACHE) >= 4:  # bound the pinned stacks
                _FMT_CACHE.pop(next(iter(_FMT_CACHE)))
            _FMT_CACHE[cache_key] = blk
        for i, b in enumerate(blk.layers):
            wd = _arr(qkv_weights[i])
            # ref layouts: trans_qkvw=True -> [3, nh, hd, E];
            # False -> [E, 3, nh, hd]. The layer's Linear wants [E, 3E].
            if wd.ndim == 4:
                if trans_qkvw:
                    wd = wd.reshape(-1, embed_dim).T
                else:
                    wd = wd.reshape(embed_dim, -1)
            b.qkv.weight._data = wd
            if qkv_biases and qkv_biases[i] is not None:
                b.qkv.bias._data = _arr(qkv_biases[i]).reshape(-1)
            b.out_proj.weight._data = _arr(linear_weights[i])
            if linear_biases and linear_biases[i] is not None:
                b.out_proj.bias._data = _arr(linear_biases[i])
            b.ln.weight._data = _arr(ln_scales[i])
            b.ln.bias._data = _arr(ln_biases[i])
            b.ffn_ln.weight._data = _arr(ffn_ln_scales[i])
            b.ffn_ln.bias._data = _arr(ffn_ln_biases[i])
            b.ffn1.weight._data = _arr(ffn1_weights[i])
            if ffn1_biases and ffn1_biases[i] is not None:
                b.ffn1.bias._data = _arr(ffn1_biases[i])
            b.ffn2.weight._data = _arr(ffn2_weights[i])
            if ffn2_biases and ffn2_biases[i] is not None:
                b.ffn2.bias._data = _arr(ffn2_biases[i])
        out = blk(x, attn_mask=attn_mask, caches=cache_kvs,
                  time_step=time_step)
    return out


import threading

_FMT_CACHE: dict = {}
# weight rebinding + forward must not interleave across threads: the
# cached block's parameters are shared mutable state
_FMT_LOCK = threading.Lock()


def _arr(t):
    return t.data if isinstance(t, Tensor) else jnp.asarray(t)


def _infer_heads(qkv_w, embed_dim, trans_qkvw):
    w = qkv_w.data if isinstance(qkv_w, Tensor) else jnp.asarray(qkv_w)
    if w.ndim == 4:
        # ref layouts: [3, nh, hd, E] (trans_qkvw) or [E, 3, nh, hd]
        return int(w.shape[1] if trans_qkvw else w.shape[2])
    raise ValueError(
        "fused_multi_transformer qkv_weights must be 4-D "
        "([3, num_heads, head_dim, embed_dim] with trans_qkvw=True, or "
        "[embed_dim, 3, num_heads, head_dim]) — the head count is not "
        "inferable from a flattened 2-D weight (ref fused_transformer.py "
        "fused_multi_transformer contract)")


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type):
    """ref fused_ec_moe.py:18 (cutlass moe kernel): dense
    mixture — every expert FFN over every token, combined with the
    token's softmax gate weights. x [B,S,D], gate [B,S,E],
    bmm0 [E,D,F], bmm1 [E,F,D]."""
    if act_type not in ("gelu", "relu"):
        raise ValueError(f"act_type must be gelu/relu, got {act_type!r}")

    def impl(x_, g, w0, b0, w1, b1):
        # exact gelu: the reference (and this repo's nn.functional.gelu)
        # default to erf-gelu; jax.nn.gelu defaults to the tanh approx
        act = (lambda h: jax.nn.gelu(h, approximate=False)) \
            if act_type == "gelu" else jax.nn.relu
        probs = jax.nn.softmax(g, axis=-1)          # [B,S,E]
        h = jnp.einsum("bsd,edf->bsef", x_, w0) + b0[None, None, :, 0]
        h = act(h)
        h = jnp.einsum("bsef,efd->bsed", h, w1) + b1[None, None, :, 0]
        return jnp.einsum("bsed,bse->bsd", h, probs)
    return apply(impl, (x, gate, bmm0_weight, bmm0_bias, bmm1_weight,
                        bmm1_bias), op_name="fused_ec_moe")


def fused_gate_attention(query, key=None, query_weight=None,
                         key_weight=None, value_weight=None,
                         qkv_weight=None, gate_linear_weight=None,
                         gate_linear_bias=None, out_linear_weight=None,
                         out_linear_bias=None, nonbatched_bias=None,
                         attn_mask=None, has_gating=True, merge_qkv=True,
                         use_flash_attn=False):
    """ref fused_gate_attention.py:19 (AlphaFold-style gated attention).
    merge_qkv: qkv_weight [3, nh, hd, D]; else separate per-projection
    weights [D, nh, hd]. Returns the gated, out-projected context."""
    if key is None:
        key = query
    opt = {"nb": nonbatched_bias, "mask": attn_mask,
           "gw": gate_linear_weight, "gb": gate_linear_bias,
           "ob": out_linear_bias}
    names = [n for n, t in opt.items() if t is not None]
    tensors = [opt[n] for n in names]

    if merge_qkv:
        if qkv_weight is None:
            raise ValueError("merge_qkv=True needs qkv_weight")
        # ref contract: merge_qkv implies self-attention (shared proj)
        base = (query, query, qkv_weight, out_linear_weight)
    else:
        if query_weight is None or key_weight is None \
                or value_weight is None:
            raise ValueError("merge_qkv=False needs separate q/k/v "
                             "weights")
        base = (query, key, query_weight, key_weight, value_weight,
                out_linear_weight)

    def impl(q_in, k_in, *rest):
        n_base = len(base) - 2
        ws = rest[:n_base]
        d = dict(zip(names, rest[n_base:]))
        if merge_qkv:
            qkv = jnp.einsum("...qd,cnhd->c...qnh", q_in, ws[0])
            q, k, v = qkv[0], qkv[1], qkv[2]
            ow = ws[1]
        else:
            q = jnp.einsum("...qd,dnh->...qnh", q_in, ws[0])
            k = jnp.einsum("...kd,dnh->...knh", k_in, ws[1])
            v = jnp.einsum("...kd,dnh->...knh", k_in, ws[2])
            ow = ws[3]
        hd = q.shape[-1]
        scores = jnp.einsum("...qnh,...knh->...nqk", q, k) \
            / math.sqrt(hd)
        if "nb" in d:
            nb = d["nb"]
            # nonbatched bias [b, 1, nh, q, k] or [nh, q, k]
            while nb.ndim < scores.ndim:
                nb = nb[None]
            scores = scores + nb
        if "mask" in d:
            scores = scores + d["mask"]
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("...nqk,...knh->...qnh", probs, v)
        if has_gating:
            if "gw" not in d:
                raise ValueError("has_gating=True needs "
                                 "gate_linear_weight")
            gate = jnp.einsum("...qd,dnh->...qnh", q_in, d["gw"])
            if "gb" in d:
                gate = gate + d["gb"]
            ctx = ctx * jax.nn.sigmoid(gate)
        out = jnp.einsum("...qnh,nhd->...qd", ctx, ow)
        if "ob" in d:
            out = out + d["ob"]
        return out
    return apply(impl, (*base, *tensors),
                 op_name="fused_gate_attention")
