"""Incubate fused layers beyond the transformer stack (ref:
/root/reference/python/paddle/incubate/nn/__init__.py — FusedLinear:19,
FusedEcMoe:23, FusedDropoutAdd:24, FusedDropout:25,
FusedBiasDropoutResidualLayerNorm from layer/fused_transformer.py).
Thin Layer wrappers over incubate.nn.functional."""
from __future__ import annotations

import numpy as np

from ... import nn
from ...framework.tensor import Tensor
from . import functional as F

__all__ = ["FusedLinear", "FusedEcMoe", "FusedDropoutAdd", "FusedDropout",
           "FusedBiasDropoutResidualLayerNorm"]


class FusedLinear(nn.Layer):
    """ref layer/fused_linear.py:19 — Linear through the fused
    matmul+bias epilogue."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        from ...nn import initializer as I
        shape = [out_features, in_features] if transpose_weight \
            else [in_features, out_features]
        self.weight = self.create_parameter(
            shape, attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.fused_linear(x, self.weight, self.bias,
                              transpose_weight=self.transpose_weight)


class FusedEcMoe(nn.Layer):
    """ref layer/fused_ec_moe.py — dense expert mixture over a gate."""

    def __init__(self, hidden_size, inter_size, num_experts,
                 act_type="gelu", weight_attr=None, bias_attr=None):
        super().__init__()
        if act_type not in ("gelu", "relu"):
            raise ValueError(f"act_type must be gelu/relu, got {act_type}")
        self.act_type = act_type
        # create_parameter: honors paddle.seed reproducibility and the
        # weight_attr/bias_attr contract like every other layer
        from ...nn import initializer as I
        self.bmm0_weight = self.create_parameter(
            [num_experts, hidden_size, inter_size], attr=weight_attr,
            default_initializer=I.Normal(std=0.02))
        self.bmm0_bias = self.create_parameter(
            [num_experts, 1, inter_size], attr=bias_attr, is_bias=True)
        self.bmm1_weight = self.create_parameter(
            [num_experts, inter_size, hidden_size], attr=weight_attr,
            default_initializer=I.Normal(std=0.02))
        self.bmm1_bias = self.create_parameter(
            [num_experts, 1, hidden_size], attr=bias_attr, is_bias=True)

    def forward(self, x, gate):
        return F.fused_ec_moe(x, gate, self.bmm0_weight, self.bmm0_bias,
                              self.bmm1_weight, self.bmm1_bias,
                              self.act_type)


class FusedDropoutAdd(nn.Layer):
    """ref layer/fused_dropout_add.py — dropout(x) + y."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return F.fused_dropout_add(x, y, p=self.p,
                                   training=self.training,
                                   mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class FusedDropout(nn.Layer):
    """ref layer/fused_dropout_nd.py — dropout with optional axis (the
    nd variant broadcasts one mask along the reduced axes)."""

    def __init__(self, p=0.5, axis=None, mode="upscale_in_train",
                 name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        from ...framework.op import apply
        from ...framework import random as _random
        import jax
        import jax.numpy as jnp
        if self.p == 0.0:
            return x
        if not self.training:
            if self.mode == "downscale_in_infer":
                return x * (1.0 - self.p)
            return x
        key = _random.next_key()
        axis = self.axis
        mode = self.mode

        def impl(a, k):
            keep = 1.0 - self.p
            if axis is None:
                shape = a.shape
            else:
                axes = (axis,) if isinstance(axis, int) else tuple(axis)
                shape = tuple(s if i in axes else 1
                              for i, s in enumerate(a.shape))
            mask = jax.random.bernoulli(k, keep, shape)
            kept = a / keep if mode == "upscale_in_train" else a
            return jnp.where(mask, kept, 0.0).astype(a.dtype)
        return apply(impl, (x, key), op_name="fused_dropout")


class FusedBiasDropoutResidualLayerNorm(nn.Layer):
    """ref layer/fused_transformer.py FusedBiasDropoutResidualLayerNorm —
    layer_norm(residual + dropout(x + bias))."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        from ...framework.tensor import Parameter
        import jax.numpy as jnp
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = Parameter(jnp.zeros((embed_dim,), jnp.float32))
        self.ln_scale = Parameter(jnp.ones((embed_dim,), jnp.float32))
        self.ln_bias = Parameter(jnp.zeros((embed_dim,), jnp.float32))

    def forward(self, x, residual):
        return F.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)

    def extra_repr(self):
        return f"embed_dim={self.embed_dim}, seq_len=?, " \
               f"dropout_rate={self.dropout_rate}"
