"""paddle.incubate.autotune (ref: /root/reference/python/paddle/incubate/
autotune.py:24 set_config).

TPU mapping: the reference's exhaustive cuDNN-algorithm search and
NCHW/NHWC layout tuning are jobs XLA already performs at compile time
(Mosaic/XLA autotune convolutions and pick layouts during lowering), so
'kernel' and 'layout' tuning are accepted and recorded but have no
runtime switch to flip. 'dataloader' tuning maps to the DataLoader
prefetch thread pool: when enabled, num_workers=0/None loaders pick a
worker count from os.cpu_count().
"""
from __future__ import annotations

import json
import os
import warnings

__all__ = ["set_config"]

_CONFIG = {
    "kernel": {"enable": True, "tuning_range": [1, 10]},
    "layout": {"enable": True},
    "dataloader": {"enable": False},
}


def get_config():
    return {k: dict(v) for k, v in _CONFIG.items()}


def suggested_num_workers():
    """Dataloader tuning hook: paddle_tpu.io.DataLoader consults this when
    autotuning is enabled and num_workers is unset."""
    if not _CONFIG["dataloader"]["enable"]:
        return None
    return max(2, min(8, (os.cpu_count() or 2) // 2))


def set_config(config=None):
    """ref autotune.py:24 — accepts a dict, a json file path, or None
    (None enables everything)."""
    if config is None:
        for section in _CONFIG.values():
            section["enable"] = True
        return
    if isinstance(config, str):
        with open(config, "r") as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise ValueError(
            f"config must be None, a dict, or a json file path; got "
            f"{type(config)}")
    for key, val in config.items():
        if key not in _CONFIG:
            warnings.warn(f"autotune config key {key!r} ignored "
                          f"(supported: {sorted(_CONFIG)})")
            continue
        if not isinstance(val, dict):
            raise ValueError(f"autotune config[{key!r}] must be a dict")
        if "enable" in val:
            if not isinstance(val["enable"], bool):
                raise ValueError(f"config[{key!r}]['enable'] must be bool")
            _CONFIG[key]["enable"] = val["enable"]
        if key == "kernel" and "tuning_range" in val:
            rng = list(val["tuning_range"])
            if len(rng) != 2:
                raise ValueError("tuning_range must be [start, end]")
            _CONFIG["kernel"]["tuning_range"] = rng
