"""paddle.incubate (ref: /root/reference/python/paddle/incubate/)."""
from . import nn  # noqa: F401
from . import moe  # noqa: F401
from . import autotune  # noqa: F401
from . import optimizer  # noqa: F401
from .moe import MoELayer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401


class distributed:
    class models:
        from . import moe  # noqa: F401


def softmax_mask_fuse_upper_triangle(x):
    from ..nn.functional import softmax
    from ..ops.creation import tril, ones
    from ..framework.op import apply
    import jax.numpy as jnp

    def impl(a):
        import jax
        T = a.shape[-1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        return jax.nn.softmax(jnp.where(mask, a, -1e30), axis=-1)
    return apply(impl, (x,), op_name="softmax_mask_fuse_upper_triangle")


def segment_sum(data, segment_ids, name=None):
    from ..framework.op import apply
    import jax

    return apply(lambda d, s: jax.ops.segment_sum(d, s), (data, segment_ids),
                 op_name="segment_sum")
from . import asp  # noqa: F401,E402
