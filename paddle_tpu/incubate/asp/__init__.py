"""paddle.incubate.asp — Automatic SParsity (2:4 structured pruning).

Ref: /root/reference/python/paddle/incubate/asp/ (asp.py —
prune_model/decorate/calculate_density; utils.py — n:m mask algorithms
get_mask_1d/get_mask_2d_greedy). The reference targets Ampere sparse
tensor cores; on TPU the n:m masks are a model-compression format (the
MXU has no sparse mode), so ASP here preserves training semantics: prune
to n:m, and `decorate` re-applies the masks after every optimizer step so
sparsity survives training.
"""
from __future__ import annotations

from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor

__all__ = ["calculate_density", "decorate", "prune_model",
           "set_excluded_layers", "reset_excluded_layers",
           "add_supported_layer", "get_mask_1d", "get_mask_2d_greedy"]

_excluded: set = set()
_supported_types: List[type] = []
_masks: Dict[str, np.ndarray] = {}


def calculate_density(x) -> float:
    """Fraction of nonzeros (ref asp.py:calculate_density)."""
    a = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    return float((a != 0).sum() / max(a.size, 1))


def get_mask_1d(mat, n=2, m=4):
    """Per-row groups of m: keep the n largest |values| (ref
    utils.py:get_mask_1d)."""
    a = np.asarray(mat)
    flat = a.reshape(-1, m)
    order = np.argsort(-np.abs(flat), axis=1)[:, :n]
    mask = np.zeros_like(flat)
    np.put_along_axis(mask, order, 1.0, axis=1)
    return mask.reshape(a.shape)


def get_mask_2d_greedy(mat, n=2, m=4):
    """Greedy 2-D n:m mask (ref utils.py:get_mask_2d_greedy): mask m x m
    blocks keeping n entries per row AND per column."""
    a = np.abs(np.asarray(mat))
    h, w = a.shape
    mask = np.zeros_like(a)
    for bi in range(0, h, m):
        for bj in range(0, w, m):
            blk = a[bi:bi + m, bj:bj + m]
            sub = np.zeros_like(blk)
            order = np.argsort(-blk, axis=None)
            rows = np.zeros(blk.shape[0], int)
            cols = np.zeros(blk.shape[1], int)
            for idx in order:
                r, c = divmod(int(idx), blk.shape[1])
                if rows[r] < n and cols[c] < n:
                    sub[r, c] = 1.0
                    rows[r] += 1
                    cols[c] += 1
            mask[bi:bi + m, bj:bj + m] = sub
    return mask


def _supported(layer):
    from ... import nn
    # defaults are always supported; add_supported_layer EXTENDS them
    return isinstance(layer, tuple([nn.Linear] + _supported_types))


def set_excluded_layers(param_names, main_program=None):
    _excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def add_supported_layer(layer_type):
    _supported_types.append(layer_type)


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True,
                sharding=False):
    """Prune every supported layer's weight to n:m sparsity (ref
    asp.py:prune_model). Returns {param_name: mask}."""
    algo = {"mask_1d": get_mask_1d,
            "mask_2d_greedy": get_mask_2d_greedy}[mask_algo]
    excluded = _excluded
    out = {}
    for name, layer in _walk(model):
        if not _supported(layer):
            continue
        w = layer.weight
        if w.name in excluded or w.data.ndim != 2 \
                or w.data.shape[0] % m:
            continue
        mask = algo(np.asarray(w.numpy()).T, n=n, m=m).T
        w.set_value(Tensor(jnp.asarray(np.asarray(w.numpy()) * mask)))
        if with_mask:
            _masks[w.name] = mask
        out[w.name] = mask
    return out


def _walk(model, prefix=""):
    yield prefix, model
    for name, child in model._sub_layers.items():
        yield from _walk(child, prefix + name + ".")


class OptimizerWithSparsityGuarantee:
    """ref asp.py: wraps an optimizer so the n:m masks are re-applied
    after every step (pruned entries stay zero through training)."""

    def __init__(self, optimizer):
        self._inner_opt = optimizer

    def __getattr__(self, item):
        return getattr(self.__dict__["_inner_opt"], item)

    def step(self, *args, **kwargs):
        out = self._inner_opt.step(*args, **kwargs)  # closure-style too
        for p in self._inner_opt._parameter_list_flat():
            mask = _masks.get(p.name)
            if mask is not None:
                p._data = p.data * jnp.asarray(mask, p.data.dtype)
        return out

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad


def decorate(optimizer):
    return OptimizerWithSparsityGuarantee(optimizer)
